"""Continuous-batching serving engine over the decode fast path.

`generate()` (models/generate.py) is the fixed-batch oracle: equal-length
prompts, lockstep to max_new_tokens, EOS rows burning full decode compute,
no admission until the whole batch drains. This engine serves the same
model the way a frontend needs it served:

- **Slots.** The KV cache is ONE fixed [SLOTS, KV, L, D] buffer per layer
  (transformer.py `decode_slots`); each row is an independent request at
  its own depth, driven by per-row cursors the host owns. Finishing a
  request frees its row immediately; the next queued request moves in.
  Nothing about admission/retirement touches compiled code.
- **One compiled decode step.** Every step advances ALL slots one token —
  cursors, input tokens, and per-slot sampling params (temperature /
  top-k / top-p, the traced-per-row generalization of generate's
  `_sample`) are plain array operands. Compiled once, reused for the
  lifetime of the engine (asserted via `compile_counts` in tests).
- **Chunked prefill.** Prompts prefill in fixed windows bucketed to ≤3
  compiled shapes (scheduler.plan_chunks), one chunk per engine loop
  iteration, interleaved with decode steps — a long prompt cannot stall
  in-flight decodes, and ragged prompt lengths stop forcing per-shape
  recompiles.
- **Double-buffered decode.** The step's input tokens chain ON DEVICE:
  a decoding row's next input is the previous step's output for its slot
  (`jnp.where(use_prev, prev_tok, host_toks)`), so the host never has to
  read a token to dispatch the next step. `run()` dispatches step N+1
  BEFORE syncing step N's tokens — host-side scheduling, stream
  callbacks, EOS/length retirement, and prefill planning all hide under
  the in-flight device step. Length-finished rows free at DISPATCH time
  (exhaustion is deterministic host state, no token read needed), so
  admission runs at full occupancy; only EOS — which the host can't see
  until the sync — is one step delayed, costing that request a single
  discarded junk step, and a freed row's junk write is overwritten by
  its next occupant exactly like a free slot's (slots.py).
  `EngineConfig.async_decode=False` drains each step before the next
  dispatch — same compiled program (compile_counts is mode-blind),
  token-identical at temperature 0, the A/B baseline the serving bench
  measures against.

Parity: at temperature 0 a single request produces token-for-token the
same output as `generate()` — tests/test_serve.py pins this across the
dense and Pallas decode-kernel paths, async and sync.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import cast_params, decode_model
from ..telemetry import span
from ..telemetry import events as ev
from .scheduler import Request, RequestState, Scheduler
from .slots import SlotManager


@dataclasses.dataclass
class EngineConfig:
    """Serving knobs. `slots` is the decode batch (rows in the cache);
    `chunk_buckets` are the ≤3 compiled prefill widths — cover your
    common prompt lengths with the fewest windows (a prompt of length P
    prefills ceil((P-1)/largest) windows, ragged tail right-aligned).
    `decode_kernel` None inherits the model config. `async_decode`
    dispatches decode step N+1 before syncing step N's tokens (the
    double-buffered loop — see the module docstring); False drains every
    step before the next dispatch, through the same compiled program."""
    slots: int = 8
    chunk_buckets: Tuple[int, ...] = (32, 128, 512)
    decode_kernel: Optional[bool] = None
    rng_seed: int = 0
    async_decode: bool = True


@dataclasses.dataclass
class RequestResult:
    id: int
    tokens: List[int]                 # new tokens only (no prompt)
    logprobs: List[float]
    finish_reason: str                # "eos" | "length"
    ttft: float                       # arrival → first new token, seconds
    token_times: List[float]          # absolute (run-relative) per token


#: bounded-mode candidate pool: exact for any request with an active
#: top_k <= this (the nucleus then lives inside the kept top-k set, so
#: the tail beyond the pool carries zero probability mass by
#: construction) — and a lax.top_k of 128 is far cheaper per step than
#: the full-vocab sort the unbounded filters need
SAMPLE_POOL = 128


def sample_slots(logits, rng, temperature, top_k, top_p,
                 mode: str = "full"):
    """[B, V] logits + per-row [B] sampling params (ALL traced) →
    ([B] token, [B] logprob of the choice, from the UNfiltered
    distribution — same reporting convention as generate._sample).

    generate's `_sample` makes greedy/top_k/use_top_p STATIC — right for
    a lockstep batch sharing one config, wrong here where every slot
    carries its own params and the step must stay one compiled program.
    So: temperature==0 rows select argmax via a where; top_k becomes a
    traced threshold (k-th largest off a descending-sorted candidate
    pool); top_p==1 rows keep the whole nucleus. The filter arithmetic
    mirrors _sample, so a slot at (t, k, p) samples from the same
    distribution a generate() batch at static (t, k, p) would.

    `mode` is the one STATIC knob — three compiled variants, chosen by
    the host which knows the active rows exactly:
      "greedy"  — every active row is temperature 0: pure argmax, no
                  filter work at all (the common serving case);
      "bounded" — every sampling row has 1 <= top_k <= SAMPLE_POOL: the
                  candidate pool is lax.top_k(SAMPLE_POOL), EXACT for
                  both filters (post-top-k, all probability mass lives
                  in the pool) at a fraction of the full sort;
      "full"    — anything else (top_k disabled or huge): the pool is
                  the whole vocab, one full descending sort."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    logp = jax.nn.log_softmax(logits)
    greedy_tok = jnp.argmax(logits, axis=-1)
    if mode == "greedy":
        return greedy_tok, jnp.take_along_axis(
            logp, greedy_tok[:, None], axis=-1)[:, 0]
    W = V if mode == "full" else min(SAMPLE_POOL, V)
    scaled = logp / jnp.maximum(temperature, 1e-6)[:, None]
    # ONE top-k/sort serves both filters: the top-k threshold reads
    # straight off the pool, and because softmax is permutation-
    # equivariant, masking in the SORTED domain gives the nucleus its
    # sorted post-top-k probabilities without a second sort.
    pool = jax.lax.top_k(scaled, W)[0]            # [B, W] descending
    # top-k: mask below the k-th largest; k<=0 disables (keeps the pool)
    k = jnp.where(top_k <= 0, W, jnp.clip(top_k, 1, W))
    kth = jnp.take_along_axis(pool, (k - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    cols = jnp.arange(W)[None, :]
    pool_masked = jnp.where(cols < k[:, None], pool, -jnp.inf)
    sorted_p = jax.nn.softmax(pool_masked)
    # nucleus: smallest prefix of the sorted distribution with cumulative
    # probability >= top_p (kept set always includes the argmax). The
    # threshold is applied in the LOGIT domain — pool entries are bitwise
    # copies of `scaled` entries, so the comparison is exact, whereas a
    # probability-domain cutoff recomputes a softmax whose 1-ulp
    # normalizer drift can strand the boundary token (softmax is
    # monotone, so the kept set is identical)
    cum = jnp.cumsum(sorted_p, axis=-1)
    cutoff_idx = jnp.minimum(jnp.sum(cum < top_p[:, None], axis=-1), W - 1)
    cutoff = jnp.take_along_axis(pool_masked, cutoff_idx[:, None], axis=-1)
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    sampled = jax.random.categorical(rng, scaled)
    tok = jnp.where(temperature <= 0.0, greedy_tok, sampled)
    return tok, jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]


class ServingEngine:
    """Continuous-batching inference over a trained CausalLM.

    Usage:
        engine = ServingEngine(model, params, EngineConfig(slots=8))
        results = engine.run([Request(0, prompt_ids, max_new_tokens=64)])
        results[0].tokens       # streamed order; or pass on_token=

    The engine is single-threaded and synchronous: `run` drives the
    admit → prefill-chunk → decode-step loop to completion and returns
    per-request results. Submit-with-future-`arrival` replays a trace.
    """

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 telemetry=None, events=None):
        """telemetry: a telemetry.ServeTelemetry — live TTFT/TPOT/step
        histograms and queue/occupancy gauges (today these exist only as
        a post-hoc trace reduction in serve_benchmark); events: a
        telemetry.EventLog receiving slot_admit/slot_retire records.
        Both optional and None-cost when absent."""
        cfg = config or EngineConfig()
        mcfg = model.config
        if not mcfg.causal:
            raise ValueError("serving needs a causal LM")
        for b in cfg.chunk_buckets:
            if b > mcfg.max_len:
                raise ValueError(f"chunk bucket {b} exceeds "
                                 f"max_len={mcfg.max_len}")
        self.config = cfg
        self.model_config = mcfg
        self.dmodel = decode_model(model, cfg.decode_kernel, slots=True)
        self._base_rng = jax.random.PRNGKey(cfg.rng_seed)
        self._steps_dispatched = 0
        self.telemetry = telemetry
        self.events = events
        if telemetry is not None:
            telemetry.slots.set(cfg.slots)

        dmodel = self.dmodel
        dt = dmodel.config.dtype
        S = cfg.slots

        # params cast once, device-resident across every step (decode is
        # HBM-bound; see generate.cast_params for the barrier story)
        self._cast = jax.jit(lambda p: cast_params(p, dt))
        self.params = self._cast(params)

        def init_cache(params):
            # a zero-token step apply materializes the cache collection
            # at its serving shape; the hidden-state output is discarded
            z = jnp.zeros((S, 1), jnp.int32)
            _, vars_ = dmodel.apply({"params": params}, z, positions=z,
                                    with_head=False, mutable=["cache"])
            return vars_["cache"]

        def prefill(params, cache, slot, tokens, start):
            # one chunk for one slot: slice the row out, run the
            # backbone headless over [1, C] tokens at absolute
            # positions start..start+C, splice the row back. `slot` and
            # `start` are traced operands — one compile per bucket C.
            row = jax.tree.map(
                lambda x: lax.dynamic_slice_in_dim(x, slot, 1, 0), cache)
            positions = (start + jnp.arange(tokens.shape[0]))[None]
            _, vars_ = dmodel.apply(
                {"params": params, "cache": row}, tokens[None],
                positions=positions, with_head=False, mutable=["cache"])
            return jax.tree.map(
                lambda full, r: lax.dynamic_update_slice_in_dim(
                    full, r, slot, 0),
                cache, vars_["cache"])

        def step(params, cache, prev_tok, host_toks, use_prev, positions,
                 rng, temperature, top_k, top_p, mode):
            # ONE token for ALL slots: [S] tokens at [S] cursors. The
            # input token per row comes from the DEVICE-side chain
            # (prev_tok = last step's output, rows with use_prev) or from
            # the host (bonus token after prefill) — the chain is what
            # lets the host dispatch step N+1 without reading step N.
            from ..models.transformer import _head_matmul
            tokens = jnp.where(use_prev, prev_tok, host_toks)
            h, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions=positions[:, None], with_head=False,
                mutable=["cache"])
            logits = _head_matmul(h[:, 0], params["wte"]["embedding"])
            tok, logp = sample_slots(logits, rng, temperature, top_k,
                                     top_p, mode=mode)
            return vars_["cache"], tok, logp

        # cache buffers are donated — the engine holds the only live
        # reference, and [SLOTS, KV, L, D] per layer is the biggest
        # allocation here; donation keeps it single-buffered. (CPU has
        # no donation support and would warn per program.) prev_tok is
        # NOT donated: the pending sync still reads its buffer after the
        # next step consumed it.
        donate = (1,) if jax.default_backend() in ("tpu", "gpu") else ()
        self._init_cache = jax.jit(init_cache)
        self._prefill = jax.jit(prefill, donate_argnums=donate)
        self._step = jax.jit(step, donate_argnums=donate,
                             static_argnums=(10,))

        self.scheduler = Scheduler(cfg.chunk_buckets, mcfg.max_len)
        self.slots = SlotManager(S)
        self.cache = self._init_cache(self.params)
        self._prev_tok = jnp.zeros((S,), jnp.int32)

    # -- bookkeeping ------------------------------------------------------

    def reset(self) -> None:
        """Clear all serving state (queue, slots, cache contents) but
        keep every compiled program — what the bench calls between the
        warmup trace and the measured trace."""
        self.scheduler = Scheduler(self.config.chunk_buckets,
                                   self.model_config.max_len)
        self.slots = SlotManager(self.config.slots)
        self.cache = self._init_cache(self.params)
        self._prev_tok = jnp.zeros((self.config.slots,), jnp.int32)
        # the per-step rng folds in this counter — rewind it so a reset
        # engine replays a trace with identical draws
        self._steps_dispatched = 0

    def compile_counts(self) -> Dict[str, int]:
        """Executable-cache sizes of the engine's jitted programs —
        the no-recompile contract is `step <= 3` (at most one program
        per sample_slots mode; a pure-greedy trace compiles 1) and
        `prefill <= len(chunk_buckets)` no matter what trace ran."""
        return {
            "step": self._step._cache_size(),
            "prefill": self._prefill._cache_size(),
            "init_cache": self._init_cache._cache_size(),
            "cast": self._cast._cache_size(),
        }

    # -- the loop ---------------------------------------------------------

    def _run_prefill_chunk(self, st: RequestState) -> None:
        w, size = st.chunks.pop(0)
        p1 = len(st.req.prompt) - 1
        window = list(st.req.prompt[w:min(w + size, p1)])
        window += [0] * (size - len(window))     # right-pad short prompts
        t0 = time.perf_counter()
        with span("serve.prefill"):
            self.cache = self._prefill(
                self.params, self.cache, jnp.int32(st.slot),
                jnp.asarray(window, jnp.int32), jnp.int32(w))
        if self.telemetry is not None:
            # async dispatch: host wall time, not device time — the next
            # decode step's sync absorbs any queued prefill work
            self.telemetry.prefill_seconds.observe(time.perf_counter() - t0)
        st.pos = min(p1, w + size)

    def _dispatch_decode_step(self):
        """Build the step arrays and dispatch ONE decode step without
        waiting for its result. Returns the pending sync handle
        (device token/logprob refs + the consumers at dispatch time),
        or None when no state is eligible to consume a step. Cursors
        and dispatch counts advance HERE — they are deterministic, so
        the host's view stays exact while the tokens are in flight."""
        toks, pos, use_prev, temps, top_ks, top_ps, consumers = \
            self.slots.step_arrays()
        if not consumers:
            return None
        # pick the cheapest step variant the active rows allow (the host
        # knows the sampling params exactly; see sample_slots)
        sampling = [st.req for st in consumers if st.req.temperature > 0.0]
        if not sampling:
            mode = "greedy"
        elif all(1 <= r.top_k <= SAMPLE_POOL for r in sampling):
            mode = "bounded"
        else:
            mode = "full"
        rng = jax.random.fold_in(self._base_rng, self._steps_dispatched)
        self._steps_dispatched += 1
        step_t0 = time.perf_counter()
        with span("serve.decode_step"):
            self.cache, out_tok, out_logp = self._step(
                self.params, self.cache, self._prev_tok,
                jnp.asarray(toks), jnp.asarray(use_prev), jnp.asarray(pos),
                rng, jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), mode)
        self._prev_tok = out_tok                 # the device-side chain
        for st in consumers:
            st.pos += 1                          # the step wrote at pos
            st.dispatched += 1
            if st.dispatched >= st.req.max_new_tokens:
                # length exhaustion is known NOW, not at sync: free the
                # row so the next iteration admits into it — the final
                # token arrives at this step's sync, which reads the
                # dispatched snapshot, not the row. A new occupant's
                # prefill is dispatched after this step, so its writes
                # land on top of (never under) this request's K/V.
                self.slots.release(st)
                st.slot_released = True
        return out_tok, out_logp, consumers, step_t0

    def _sync_decode_step(self, pending, now_fn, on_token=None) \
            -> List[RequestState]:
        """Host-sync a previously dispatched step: fetch its tokens
        (the only blocking device read in the loop — host_gap_seconds
        is exactly this wait), stream them, and mark EOS/length
        retirements. A consumer already done at sync time took its
        one post-EOS junk step; its junk token is discarded here."""
        dev_tok, dev_logp, consumers, step_t0 = pending
        tel = self.telemetry
        gap_t0 = time.perf_counter()
        out_tok = np.asarray(dev_tok)            # host sync: stream point
        out_logp = np.asarray(dev_logp)
        t_sync = time.perf_counter()
        if tel is not None:
            # how long the host was BLOCKED on the device — near zero
            # when the dispatched work fully hides under host scheduling
            tel.host_gap_seconds.observe(t_sync - gap_t0)
            # dispatch → sync: the effective per-step latency (in async
            # mode this spans the loop iteration that hid under it)
            tel.decode_step_seconds.observe(t_sync - step_t0)
        now = now_fn()
        finished = []
        for st in consumers:
            if st.done:
                continue
            t = int(out_tok[st.slot])
            if tel is not None:
                if st.token_times:
                    tel.tpot_seconds.observe(now - st.token_times[-1])
                else:
                    tel.ttft_seconds.observe(now - st.req.arrival)
                tel.tokens_total.inc()
            st.next_input = t
            st.generated.append(t)
            st.logprobs.append(float(out_logp[st.slot]))
            st.token_times.append(now)
            if on_token is not None:
                on_token(st.req, t)
            if st.req.eos_id is not None and t == st.req.eos_id:
                st.finish_reason = "eos"
            elif len(st.generated) >= st.req.max_new_tokens:
                st.finish_reason = "length"
            if st.done:
                finished.append(st)
        return finished

    def run(self, requests: Sequence[Request] = (),
            on_token: Optional[Callable[[Request, int], None]] = None,
            ) -> Dict[int, RequestResult]:
        """Drive the engine until every submitted request completes.
        `on_token(request, token)` streams tokens as they are fetched.
        Returns {request.id: RequestResult}."""
        for r in requests:
            self.scheduler.submit(r)
        t0 = time.perf_counter()
        now_fn = lambda: time.perf_counter() - t0   # noqa: E731
        results: Dict[int, RequestResult] = {}
        tel = self.telemetry

        def retire(finished: List[RequestState]) -> None:
            for st in finished:
                self.scheduler.retire(st)
                if not st.slot_released:      # EOS path: freed here; the
                    self.slots.release(st)    # length path freed its row
                    st.slot_released = True   # at dispatch already
                if self.events is not None:
                    self.events.emit(
                        ev.SLOT_RETIRE, request=st.req.id, slot=st.slot,
                        finish_reason=st.finish_reason,
                        new_tokens=len(st.generated))
                if tel is not None:
                    tel.requests_total.inc()
                results[st.req.id] = RequestResult(
                    id=st.req.id, tokens=list(st.generated),
                    logprobs=list(st.logprobs),
                    finish_reason=st.finish_reason,
                    ttft=st.token_times[0] - st.req.arrival,
                    token_times=list(st.token_times))

        # the double buffer: the step whose tokens are still on the
        # device. Each iteration dispatches step N+1 FIRST, then syncs
        # step N — admission/retirement/prefill planning all happen
        # while the dispatched step runs, and a slot retired at step N
        # stays masked until step N+1's dispatch already consumed the
        # old occupancy (the one-step-lagged lifecycle).
        pending = None
        while not (self.scheduler.idle and pending is None):
            now = now_fn()
            with span("serve.schedule"):
                for st in self.scheduler.admit(self.slots.free, now):
                    self.slots.bind(st)
                    if self.events is not None:
                        self.events.emit(ev.SLOT_ADMIT, request=st.req.id,
                                         slot=st.slot,
                                         prompt_len=len(st.req.prompt))
            if tel is not None:
                tel.queue_depth.set(len(self.scheduler.queue))
                tel.slot_occupancy.set(self.slots.occupied)
            # nothing resident yet and the next arrival is in the
            # future: sleep up to it instead of spinning
            if self.slots.occupied == 0 and pending is None:
                nxt = self.scheduler.next_arrival()
                if nxt is not None and nxt > now_fn():
                    time.sleep(min(nxt - now_fn(), 0.05))
                continue
            st = self.scheduler.next_prefill()
            if st is not None:
                self._run_prefill_chunk(st)
            new_pending = (self._dispatch_decode_step()
                           if self.scheduler.decoding() else None)
            if pending is not None:
                retire(self._sync_decode_step(pending, now_fn, on_token))
                pending = None
            if self.config.async_decode:
                pending = new_pending
            elif new_pending is not None:
                # sync mode: same compiled step, fetched immediately
                retire(self._sync_decode_step(new_pending, now_fn,
                                              on_token))
        if tel is not None:
            counts = self.compile_counts()
            tel.step_compiles.set(counts["step"])
            tel.prefill_compiles.set(counts["prefill"])
            tel.queue_depth.set(0)
            tel.slot_occupancy.set(self.slots.occupied)
        return results


__all__ = ["SAMPLE_POOL", "EngineConfig", "RequestResult",
           "ServingEngine", "sample_slots"]
