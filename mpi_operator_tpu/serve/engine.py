"""Continuous-batching serving engine over the decode fast path.

`generate()` (models/generate.py) is the fixed-batch oracle: equal-length
prompts, lockstep to max_new_tokens, EOS rows burning full decode compute,
no admission until the whole batch drains. This engine serves the same
model the way a frontend needs it served:

- **Slots.** The KV cache is ONE fixed [SLOTS, KV, L, D] buffer per layer
  (transformer.py `decode_slots`); each row is an independent request at
  its own depth, driven by per-row cursors the host owns. Finishing a
  request frees its row immediately; the next queued request moves in.
  Nothing about admission/retirement touches compiled code.
- **One compiled decode step.** Every step advances ALL slots one token —
  cursors, input tokens, and per-slot sampling params (temperature /
  top-k / top-p, the traced-per-row generalization of generate's
  `_sample`) are plain array operands. Compiled once, reused for the
  lifetime of the engine (asserted via `compile_counts` in tests).
- **Chunked prefill.** Prompts prefill in fixed windows bucketed to ≤3
  compiled shapes (scheduler.plan_chunks), one chunk per engine loop
  iteration, interleaved with decode steps — a long prompt cannot stall
  in-flight decodes, and ragged prompt lengths stop forcing per-shape
  recompiles.
- **Double-buffered decode.** The step's input tokens chain ON DEVICE:
  a decoding row's next input is the previous step's output for its slot
  (`jnp.where(use_prev, prev_tok, host_toks)`), so the host never has to
  read a token to dispatch the next step. `run()` dispatches step N+1
  BEFORE syncing step N's tokens — host-side scheduling, stream
  callbacks, EOS/length retirement, and prefill planning all hide under
  the in-flight device step. Length-finished rows free at DISPATCH time
  (exhaustion is deterministic host state, no token read needed), so
  admission runs at full occupancy; only EOS — which the host can't see
  until the sync — is one step delayed, costing that request a single
  discarded junk step, and a freed row's junk write is overwritten by
  its next occupant exactly like a free slot's (slots.py).
  `EngineConfig.async_decode=False` drains each step before the next
  dispatch — same compiled program (compile_counts is mode-blind),
  token-identical at temperature 0, the A/B baseline the serving bench
  measures against.
- **Paged KV + prefix caching** (`EngineConfig.paged`). The per-layer
  cache becomes a POOL of fixed-size pages ([num_pages, KV, page_size,
  D], transformer.py decode_page_size) and each slot carries a page
  TABLE instead of a contiguous row — slot count decouples from
  max_len, so the same cache bytes serve strictly more concurrent
  requests whenever typical spans run short of the worst case.
  Admission reserves a request's whole worst-case page span up front
  (slots.PageAllocator; scheduler packing skips past a head that
  doesn't fit), so decode never allocates mid-flight. On top of pages:
  fully-prefilled PROMPT pages are published into a refcounted prefix
  cache (chained keys — exact token equality back to position 0), so a
  request sharing a system prompt pins the existing pages and starts
  prefill at the first divergent page; at worst-case TTFT the whole
  prompt is already resident and the request goes straight to decode.
  Retired requests' published pages linger in an evictable LRU until
  the free list runs dry. Paged prefill is BATCHED: one fixed-shape
  [slots, C] program per bucket advances every waiting slot whose next
  chunk shares the bucket — same ≤3 compiled widths, deeper queues
  amortize them. The contiguous path (paged=False, the default) stays
  byte-for-byte what it was — it is the token-exactness oracle the
  paged engine is pinned against in tests/test_paged_kv.py.

- **Speculative decoding** (`EngineConfig.speculative`). Decode is one
  memory-bound HBM sweep per token; speculation turns k sequential
  sweeps into ONE batched verify step. A host-side drafter proposes up
  to `draft_k` continuation tokens per row — "ngram" self-drafting
  matches the request's own prompt+output history (no second model),
  "draft" plugs in any callable (a small draft model) — and the verify
  program scores all proposals plus the bonus token in a single pass:
  the same right-aligned ragged-row shape as a chunked-prefill window,
  bucketed to ≤2 compiled widths. Greedy acceptance keeps the longest
  prefix where draft == previous position's argmax, then emits the
  model's own next token — so speculation changes WHEN tokens are
  computed, never WHICH (token-exact vs the plain engine at temperature
  0, pinned in tests/test_spec_decode.py). Rejection is a cursor
  rewind (slots.SlotManager.rewind): written-but-rejected K/V is dead
  weight the next write overwrites — never a copy — and prefix-cache
  publishing only ever covers prompt pages, so published boundaries
  advance on accepted tokens by construction. The decode pool of a
  DisaggEngine verifies the same way; drafting is host state, so the
  split gets speculation for free.

Parity: at temperature 0 a single request produces token-for-token the
same output as `generate()` — tests/test_serve.py pins this across the
dense and Pallas decode-kernel paths, async and sync.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import cast_params, decode_model
from ..telemetry import span
from ..telemetry import events as ev
from .scheduler import Request, RequestState, Scheduler
from .slots import PageAllocator, SlotManager
from .transfer import PageTransfer


@dataclasses.dataclass
class EngineConfig:
    """Serving knobs. `slots` is the decode batch (rows in the cache);
    `chunk_buckets` are the ≤3 compiled prefill widths — cover your
    common prompt lengths with the fewest windows (a prompt of length P
    prefills ceil((P-1)/largest) windows, ragged tail right-aligned).
    `decode_kernel` None inherits the model config. `async_decode`
    dispatches decode step N+1 before syncing step N's tokens (the
    double-buffered loop — see the module docstring); False drains every
    step before the next dispatch, through the same compiled program.

    `paged` switches the cache to the page-pool layout: `page_size`
    tokens per page (64 default — big enough that the page-table
    indirection amortizes, small enough that a short request doesn't
    strand half a row; must divide max_len, and the Pallas path wants a
    multiple of 32 so every cache dtype tiles), `num_pages` physical
    pages plus the reserved trash page (None sizes the pool to the
    contiguous layout's bytes: slots * max_len // page_size, + 1 —
    capacity wins then come from requests that DON'T use their worst
    case). `prefix_cache` publishes fully-prefilled prompt pages for
    cross-request sharing; False keeps pure paging. `admit_lookahead`
    bounds the packing scan past a head-of-queue that doesn't fit.

    `request_timeout` (seconds, None = off) stamps a deadline on every
    request at ADMISSION (RequestState.deadline); the run loop's sweep
    retires a past-deadline request with finish_reason "timeout" through
    the normal retire path — slot and KV pages reclaimed like any EOS,
    plus a request_timeout event. This is the engine-side half of the
    serving progress lease: one wedged request cannot pin a slot (and
    its pages) forever, so the retired-request/token frontier the
    controller watches keeps moving unless the whole engine is stuck.
    In the disaggregated facade each pool stamps its own window (prefill
    admission and decode install each start a fresh deadline).

    `speculative` (None = off) enables multi-token verify: "ngram"
    self-drafts via prompt lookup against each request's own history
    (`spec_ngram` caps the match length), "draft" uses the `drafter`
    callable handed to the engine (a small draft model, or anything
    else — correctness never depends on draft quality). `draft_k` caps
    proposed tokens per row per verify step; the verify program runs at
    ≤2 bucketed widths from {2, draft_k+1}. Greedy rows are token-exact
    vs the plain engine; sampling rows never speculate (their next
    token is a draw, not an argmax, so lookahead has nothing to verify
    against) and run plain decode in the same batch."""
    slots: int = 8
    chunk_buckets: Tuple[int, ...] = (32, 128, 512)
    decode_kernel: Optional[bool] = None
    rng_seed: int = 0
    async_decode: bool = True
    paged: bool = False
    page_size: int = 64
    num_pages: Optional[int] = None
    prefix_cache: bool = True
    admit_lookahead: int = 8
    request_timeout: Optional[float] = None
    speculative: Optional[str] = None     # None | "ngram" | "draft"
    draft_k: int = 4
    spec_ngram: int = 3


@dataclasses.dataclass
class RequestResult:
    id: int
    tokens: List[int]                 # new tokens only (no prompt)
    logprobs: List[float]
    finish_reason: str                # "eos" | "length" | "timeout"
    #                                   ("shed" at the router front door:
    #                                   rejected before any replica)
    ttft: float                       # arrival → first new token, seconds
    #                                   (-1.0 when the request timed out
    #                                   before its first token)
    token_times: List[float]          # absolute (run-relative) per token
    cached_tokens: int = 0            # prompt span served from the prefix
    #                                   cache (paged mode; 0 = cold)
    admitted_at: float = 0.0          # run-relative admission time —
    #                                   token_times[0] - admitted_at is
    #                                   TTFT with queueing excluded (the
    #                                   prefix-cache comparison the bench
    #                                   makes: a hit skips prefill, not
    #                                   the queue)


#: bounded-mode candidate pool: exact for any request with an active
#: top_k <= this (the nucleus then lives inside the kept top-k set, so
#: the tail beyond the pool carries zero probability mass by
#: construction) — and a lax.top_k of 128 is far cheaper per step than
#: the full-vocab sort the unbounded filters need
SAMPLE_POOL = 128


def sample_slots(logits, rng, temperature, top_k, top_p,
                 mode: str = "full"):
    """[B, V] logits + per-row [B] sampling params (ALL traced) →
    ([B] token, [B] logprob of the choice, from the UNfiltered
    distribution — same reporting convention as generate._sample).

    generate's `_sample` makes greedy/top_k/use_top_p STATIC — right for
    a lockstep batch sharing one config, wrong here where every slot
    carries its own params and the step must stay one compiled program.
    So: temperature==0 rows select argmax via a where; top_k becomes a
    traced threshold (k-th largest off a descending-sorted candidate
    pool); top_p==1 rows keep the whole nucleus. The filter arithmetic
    mirrors _sample, so a slot at (t, k, p) samples from the same
    distribution a generate() batch at static (t, k, p) would.

    `mode` is the one STATIC knob — three compiled variants, chosen by
    the host which knows the active rows exactly:
      "greedy"  — every active row is temperature 0: pure argmax, no
                  filter work at all (the common serving case);
      "bounded" — every sampling row has 1 <= top_k <= SAMPLE_POOL: the
                  candidate pool is lax.top_k(SAMPLE_POOL), EXACT for
                  both filters (post-top-k, all probability mass lives
                  in the pool) at a fraction of the full sort;
      "full"    — anything else (top_k disabled or huge): the pool is
                  the whole vocab, one full descending sort."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    logp = jax.nn.log_softmax(logits)
    greedy_tok = jnp.argmax(logits, axis=-1)
    if mode == "greedy":
        return greedy_tok, jnp.take_along_axis(
            logp, greedy_tok[:, None], axis=-1)[:, 0]
    W = V if mode == "full" else min(SAMPLE_POOL, V)
    scaled = logp / jnp.maximum(temperature, 1e-6)[:, None]
    # ONE top-k/sort serves both filters: the top-k threshold reads
    # straight off the pool, and because softmax is permutation-
    # equivariant, masking in the SORTED domain gives the nucleus its
    # sorted post-top-k probabilities without a second sort.
    pool = jax.lax.top_k(scaled, W)[0]            # [B, W] descending
    # top-k: mask below the k-th largest; k<=0 disables (keeps the pool)
    k = jnp.where(top_k <= 0, W, jnp.clip(top_k, 1, W))
    kth = jnp.take_along_axis(pool, (k - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    cols = jnp.arange(W)[None, :]
    pool_masked = jnp.where(cols < k[:, None], pool, -jnp.inf)
    sorted_p = jax.nn.softmax(pool_masked)
    # nucleus: smallest prefix of the sorted distribution with cumulative
    # probability >= top_p (kept set always includes the argmax). The
    # threshold is applied in the LOGIT domain — pool entries are bitwise
    # copies of `scaled` entries, so the comparison is exact, whereas a
    # probability-domain cutoff recomputes a softmax whose 1-ulp
    # normalizer drift can strand the boundary token (softmax is
    # monotone, so the kept set is identical)
    cum = jnp.cumsum(sorted_p, axis=-1)
    cutoff_idx = jnp.minimum(jnp.sum(cum < top_p[:, None], axis=-1), W - 1)
    cutoff = jnp.take_along_axis(pool_masked, cutoff_idx[:, None], axis=-1)
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    sampled = jax.random.categorical(rng, scaled)
    tok = jnp.where(temperature <= 0.0, greedy_tok, sampled)
    return tok, jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]


def propose_ngram(history: Sequence[int], k: int,
                  max_n: int = 3) -> List[int]:
    """Prompt-lookup self-drafting: propose up to `k` tokens by matching
    the longest suffix n-gram (n = max_n down to 1) of `history` against
    its most recent EARLIER occurrence and copying what followed it.
    Pure host work, no second model — repetitive continuations (code,
    lists, quoted spans, the cyclic output of a greedy decode) hit
    constantly; novel text just returns [] and the engine falls back to
    plain decode. Wrong proposals cost a verify column, never a token
    (greedy acceptance discards them)."""
    L = len(history)
    out: List[int] = []
    if k < 1 or L < 2:
        return out
    for n in range(min(max_n, L - 1), 0, -1):
        pat = list(history[L - n:])
        # scan right-to-left: recency wins (the latest occurrence is the
        # best predictor of what the model is currently repeating)
        for s in range(L - n - 1, -1, -1):
            if list(history[s:s + n]) == pat:
                out = [int(t) for t in history[s + n:s + n + k]]
                break
        if out:
            break
    return out


class ServingEngine:
    """Continuous-batching inference over a trained CausalLM.

    Usage:
        engine = ServingEngine(model, params, EngineConfig(slots=8))
        results = engine.run([Request(0, prompt_ids, max_new_tokens=64)])
        results[0].tokens       # streamed order; or pass on_token=

    The engine is single-threaded and synchronous: `run` drives the
    admit → prefill-chunk → decode-step loop to completion and returns
    per-request results. Submit-with-future-`arrival` replays a trace.
    """

    #: page-reservation mode handed to the Scheduler — the
    #: disaggregated PrefillEngine overrides this to "prompt" (its pool
    #: never holds decode tokens, so it only reserves the prompt span)
    RESERVE = "full"

    #: the hop a request's trace enters when its prompt finishes
    #: prefilling — decode here; the disaggregated PrefillEngine hands
    #: off instead (telemetry/trace.py taxonomy)
    POST_PREFILL_HOP = "serve.decode"

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 telemetry=None, events=None, drafter=None, tracer=None):
        """telemetry: a telemetry.ServeTelemetry — live TTFT/TPOT/step
        histograms and queue/occupancy gauges (today these exist only as
        a post-hoc trace reduction in serve_benchmark); events: a
        telemetry.EventLog receiving slot_admit/slot_retire records.
        Both optional and None-cost when absent. drafter: the
        speculative="draft" proposal hook — callable(history, k) -> up
        to k candidate tokens (history = prompt + generated so far);
        correctness never depends on what it returns. tracer: a
        telemetry.Tracer — per-request span trees (admission / prefill
        / decode hops on the session clock, batch-level decode/verify
        spans under a per-session root). All tracing is host-side
        bookkeeping: no device operand, no rng fold, no compiled
        program changes — greedy tokens and compile pins are bitwise
        identical with tracing on or off."""
        cfg = config or EngineConfig()
        mcfg = model.config
        if not mcfg.causal:
            raise ValueError("serving needs a causal LM")
        for b in cfg.chunk_buckets:
            if b > mcfg.max_len:
                raise ValueError(f"chunk bucket {b} exceeds "
                                 f"max_len={mcfg.max_len}")
        if cfg.speculative not in (None, "ngram", "draft"):
            raise ValueError(f"speculative={cfg.speculative!r}: expected "
                             f"None, 'ngram' or 'draft'")
        if cfg.speculative is not None and cfg.draft_k < 1:
            raise ValueError(f"draft_k={cfg.draft_k}: speculation needs "
                             f"at least one proposed token")
        if cfg.speculative == "draft" and drafter is None:
            raise ValueError("speculative='draft' needs a drafter "
                             "callable (history, k) -> tokens")
        self._drafter = drafter
        # ≤2 compiled verify widths: a narrow one for single-token
        # proposals plus the full draft_k+1 (compile_counts pins this)
        self._verify_buckets = tuple(sorted({min(2, cfg.draft_k + 1),
                                             cfg.draft_k + 1}))
        self.config = cfg
        self.model_config = mcfg
        ps = cfg.page_size
        if cfg.paged:
            if ps < 1 or mcfg.max_len % ps:
                raise ValueError(f"page_size={ps} must be >= 1 and divide "
                                 f"max_len={mcfg.max_len}")
            NP = cfg.num_pages
            if NP is None:
                # contiguous layout's byte budget, plus the trash page
                NP = cfg.slots * (mcfg.max_len // ps) + 1
            self.page_allocator: Optional[PageAllocator] = \
                PageAllocator(NP, ps)
        else:
            NP = 0
            self.page_allocator = None
        self.dmodel = decode_model(model, cfg.decode_kernel, slots=True,
                                   page_size=ps if cfg.paged else None,
                                   num_pages=NP)
        self._base_rng = jax.random.PRNGKey(cfg.rng_seed)
        self._steps_dispatched = 0
        self.telemetry = telemetry
        self.events = events
        self.tracer = tracer
        # session clock for trace hops — set while a session (or the
        # disaggregated run loop) is live; tracing is inert without it
        self._trace_now: Optional[Callable[[], float]] = None
        self._session_span = None
        if telemetry is not None:
            telemetry.slots.set(cfg.slots)
            if cfg.paged:
                telemetry.pages_total.set(self.page_allocator.usable)

        dmodel = self.dmodel
        dt = dmodel.config.dtype
        S = cfg.slots

        # params cast once, device-resident across every step (decode is
        # HBM-bound; see generate.cast_params for the barrier story)
        self._cast = jax.jit(lambda p: cast_params(p, dt))
        self.params = self._cast(params)
        # the device the engine's params are COMMITTED to, or None when
        # they are uncommitted/sharded (the colocated default — jit
        # places everything on the default device). A disaggregated
        # pool's params arrive committed to its pool device, which
        # makes every jit output committed too; the persistent
        # host-born operand (_prev_tok) must then match, or the first
        # decode step (uncommitted chain) and every later one
        # (committed chain) would key two compiled programs
        leaves = jax.tree.leaves(self.params)
        self.device = None
        if leaves and getattr(leaves[0], "committed", False):
            devs = leaves[0].devices()
            if len(devs) == 1:
                self.device = next(iter(devs))

        nblk = mcfg.max_len // ps if cfg.paged else 0
        self._nblk = nblk

        def init_cache(params):
            # a zero-token step apply materializes the cache collection
            # at its serving shape; the hidden-state output is discarded
            z = jnp.zeros((S, 1), jnp.int32)
            kw = ({"pages": jnp.zeros((S, nblk), jnp.int32)}
                  if cfg.paged else {})
            _, vars_ = dmodel.apply({"params": params}, z, positions=z,
                                    with_head=False, mutable=["cache"],
                                    **kw)
            return vars_["cache"]

        def prefill(params, cache, slot, tokens, start):
            # one chunk for one slot: slice the row out, run the
            # backbone headless over [1, C] tokens at absolute
            # positions start..start+C, splice the row back. `slot` and
            # `start` are traced operands — one compile per bucket C.
            row = jax.tree.map(
                lambda x: lax.dynamic_slice_in_dim(x, slot, 1, 0), cache)
            positions = (start + jnp.arange(tokens.shape[0]))[None]
            _, vars_ = dmodel.apply(
                {"params": params, "cache": row}, tokens[None],
                positions=positions, with_head=False, mutable=["cache"])
            return jax.tree.map(
                lambda full, r: lax.dynamic_update_slice_in_dim(
                    full, r, slot, 0),
                cache, vars_["cache"])

        def prefill_paged(params, cache, tokens, starts, pages):
            # BATCHED chunk over the page pool: [S, C] tokens, one row
            # per slot, writes routed through the page tables — the pool
            # is shared so there is no row to slice out, and every
            # waiting slot whose next chunk shares this bucket advances
            # in the same program. Non-member rows carry zero tokens at
            # their OWN cursor: their junk K/V lands exactly where their
            # next real write (chunk or decode step) overwrites it, the
            # same argument as the fixed-shape decode step's masked rows
            # (free rows' tables are all trash-page entries).
            positions = starts[:, None] + jnp.arange(tokens.shape[1])[None]
            _, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, tokens,
                positions=positions, with_head=False, mutable=["cache"],
                pages=pages)
            return vars_["cache"]

        def step(params, cache, prev_tok, host_toks, use_prev, positions,
                 rng, temperature, top_k, top_p, mode):
            # ONE token for ALL slots: [S] tokens at [S] cursors. The
            # input token per row comes from the DEVICE-side chain
            # (prev_tok = last step's output, rows with use_prev) or from
            # the host (bonus token after prefill) — the chain is what
            # lets the host dispatch step N+1 without reading step N.
            from ..models.transformer import _head_matmul
            tokens = jnp.where(use_prev, prev_tok, host_toks)
            h, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions=positions[:, None], with_head=False,
                mutable=["cache"])
            logits = _head_matmul(h[:, 0], params["wte"]["embedding"])
            tok, logp = sample_slots(logits, rng, temperature, top_k,
                                     top_p, mode=mode)
            return vars_["cache"], tok, logp

        def step_paged(params, cache, prev_tok, host_toks, use_prev,
                       positions, rng, temperature, top_k, top_p, pages,
                       mode):
            # the decode step with the per-slot page tables as one extra
            # [S, nblk] operand — table churn (admit/retire) never
            # recompiles, exactly like cursor churn
            from ..models.transformer import _head_matmul
            tokens = jnp.where(use_prev, prev_tok, host_toks)
            h, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions=positions[:, None], with_head=False,
                mutable=["cache"], pages=pages)
            logits = _head_matmul(h[:, 0], params["wte"]["embedding"])
            tok, logp = sample_slots(logits, rng, temperature, top_k,
                                     top_p, mode=mode)
            return vars_["cache"], tok, logp

        def _verify_targets(h, params, rng, temperature, top_k, top_p,
                            mode):
            # shared verify tail: [S, W] hidden states → per-position
            # target tokens + logprobs. Column 0 is the plain decode
            # step's sample (same sample_slots, so sampling rows in a
            # mixed batch still draw correctly); columns 1.. are the
            # greedy targets the drafts are checked against — argmax in
            # float32, bitwise the same reduction sample_slots runs for
            # a temperature-0 row, which is the token-exactness hinge.
            from ..models.transformer import _head_matmul
            Sv, W, E = h.shape
            logits = _head_matmul(h.reshape(Sv * W, E),
                                  params["wte"]["embedding"])
            logits = logits.reshape(Sv, W, -1)
            tok0, lp0 = sample_slots(logits[:, 0], rng, temperature,
                                     top_k, top_p, mode=mode)
            f32 = logits.astype(jnp.float32)
            logp = jax.nn.log_softmax(f32)
            greedy = jnp.argmax(f32, axis=-1)
            glp = jnp.take_along_axis(logp, greedy[..., None],
                                      axis=-1)[..., 0]
            targets = greedy.at[:, 0].set(tok0)
            return targets, glp.at[:, 0].set(lp0)

        def verify(params, cache, toks, positions, rng, temperature,
                   top_k, top_p, mode):
            # ONE batched pass over [S, W] proposed tokens at explicit
            # per-position cursors — a chunked-prefill-shaped step with
            # right-aligned ragged rows. Row layout (host-built): column
            # 0 = the row's real next input, columns 1..k = drafts,
            # padded tail positions = max_len (out-of-bounds, so their
            # K/V writes DROP — transformer.py's multi-token scatter).
            # K/V for every column is written BEFORE attention reads it,
            # and each query position attends only <= itself, so a
            # row's rejected tail never contaminates an accepted
            # position; the cursor rewind makes it invisible to every
            # later step too.
            h, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, toks,
                positions=positions, with_head=False, mutable=["cache"])
            targets, tlp = _verify_targets(h, params, rng, temperature,
                                           top_k, top_p, mode)
            return vars_["cache"], targets, tlp

        def verify_paged(params, cache, toks, positions, rng, temperature,
                         top_k, top_p, pages, mode):
            # padded tail positions hit the trash-page guard instead of
            # the scatter bound — same dropped-write semantics
            h, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, toks,
                positions=positions, with_head=False, mutable=["cache"],
                pages=pages)
            targets, tlp = _verify_targets(h, params, rng, temperature,
                                           top_k, top_p, mode)
            return vars_["cache"], targets, tlp

        # cache buffers are donated — the engine holds the only live
        # reference, and the cache ([SLOTS, KV, L, D] per layer, or the
        # page pool) is the biggest allocation here; donation keeps it
        # single-buffered. (CPU has no donation support and would warn
        # per program.) prev_tok is NOT donated: the pending sync still
        # reads its buffer after the next step consumed it.
        donate = (1,) if jax.default_backend() in ("tpu", "gpu") else ()
        self._init_cache = jax.jit(init_cache)
        if cfg.paged:
            self._prefill = jax.jit(prefill_paged, donate_argnums=donate)
            self._step = jax.jit(step_paged, donate_argnums=donate,
                                 static_argnums=(11,))
            self._verify = jax.jit(verify_paged, donate_argnums=donate,
                                   static_argnums=(9,))
        else:
            self._prefill = jax.jit(prefill, donate_argnums=donate)
            self._step = jax.jit(step, donate_argnums=donate,
                                 static_argnums=(10,))
            self._verify = jax.jit(verify, donate_argnums=donate,
                                   static_argnums=(8,))

        self.scheduler = Scheduler(cfg.chunk_buckets, mcfg.max_len,
                                   admit_lookahead=cfg.admit_lookahead,
                                   reserve=self.RESERVE)
        self.slots = SlotManager(S)
        self.cache = self._init_cache(self.params)
        self._prev_tok = self._zeros_tok(S)
        self._session = None   # open steppable session (start()/finish())
        # push-based load reporting (set_heartbeat): (hook, interval)
        self._heartbeat = None
        self._heartbeat_last: Optional[float] = None
        # high-water marks over a run(): the capacity story in one pair
        # of numbers (paged mode sustains more slots than contiguous at
        # equal cache bytes exactly when pages_in_use_peak stays under
        # the pool while occupancy_peak exceeds the contiguous slot cap)
        self.occupancy_peak = 0
        self.pages_in_use_peak = 0
        # speculation run counters (host truth the bench reads;
        # spec_stats() derives acceptance_rate / effective tokens/step)
        self.spec_proposed = 0       # draft tokens sent to verify
        self.spec_accepted = 0       # draft tokens that matched argmax
        self.spec_steps = 0          # verify steps run
        self.spec_rows = 0           # consumer rows across verify steps
        self.spec_tokens = 0         # tokens emitted by verify steps

    # -- bookkeeping ------------------------------------------------------

    def _zeros_tok(self, n: int):
        """The device-side token chain's initial value, committed to the
        engine's device (see __init__) — step N's out_tok is committed
        there too, so step 1 and step N hit the same compiled program."""
        z = jnp.zeros((n,), jnp.int32)
        return z if self.device is None else jax.device_put(z, self.device)

    def reset(self) -> None:
        """Clear all serving state (queue, slots, cache contents, page
        allocator and prefix cache) but keep every compiled program —
        what the bench calls between the warmup trace and the measured
        trace. A reset engine replays a trace with identical tokens AND
        identical compile counts."""
        self.scheduler = Scheduler(self.config.chunk_buckets,
                                   self.model_config.max_len,
                                   admit_lookahead=self.config
                                   .admit_lookahead,
                                   reserve=self.RESERVE)
        self.slots = SlotManager(self.config.slots)
        if self.page_allocator is not None:
            if os.environ.get("TPU_DEBUG_PAGES") == "1":
                # O(num_pages) invariant audit of the state the trace
                # left behind — debug builds only (the test suite sets
                # TPU_DEBUG_PAGES=1), so the bench's warmup→measure
                # reset stays O(slots)
                self.page_allocator.check()
            # rewind refcounts, free list, AND the prefix cache — cached
            # pages index into a cache whose contents init_cache is about
            # to zero, so carrying them over would serve stale K/V
            self.page_allocator.reset()
        self.cache = self._init_cache(self.params)
        self._prev_tok = self._zeros_tok(self.config.slots)
        # the per-step rng folds in this counter — rewind it so a reset
        # engine replays a trace with identical draws
        self._steps_dispatched = 0
        self._session: Optional[Dict] = None
        self._session_span = None
        self._trace_now = None
        self.occupancy_peak = 0
        self.pages_in_use_peak = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_steps = 0
        self.spec_rows = 0
        self.spec_tokens = 0

    def compile_counts(self) -> Dict[str, int]:
        """Executable-cache sizes of the engine's jitted programs —
        the no-recompile contract is `step <= 3` (at most one program
        per sample_slots mode; a pure-greedy trace compiles 1),
        `prefill <= len(chunk_buckets)`, and `verify <=
        len(_verify_buckets)` per mode (a greedy speculative trace
        compiles at most 2) no matter what trace ran."""
        return {
            "step": self._step._cache_size(),
            "prefill": self._prefill._cache_size(),
            "verify": self._verify._cache_size(),
            "init_cache": self._init_cache._cache_size(),
            "cast": self._cast._cache_size(),
        }

    def spec_stats(self) -> Dict[str, float]:
        """Speculation accounting since construction/reset().
        effective_tokens_per_step is tokens emitted PER ROW per verify
        step (so batch width cancels out): 1.0 means drafts never
        helped (each row's bonus token only — exactly plain decode in
        step count), > 1.0 is sequential HBM sweeps actually saved."""
        return {
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "verify_steps": self.spec_steps,
            "spec_tokens": self.spec_tokens,
            "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
            "effective_tokens_per_step": (self.spec_tokens / self.spec_rows
                                          if self.spec_rows else 0.0),
        }

    # -- tracing ----------------------------------------------------------

    def _trace(self, rid: int):
        """The open RequestTrace for request `rid`, or None — tracer
        absent, id sampled out, or no session clock to stamp hops with.
        One dict probe on the traced path, zero work otherwise."""
        if self.tracer is None or self._trace_now is None:
            return None
        return self.tracer.active(rid)

    def trace_abandon(self, now: float) -> None:
        """This engine is being killed/dropped mid-session (router
        failover): close its per-session trace root so already-recorded
        batch spans keep a parent — the zero-orphans invariant. The
        router abandons each in-flight REQUEST trace itself; those
        roots stay open for the replay on a surviving replica."""
        if self._session_span is not None:
            self._session_span.abandon(now)
            self._session_span = None
        self._trace_now = None

    # -- the loop ---------------------------------------------------------

    def _run_prefill_chunk(self, st: RequestState) -> None:
        w, size = st.chunks.pop(0)
        p1 = len(st.req.prompt) - 1
        window = list(st.req.prompt[w:min(w + size, p1)])
        window += [0] * (size - len(window))     # right-pad short prompts
        t0 = time.perf_counter()
        with span("serve.prefill"):
            self.cache = self._prefill(
                self.params, self.cache, jnp.int32(st.slot),
                jnp.asarray(window, jnp.int32), jnp.int32(w))
        if self.telemetry is not None:
            # async dispatch: host wall time, not device time — the next
            # decode step's sync absorbs any queued prefill work
            self.telemetry.prefill_seconds.observe(time.perf_counter() - t0)
        st.pos = min(p1, w + size)
        if not st.chunks:
            rt = self._trace(st.req.id)
            if rt is not None:
                rt.begin_hop(self.POST_PREFILL_HOP, self._trace_now())

    def _page_table_array(self) -> np.ndarray:
        """[S, nblk] physical-page tables for every slot row; free rows
        are all trash-page entries (their masked writes sink there)."""
        pt = np.zeros((self.config.slots, self._nblk), np.int32)
        for st in self.slots.states:
            if st is not None:
                pt[st.slot] = st.page_table
        return pt

    def _run_prefill_batched(self, lead: RequestState) -> None:
        """Paged prefill: advance EVERY waiting slot whose next chunk
        shares the lead's bucket in one [S, C] program — deeper queues
        amortize the same ≤3 compiled widths instead of serializing one
        chunk per loop iteration. Bound non-member rows run zero tokens
        at their own cursor (junk lands at their next write offset)."""
        size = lead.chunks[0][1]
        batch = [st for st in self.scheduler.active
                 if st.prefilling and st.chunks[0][1] == size]
        toks = np.zeros((self.config.slots, size), np.int32)
        starts = np.zeros((self.config.slots,), np.int32)
        for st in self.slots.states:
            if st is not None:
                starts[st.slot] = st.pos
        done = []
        for st in batch:
            w, _ = st.chunks.pop(0)
            p1 = len(st.req.prompt) - 1
            window = list(st.req.prompt[w:min(w + size, p1)])
            window += [0] * (size - len(window))
            toks[st.slot] = window
            starts[st.slot] = w
            done.append((st, w, p1))
        t0 = time.perf_counter()
        with span("serve.prefill"):
            self.cache = self._prefill(
                self.params, self.cache, jnp.asarray(toks),
                jnp.asarray(starts), jnp.asarray(self._page_table_array()))
        if self.telemetry is not None:
            self.telemetry.prefill_seconds.observe(time.perf_counter() - t0)
        for st, w, p1 in done:
            st.pos = max(st.pos, min(p1, w + size))
            if not st.chunks:
                rt = self._trace(st.req.id)
                if rt is not None:
                    rt.begin_hop(self.POST_PREFILL_HOP, self._trace_now())
            if self.config.prefix_cache:
                self._publish_prompt_pages(st)

    def _publish_prompt_pages(self, st: RequestState) -> None:
        """Register this request's newly COMPLETED prompt pages in the
        prefix cache (chained keys, slots.PageAllocator.publish). Only
        full pages of prompt positions [0, P-1) are ever published — the
        partial tail page also holds decode tokens and stays private.
        A False from publish() means another request registered the
        identical prefix concurrently; our copy stays private, and we
        stop publishing descendants (they would chain off a parent page
        nothing can reach through the cache)."""
        alloc = self.page_allocator
        ps = alloc.page_size
        p1 = len(st.req.prompt) - 1
        full = p1 // ps
        while (st.published_pages < full
               and (st.published_pages + 1) * ps <= st.pos):
            k = st.published_pages
            page = st.page_table[k]
            if not alloc.publish(page, st.publish_parent,
                                 st.req.prompt[k * ps:(k + 1) * ps]):
                st.published_pages = full
                break
            st.published_pages = k + 1
            st.publish_parent = page

    def _dispatch_decode_step(self):
        """Build the step arrays and dispatch ONE decode step without
        waiting for its result. Returns the pending sync handle
        (device token/logprob refs + the consumers at dispatch time),
        or None when no state is eligible to consume a step. Cursors
        and dispatch counts advance HERE — they are deterministic, so
        the host's view stays exact while the tokens are in flight."""
        toks, pos, use_prev, temps, top_ks, top_ps, consumers = \
            self.slots.step_arrays()
        if not consumers:
            return None
        # pick the cheapest step variant the active rows allow (the host
        # knows the sampling params exactly; see sample_slots)
        sampling = [st.req for st in consumers if st.req.temperature > 0.0]
        if not sampling:
            mode = "greedy"
        elif all(1 <= r.top_k <= SAMPLE_POOL for r in sampling):
            mode = "bounded"
        else:
            mode = "full"
        rng = jax.random.fold_in(self._base_rng, self._steps_dispatched)
        self._steps_dispatched += 1
        step_t0 = time.perf_counter()
        extra = ((jnp.asarray(self._page_table_array()),)
                 if self.config.paged else ())
        with span("serve.decode_step"):
            self.cache, out_tok, out_logp = self._step(
                self.params, self.cache, self._prev_tok,
                jnp.asarray(toks), jnp.asarray(use_prev), jnp.asarray(pos),
                rng, jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), *extra, mode)
        self._prev_tok = out_tok                 # the device-side chain
        for st in consumers:
            st.pos += 1                          # the step wrote at pos
            st.dispatched += 1
            st.host_next = False                 # chain re-established
            if st.dispatched >= st.req.max_new_tokens:
                # length exhaustion is known NOW, not at sync: free the
                # row so the next iteration admits into it — the final
                # token arrives at this step's sync, which reads the
                # dispatched snapshot, not the row. A new occupant's
                # prefill is dispatched after this step, so its writes
                # land on top of (never under) this request's K/V.
                self.slots.release(st)
                st.slot_released = True
        return out_tok, out_logp, consumers, step_t0

    def _plan_drafts(self) -> Dict[int, List[int]]:
        """Host-side proposal pass: {slot: draft tokens} for every row
        that can speculate THIS step. Eligibility: decoding (not
        prefilling/drained/done), temperature 0 (greedy acceptance
        verifies argmax agreement — a sampling row's next token is a
        draw, so there is nothing to verify), and ≥2 tokens of budget
        left (a 1-token budget is exactly a plain step). The caller
        must have synced any in-flight step first: drafting reads the
        request's full host-known history. Draft length is clamped so
        the verify step's worst-case writes stay inside the budget the
        scheduler reserved pages for (pos never passes P-2+max_new)."""
        cfg = self.config
        planned: Dict[int, List[int]] = {}
        vocab = self.model_config.vocab_size
        for st in self.slots.states:
            if st is None or st.prefilling or st.done:
                continue
            if st.req.temperature > 0.0:
                continue
            budget = st.req.max_new_tokens - st.dispatched - 1
            if budget < 1:
                continue
            k = min(cfg.draft_k, budget)
            hist = list(st.req.prompt) + st.generated
            if cfg.speculative == "ngram":
                raw = propose_ngram(hist, k, cfg.spec_ngram)
            else:
                raw = self._drafter(hist, k)
            draft: List[int] = []
            for t in raw[:k]:
                t = int(t)
                if not 0 <= t < vocab:
                    break          # garbage id: stop, keep the prefix
                draft.append(t)
            if draft:
                planned[st.slot] = draft
        return planned

    def _spec_step(self, planned: Dict[int, List[int]], now_fn,
                   on_token=None) -> List[RequestState]:
        """Dispatch ONE verify step over every decoding row and sync it:
        drafting rows carry [next_input, draft...] at consecutive
        cursors, plain rows ride along in column 0 (mixed batches cost
        nothing — the program is fixed-shape), padded tail positions sit
        at max_len so their writes drop. Greedy acceptance per row: keep
        the longest draft prefix matching the previous column's argmax,
        then the model's own next token rides free — every verify step
        emits ≥1 token, so speculation is never behind plain decode in
        steps. The cursor advanced over ALL written columns; the
        rejected tail is rolled back via slots.rewind (pure host
        bookkeeping — the dead K/V is masked now and overwritten next
        write). Synchronous by design: acceptance decides the NEXT
        step's inputs, so there is nothing to overlap (host_next keeps
        the device-side chain honest for the next plain step)."""
        cfg = self.config
        Sn = cfg.slots
        L = self.model_config.max_len
        max_k = max((len(d) for d in planned.values()), default=0)
        W = next(b for b in self._verify_buckets if b >= max_k + 1)
        toks = np.zeros((Sn, W), np.int32)
        posn = np.full((Sn, W), L, np.int32)   # max_len = dropped write
        temps = np.zeros((Sn,), np.float32)
        top_ks = np.zeros((Sn,), np.int32)
        top_ps = np.ones((Sn,), np.float32)
        consumers: List[RequestState] = []
        for st in self.slots.states:
            if st is None or st.prefilling or st.done:
                continue
            if st.dispatched >= st.req.max_new_tokens:
                continue                       # drained: final sync only
            toks[st.slot, 0] = st.next_input
            posn[st.slot, 0] = st.pos
            temps[st.slot] = st.req.temperature
            top_ks[st.slot] = st.req.top_k
            top_ps[st.slot] = st.req.top_p
            d = planned.get(st.slot, ())
            if d:
                toks[st.slot, 1:1 + len(d)] = d
                posn[st.slot, 1:1 + len(d)] = \
                    st.pos + 1 + np.arange(len(d))
            consumers.append(st)
        if not consumers:
            return []
        sampling = [st.req for st in consumers if st.req.temperature > 0.0]
        if not sampling:
            mode = "greedy"
        elif all(1 <= r.top_k <= SAMPLE_POOL for r in sampling):
            mode = "bounded"
        else:
            mode = "full"
        rng = jax.random.fold_in(self._base_rng, self._steps_dispatched)
        self._steps_dispatched += 1
        step_t0 = time.perf_counter()
        extra = ((jnp.asarray(self._page_table_array()),)
                 if cfg.paged else ())
        with span("serve.verify_step"):
            self.cache, dev_tg, dev_lp = self._verify(
                self.params, self.cache, jnp.asarray(toks),
                jnp.asarray(posn), rng, jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps), *extra, mode)
        tel = self.telemetry
        gap_t0 = time.perf_counter()
        tg = np.asarray(dev_tg)
        lp = np.asarray(dev_lp)
        t_sync = time.perf_counter()
        if tel is not None:
            tel.host_gap_seconds.observe(t_sync - gap_t0)
            tel.decode_step_seconds.observe(t_sync - step_t0)
        now = now_fn()
        ps = cfg.page_size if cfg.paged else None
        finished: List[RequestState] = []
        self.spec_steps += 1
        spec_p0, spec_a0 = self.spec_proposed, self.spec_accepted
        for st in consumers:
            d = planned.get(st.slot, [])
            row_t, row_l = tg[st.slot], lp[st.slot]
            accepted = 0
            while accepted < len(d) and d[accepted] == int(row_t[accepted]):
                accepted += 1
            emit = accepted + 1          # the model's own token is free
            eos = st.req.eos_id
            if eos is not None:
                for j in range(emit):    # nothing streams past an EOS
                    if int(row_t[j]) == eos:
                        emit = j + 1
                        break
            written = len(d) + 1         # columns this row really wrote
            st.pos += written
            if written > emit:
                self.slots.rewind(st.slot, written - emit, page_size=ps)
            st.dispatched += emit
            if d:
                self.spec_proposed += len(d)
                self.spec_accepted += accepted
                if tel is not None:
                    tel.spec_proposed_total.inc(len(d))
                    tel.spec_accepted_total.inc(accepted)
                    tel.spec_acceptance_ratio.observe(accepted / len(d))
            self.spec_rows += 1
            self.spec_tokens += emit
            if tel is not None:
                tel.spec_tokens_per_step.observe(emit)
            for j in range(emit):
                t = int(row_t[j])
                if tel is not None:
                    if st.token_times:
                        tel.tpot_seconds.observe(now - st.token_times[-1])
                    else:
                        tel.ttft_seconds.observe(now - st.req.arrival)
                    tel.tokens_total.inc()
                st.generated.append(t)
                st.logprobs.append(float(row_l[j]))
                st.token_times.append(now)
                if on_token is not None:
                    on_token(st.req, t)
            st.next_input = int(row_t[emit - 1])
            st.host_next = True          # device chain token is stale
            if (eos is not None and st.generated
                    and st.generated[-1] == eos):
                st.finish_reason = "eos"
            elif len(st.generated) >= st.req.max_new_tokens:
                st.finish_reason = "length"
            if st.done:
                finished.append(st)
        if self._session_span is not None:
            # batch-level verify span under the session root, stamped
            # at sync on the session clock; acceptance counts ride as
            # attributes (the per-request roots cannot own a span that
            # served the whole batch)
            dur = t_sync - step_t0
            self._session_span.child(
                "serve.verify_step", now - dur, dur,
                batch=len(consumers),
                proposed=self.spec_proposed - spec_p0,
                accepted=self.spec_accepted - spec_a0)
        return finished

    def _sync_decode_step(self, pending, now_fn, on_token=None) \
            -> List[RequestState]:
        """Host-sync a previously dispatched step: fetch its tokens
        (the only blocking device read in the loop — host_gap_seconds
        is exactly this wait), stream them, and mark EOS/length
        retirements. A consumer already done at sync time took its
        one post-EOS junk step; its junk token is discarded here."""
        dev_tok, dev_logp, consumers, step_t0 = pending
        tel = self.telemetry
        gap_t0 = time.perf_counter()
        out_tok = np.asarray(dev_tok)            # host sync: stream point
        out_logp = np.asarray(dev_logp)
        t_sync = time.perf_counter()
        if tel is not None:
            # how long the host was BLOCKED on the device — near zero
            # when the dispatched work fully hides under host scheduling
            tel.host_gap_seconds.observe(t_sync - gap_t0)
            # dispatch → sync: the effective per-step latency (in async
            # mode this spans the loop iteration that hid under it)
            tel.decode_step_seconds.observe(t_sync - step_t0)
        now = now_fn()
        if self._session_span is not None:
            dur = t_sync - step_t0
            self._session_span.child("serve.decode_step", now - dur, dur,
                                     batch=len(consumers))
        finished = []
        for st in consumers:
            if st.done:
                continue
            t = int(out_tok[st.slot])
            if tel is not None:
                if st.token_times:
                    tel.tpot_seconds.observe(now - st.token_times[-1])
                else:
                    tel.ttft_seconds.observe(now - st.req.arrival)
                tel.tokens_total.inc()
            st.next_input = t
            st.generated.append(t)
            st.logprobs.append(float(out_logp[st.slot]))
            st.token_times.append(now)
            if on_token is not None:
                on_token(st.req, t)
            if st.req.eos_id is not None and t == st.req.eos_id:
                st.finish_reason = "eos"
            elif len(st.generated) >= st.req.max_new_tokens:
                st.finish_reason = "length"
            if st.done:
                finished.append(st)
        return finished

    def _note_admissions(self, admitted: List[RequestState]) -> None:
        """Bind newly admitted states to their slot rows and record the
        admission (slot_admit event, prefix-cache page counters). Shared
        by run() and the disaggregated facade's prefill side."""
        alloc = self.page_allocator
        tel = self.telemetry
        timeout = self.config.request_timeout
        for st in admitted:
            if timeout is not None:
                st.deadline = st.admitted_at + timeout
            self.slots.bind(st)
            rt = self._trace(st.req.id)
            if rt is not None:
                # admission hop ends where the scheduler stamped it; a
                # fully-cached prompt has no chunks and skips straight
                # to the post-prefill hop
                rt.begin_hop("serve.prefill" if st.chunks
                             else self.POST_PREFILL_HOP,
                             st.admitted_at,
                             cached_tokens=st.cached_tokens)
            if self.events is not None:
                self.events.emit(ev.SLOT_ADMIT, request=st.req.id,
                                 slot=st.slot,
                                 prompt_len=len(st.req.prompt),
                                 cached_tokens=st.cached_tokens)
            if tel is not None and alloc is not None:
                ps_ = alloc.page_size
                full = (len(st.req.prompt) - 1) // ps_
                hit = st.cached_tokens // ps_
                tel.prefix_hit_pages.inc(hit)
                tel.prefix_miss_pages.inc(full - hit)

    def _retire_state(self, st: RequestState,
                      results: Dict[int, "RequestResult"]) -> None:
        """Retire ONE finished state: scheduler/slot/page bookkeeping,
        the slot_retire event, and the RequestResult record. Shared by
        run() and the disaggregated facade's decode side."""
        alloc = self.page_allocator
        self.scheduler.retire(st)
        if not st.slot_released:          # EOS path: freed here; the
            self.slots.release(st)        # length path freed its row
            st.slot_released = True       # at dispatch already
        if alloc is not None:
            # drop every reference this request held — pinned shared
            # prefix pages and private pages alike; its PUBLISHED pages
            # park in the evictable LRU where future lookups still find
            # them
            for p in st.owned_pages:
                alloc.release(p)
            st.owned_pages = []
        if self.events is not None:
            self.events.emit(
                ev.SLOT_RETIRE, request=st.req.id, slot=st.slot,
                finish_reason=st.finish_reason,
                new_tokens=len(st.generated))
        if self.telemetry is not None:
            self.telemetry.requests_total.inc()
        rt = self._trace(st.req.id)
        if rt is not None:
            rt.attrs.update(finish_reason=st.finish_reason,
                            new_tokens=len(st.generated),
                            cached_tokens=st.cached_tokens)
            rt.finish("timeout" if st.finish_reason == "timeout"
                      else "ok", self._trace_now())
        results[st.req.id] = RequestResult(
            id=st.req.id, tokens=list(st.generated),
            logprobs=list(st.logprobs),
            finish_reason=st.finish_reason,
            # a request timed out before its first token has no TTFT
            ttft=(st.token_times[0] - st.req.arrival
                  if st.token_times else -1.0),
            token_times=list(st.token_times),
            cached_tokens=st.cached_tokens,
            admitted_at=st.admitted_at)

    def _sweep_timeouts(self, now: float,
                        results: Dict[int, "RequestResult"]) -> None:
        """Retire every resident state past its deadline with
        finish_reason "timeout" — through _retire_state, so the slot and
        pages come back exactly like an EOS retirement. Marking the state
        done here also makes any in-flight decode step's sync skip it
        (same discipline as a length retirement): the junk token the
        dispatched step produces for its old slot is discarded, and the
        row's next occupant overwrites its K/V."""
        if self.config.request_timeout is None:
            return
        for st in list(self.scheduler.active):
            if st.done or st.deadline is None or now < st.deadline:
                continue
            st.finish_reason = "timeout"
            st.chunks = []        # a mid-prefill request stops consuming
            #                       windows; nothing re-plans a done state
            if self.events is not None:
                # trace= pairs the incident with its span tree — the
                # postmortem "slow traces:" exemplar link
                self.events.emit(ev.REQUEST_TIMEOUT, request=st.req.id,
                                 slot=st.slot,
                                 new_tokens=len(st.generated),
                                 deadline_seconds=self.config
                                 .request_timeout,
                                 trace=st.req.id)
            self._retire_state(st, results)

    # -- steppable session (the router drives replicas through these) -----

    def start(self, on_token: Optional[Callable[[Request, int], None]]
              = None, now_fn: Optional[Callable[[], float]] = None) -> None:
        """Open a streaming session: submit() feeds requests in, tick()
        advances the loop one iteration, finish() closes it and returns
        the results. `now_fn` is the session clock (seconds, arbitrary
        epoch) — the serving router passes ONE shared clock to every
        replica so arrivals and TTFTs are comparable fleet-wide; None
        starts a private clock at 0."""
        if self._session is not None:
            raise RuntimeError("session already open (call finish())")
        if now_fn is None:
            t0 = time.perf_counter()
            now_fn = lambda: time.perf_counter() - t0   # noqa: E731
        self._session = {"results": {}, "pending": None,
                         "on_token": on_token, "now_fn": now_fn}
        self._trace_now = now_fn
        if self.tracer is not None:
            self._session_span = self.tracer.begin_session(
                now_fn(), slots=self.config.slots)

    def set_heartbeat(self, hook: Callable[..., None],
                      interval: float) -> None:
        """Install a push-based load reporter: at most once per
        `interval` seconds of session time, tick() calls
        ``hook(now=..., queue_depth=..., free_slots=..., free_pages=...)``
        with this replica's instantaneous load. The router wires the
        hook into RouterTelemetry so dispatch can score replicas off
        published reports instead of probing engine state in-process —
        the shape a cross-host router actually has to live with."""
        if interval <= 0:
            raise ValueError(f"heartbeat interval must be > 0, got "
                             f"{interval}")
        self._heartbeat = (hook, float(interval))
        self._heartbeat_last = None

    def _maybe_heartbeat(self, now: float) -> None:
        """Rate-limited publish (see set_heartbeat); no-op when no
        reporter is installed."""
        if self._heartbeat is None:
            return
        hook, interval = self._heartbeat
        last = self._heartbeat_last
        if last is not None and now - last < interval:
            return
        self._heartbeat_last = now
        alloc = self.page_allocator
        hook(now=now,
             queue_depth=len(self.scheduler.queue),
             free_slots=len(self.slots.free),
             free_pages=alloc.available if alloc is not None else 0)

    def submit(self, req: Request) -> None:
        """Queue one request into the open session (front-door entry
        point). Raises ValueError for spans the engine can NEVER
        satisfy — the same up-front rejection run() applies."""
        if self._session is None:
            raise RuntimeError("submit() outside a session (call start())")
        alloc = self.page_allocator
        if alloc is not None:
            need = Scheduler.pages_needed(req, alloc.page_size)
            if need > alloc.usable:
                # a request the pool can NEVER satisfy would sit at
                # the head of the queue forever (admission livelock);
                # reject it up front like an over-max_len prompt
                raise ValueError(
                    f"request {req.id}: worst-case span needs {need} KV "
                    f"pages but the pool has {alloc.usable} usable "
                    f"(raise num_pages or lower max_new_tokens)")
        self.scheduler.submit(req)
        if self.tracer is not None:
            # open (or, behind a router / on a failover replay, JOIN)
            # this request's trace — the router's queue-wait hop closes
            # where admission begins
            rt = self.tracer.begin_request(
                req.id, t0=req.arrival, prompt_len=len(req.prompt),
                max_new_tokens=req.max_new_tokens)
            if rt is not None:
                rt.begin_hop("serve.admission",
                             max(req.arrival, self._session["now_fn"]()))

    @property
    def active(self) -> bool:
        """True while the open session still has work in flight."""
        return (self._session is not None
                and not (self.scheduler.idle
                         and self._session["pending"] is None))

    def tick(self) -> bool:
        """One iteration of the admit → prefill → decode loop. Returns
        False when the engine had nothing to do this instant (idle, or
        every queued arrival is in the future) WITHOUT sleeping — the
        caller owns the wait policy (run() naps; the router services
        other replicas)."""
        sess = self._session
        if sess is None:
            raise RuntimeError("tick() outside a session (call start())")
        if not self.active:
            return False
        alloc = self.page_allocator
        tel = self.telemetry
        now_fn = sess["now_fn"]
        on_token = sess["on_token"]
        results = sess["results"]

        def retire(finished: List[RequestState]) -> None:
            for st in finished:
                self._retire_state(st, results)

        now = now_fn()
        # deadline sweep FIRST: a wedged head-of-queue request frees
        # its slot before this iteration's admission fills the rows
        self._sweep_timeouts(now, results)
        with span("serve.schedule"):
            self._note_admissions(
                self.scheduler.admit(self.slots.free, now,
                                     allocator=alloc))
        self.occupancy_peak = max(self.occupancy_peak,
                                  self.slots.occupied)
        if alloc is not None:
            self.pages_in_use_peak = max(self.pages_in_use_peak,
                                         alloc.in_use)
        if tel is not None:
            tel.queue_depth.set(len(self.scheduler.queue))
            tel.slot_occupancy.set(self.slots.occupied)
            if alloc is not None:
                tel.pages_in_use.set(alloc.in_use)
                tel.pages_cached.set(alloc.cached_pages)
        # heartbeat AFTER admission: the published queue depth is what
        # is still waiting behind the slots, not this instant's intake
        self._maybe_heartbeat(now)
        # nothing resident yet and the next arrival is in the future:
        # nothing to advance — report it instead of spinning
        pending = sess["pending"]
        if self.slots.occupied == 0 and pending is None:
            nxt = self.scheduler.next_arrival()
            if nxt is not None and nxt > now_fn():
                return False
        st = self.scheduler.next_prefill()
        if st is not None:
            if self.config.paged:
                self._run_prefill_batched(st)
            else:
                self._run_prefill_chunk(st)
        planned = {}
        if (self.config.speculative is not None
                and self.scheduler.decoding()):
            # drafting reads host-known history, and acceptance
            # decides the next step's inputs — drain the in-flight
            # step first (speculative steps are synchronous; the
            # multi-token payoff replaces the dispatch overlap)
            if pending is not None:
                retire(self._sync_decode_step(pending, now_fn,
                                              on_token))
                pending = None
            planned = self._plan_drafts()
        if planned:
            retire(self._spec_step(planned, now_fn, on_token))
            new_pending = None
        else:
            # no row drafted this step (novel text, sampling rows,
            # exhausted budgets): plain decode, async overlap intact
            new_pending = (self._dispatch_decode_step()
                           if self.scheduler.decoding() else None)
        if pending is not None:
            retire(self._sync_decode_step(pending, now_fn, on_token))
            pending = None
        if self.config.async_decode:
            pending = new_pending
        elif new_pending is not None:
            # sync mode: same compiled step, fetched immediately
            retire(self._sync_decode_step(new_pending, now_fn,
                                          on_token))
        sess["pending"] = pending
        return True

    def session_results(self) -> Dict[int, RequestResult]:
        """The open session's retired results so far (live view) — the
        router fans these in after each tick()."""
        if self._session is None:
            raise RuntimeError("session_results() outside a session")
        return self._session["results"]

    def finish(self) -> Dict[int, RequestResult]:
        """Close the session (final telemetry flush) and return
        {request.id: RequestResult} for everything retired in it."""
        sess = self._session
        if sess is None:
            raise RuntimeError("finish() outside a session")
        tel = self.telemetry
        if tel is not None:
            counts = self.compile_counts()
            tel.step_compiles.set(counts["step"])
            tel.prefill_compiles.set(counts["prefill"])
            tel.queue_depth.set(len(self.scheduler.queue))
            tel.slot_occupancy.set(self.slots.occupied)
        if self._session_span is not None:
            self._session_span.end(sess["now_fn"]())
            self._session_span = None
        self._trace_now = None
        self._session = None
        return sess["results"]

    def run(self, requests: Sequence[Request] = (),
            on_token: Optional[Callable[[Request, int], None]] = None,
            ) -> Dict[int, RequestResult]:
        """Drive the engine until every submitted request completes.
        `on_token(request, token)` streams tokens as they are fetched.
        Returns {request.id: RequestResult}.

        The body is exactly start → submit* → tick-until-idle → finish;
        the double buffer lives inside tick(): each iteration dispatches
        step N+1 FIRST, then syncs step N — admission/retirement/prefill
        planning all happen while the dispatched step runs, and a slot
        retired at step N stays masked until step N+1's dispatch already
        consumed the old occupancy (the one-step-lagged lifecycle)."""
        self.start(on_token)
        try:
            for r in requests:
                self.submit(r)
            while self.active:
                if not self.tick():
                    # queue non-empty but every arrival is in the
                    # future: sleep up to the next one instead of
                    # spinning
                    nxt = self.scheduler.next_arrival()
                    now = self._session["now_fn"]()
                    if nxt is not None and nxt > now:
                        time.sleep(min(nxt - now, 0.05))
        except Exception:
            if self._session is not None:
                self.trace_abandon(self._session["now_fn"]())
            self._session = None
            raise
        return self.finish()


class PrefillEngine(ServingEngine):
    """The prefill half of a disaggregated pair (DisaggEngine drives
    it): admits requests and runs batched chunked prefill, but never
    dispatches a decode step — so its compiled-program footprint is
    prefill-only (`prefill <= len(chunk_buckets)`, `step == 0`; the
    per-pool HBM program-cache win of the split). Page reservations
    cover the PROMPT span only (Scheduler reserve="prompt"): the decode
    span lives in the decode pool, so this pool's pages all do prefill
    work — at equal bytes it keeps strictly more prompts in flight than
    a colocated engine could."""

    RESERVE = "prompt"

    #: a prefilled prompt's next hop in this pool is the page handoff,
    #: not decode — trace hop names follow the disaggregated flow
    POST_PREFILL_HOP = "serve.kv_handoff"

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 telemetry=None, events=None, tracer=None):
        cfg = config or EngineConfig()
        if not cfg.paged:
            raise ValueError("disaggregated serving requires paged=True "
                             "(the handoff unit is a page list)")
        # the prefill pool never decodes, so it never drafts either —
        # strip the speculation knob rather than make it validate a
        # drafter it will not call
        if cfg.speculative is not None:
            cfg = dataclasses.replace(cfg, speculative=None)
        super().__init__(model, params, cfg, telemetry=telemetry,
                         events=events, tracer=tracer)

    def take_prefilled(self) -> List[RequestState]:
        """Pop every state whose prefill just completed: it leaves the
        scheduler and frees its slot row (the next prompt starts
        immediately) but KEEPS its page references — the handoff copy
        still reads those pages; DisaggEngine releases them once the
        copy is dispatched. Nothing can write the kept pages meanwhile:
        writes route through slot page tables, and the freed row's
        table is rebuilt from its next occupant's pages."""
        done = [st for st in self.scheduler.active if not st.prefilling]
        for st in done:
            self.scheduler.retire(st)
            self.slots.release(st)
            st.slot_released = True
        return done


class DecodeEngine(ServingEngine):
    """The decode half: requests arrive pre-filled via
    `install_handoff` and flow through the shared decode step; this
    pool never compiles a prefill program (`step <= 3`, `prefill ==
    0`). Its PageAllocator runs the same prefix cache as a colocated
    engine — a handed-off prompt whose prefix is already resident here
    needs NO bytes moved for those pages (DisaggEngine transfers only
    the misses)."""

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 telemetry=None, events=None, drafter=None, tracer=None):
        cfg = config or EngineConfig()
        if not cfg.paged:
            raise ValueError("disaggregated serving requires paged=True "
                             "(the handoff unit is a page list)")
        super().__init__(model, params, cfg, telemetry=telemetry,
                         events=events, drafter=drafter, tracer=tracer)

    def install_handoff(self, req: Request, reserved, now: float,
                        cached_tokens: int = 0,
                        ) -> Tuple[RequestState, List[Tuple[int, int]]]:
        """Bind a prefill-complete request into a slot of THIS pool.
        `reserved` is this pool's full-span page reservation (chain,
        private, table) from Scheduler._reserve_pages — the chain pages
        are decode-side prefix-cache hits whose KV is already resident.
        Returns (state, fill) where fill lists (prompt-page index,
        physical page here) for every page whose contents must still be
        copied in from the prefill pool; full prompt pages among them
        are published into this pool's prefix cache immediately, so the
        NEXT handoff sharing the prefix skips their copy too.

        The caller must have checked `self.slots.free` first."""
        chain, private, table = reserved
        alloc = self.page_allocator
        ps = alloc.page_size
        p1 = len(req.prompt) - 1
        full = p1 // ps                   # complete PROMPT pages
        # pages prefill actually wrote: positions [0, p1)
        written = 0 if p1 < 1 else (p1 - 1) // ps + 1
        slot = self.slots.free.pop(0)
        st = RequestState(req=req, slot=slot, pos=p1, chunks=[],
                          next_input=int(req.prompt[-1]), admitted_at=now)
        if self.config.request_timeout is not None:
            # the decode pool stamps its OWN window — the prefill-side
            # deadline was consumed getting the request this far
            st.deadline = now + self.config.request_timeout
        st.page_table = table
        st.owned_pages = chain + private
        st.cached_tokens = cached_tokens
        st.published_pages = full         # published below or inherited —
        st.publish_parent = -1            # the engine never re-publishes
        self.slots.bind(st)
        self.scheduler.active.append(st)
        fill = [(k, table[k]) for k in range(len(chain), written)]
        if self.config.prefix_cache:
            parent = chain[-1] if chain else -1
            for k in range(len(chain), full):
                if not alloc.publish(table[k], parent,
                                     req.prompt[k * ps:(k + 1) * ps]):
                    break
                parent = table[k]
        if self.telemetry is not None:
            # decode-side hit/miss = handoff pages saved/moved — the
            # same instruments a colocated engine feeds at admission
            self.telemetry.prefix_hit_pages.inc(len(chain))
            self.telemetry.prefix_miss_pages.inc(written - len(chain))
        if self.events is not None:
            self.events.emit(ev.SLOT_ADMIT, request=req.id, slot=slot,
                             prompt_len=len(req.prompt),
                             cached_tokens=len(chain) * ps)
        return st, fill


class DisaggEngine:
    """Disaggregated prefill/decode serving: a PrefillEngine and a
    DecodeEngine on SEPARATE devices, bridged by paged-KV handoff
    (serve/transfer.py). One long prompt saturates the prefill pool
    while in-flight decodes keep stepping on the decode pool — the
    TTFT/TPOT interference a colocated engine can't avoid is gone by
    construction, and each pool compiles only its own programs.

    Flow per request: admit → prefill pool (prompt-span-only page
    reservation, batched chunked prefill) → handoff (decode-side
    full-span reservation; device-to-device copy of exactly the prompt
    pages the decode pool's prefix cache does NOT already hold) →
    decode pool (shared double-buffered step) → retire (pages park in
    the decode pool's prefix cache). Admission is backpressured when
    the decode pool's free pages can't absorb the in-flight handoffs
    (Scheduler.gate), so a handoff can stall only on slots, never
    deadlock on pages.

    Token parity: at temperature 0 the facade is token-for-token
    identical to a colocated paged ServingEngine over the same trace
    (tests/test_disagg.py pins it, dense and Pallas-kernel, int8 KV
    included): per-slot prefill/step rows are computed independently,
    so batching composition doesn't change a row's KV; the handoff
    copies those exact bytes (int8 payloads move with their scale
    planes); and the decode step is the same compiled program. At
    temperature > 0 sampling matches distributionally but not bitwise —
    the per-step rng folds in each pool's own dispatch counter.

    On CPU smoke the two "pools" are two of the virtual host devices
    (same program structure, host-memory device_put); on real hardware
    point `devices=` at chips in different pools and the copy rides
    ICI/DCN. The controller stands up the two pools as distinct worker
    groups (TPU_SERVE_ROLE) — see controller/controller.py."""

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, prefill_config: Optional[EngineConfig] = None,
                 registry=None, events=None, devices=None, drafter=None,
                 tracer=None):
        cfg = config or EngineConfig(paged=True)
        pcfg = prefill_config or cfg
        if not cfg.paged or not pcfg.paged:
            raise ValueError("disaggregated serving requires paged=True")
        if pcfg.page_size != cfg.page_size:
            raise ValueError(
                f"prefill/decode page_size disagree "
                f"({pcfg.page_size} vs {cfg.page_size}) — the handoff "
                f"moves pages verbatim")
        if devices is None:
            local = jax.local_devices()
            devices = ((local[0], local[1]) if len(local) > 1
                       else (local[0], local[0]))
        self.devices = tuple(devices)
        pre_tel = dec_tel = None
        if registry is not None:
            from ..telemetry.worker import ServeTelemetry
            pre_tel = ServeTelemetry(registry, labels={"pool": "prefill"})
            dec_tel = ServeTelemetry(registry, labels={"pool": "decode"})
        self.events = events
        pre_ev = events.bind(pool="prefill") if events is not None else None
        dec_ev = events.bind(pool="decode") if events is not None else None
        # device_put COMMITS each pool's params to its device; every jit
        # downstream (cast, init_cache, prefill/step, transfer
        # gather/scatter) follows its committed operands, so the two
        # engines' programs land on the two devices with no mesh code
        self.tracer = tracer
        self.prefill = PrefillEngine(
            model, jax.device_put(params, self.devices[0]), pcfg,
            telemetry=pre_tel, events=pre_ev, tracer=tracer)
        self.decode = DecodeEngine(
            model, jax.device_put(params, self.devices[1]), cfg,
            telemetry=dec_tel, events=dec_ev, drafter=drafter,
            tracer=tracer)
        self.transfer = PageTransfer(self.prefill.page_allocator.num_pages,
                                     self.decode.page_allocator.num_pages)
        self.config = cfg
        self._handoff_q: List[RequestState] = []
        # handoff trace for the bench: (seconds, pages moved, pages
        # skipped via the decode-side prefix cache) per handoff
        self.handoff_log: List[Tuple[float, int, int]] = []
        self._install_gate()

    def _install_gate(self) -> None:
        """Decode-capacity backpressure on PREFILL admission: a request
        enters the prefill pool only while the decode pool's available
        pages cover every in-flight request's worst-case span plus this
        one — so prefill can't fill with prompts the decode pool cannot
        absorb, and handoffs drain as decode capacity frees (the
        scheduler's lookahead still packs smaller requests past a gated
        head)."""
        ps = self.config.page_size
        dec_alloc = self.decode.page_allocator

        def gate(req: Request) -> bool:
            inflight = sum(Scheduler.pages_needed(s.req, ps)
                           for s in self.prefill.scheduler.active)
            inflight += sum(Scheduler.pages_needed(s.req, ps)
                            for s in self._handoff_q)
            return (dec_alloc.available
                    >= inflight + Scheduler.pages_needed(req, ps))

        self.prefill.scheduler.gate = gate

    def reset(self) -> None:
        """Reset both pools (queues, caches, allocators) keeping every
        compiled program — including the transfer's gather/scatter,
        which live on this facade, so a warmed DisaggEngine replays a
        trace with identical tokens and identical compile counts."""
        self.prefill.reset()
        self.decode.reset()
        self._handoff_q = []
        self.handoff_log = []
        self.transfer.pages_moved = 0
        self._install_gate()              # reset() rebuilt the scheduler

    def compile_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-pool program-cache sizes plus the transfer pair. The
        disaggregation pins: prefill pool `step == 0`, decode pool
        `prefill == 0` — neither pool ever compiles the other's
        programs."""
        return {"prefill_pool": self.prefill.compile_counts(),
                "decode_pool": self.decode.compile_counts(),
                "transfer": self.transfer.compile_counts()}

    def _handoff(self, st: RequestState, reserved, now: float) -> None:
        """Move one prefill-complete request into the decode pool:
        install it there (decode-side reservation already made), copy
        exactly the non-cached written prompt pages device-to-device,
        then drop the prefill pool's page references — its published
        prompt pages park in the prefill prefix cache (a repeat prompt
        skips the recompute), the private tail returns to its free
        list."""
        pre, dec = self.prefill, self.decode
        t0 = time.perf_counter()
        chain_hits = len(reserved[0])
        new_st, fill = dec.install_handoff(st.req, reserved, now,
                                           cached_tokens=st.cached_tokens)
        src_ids = [st.page_table[k] for k, _ in fill]
        dst_ids = [p for _, p in fill]
        with span("serve.kv_handoff"):
            dec.cache, moved = self.transfer.move(pre.cache, dec.cache,
                                                  src_ids, dst_ids)
        # the gather captured the source buffers at dispatch — the page
        # REFERENCES can drop now (see PageTransfer.move)
        for p in st.owned_pages:
            pre.page_allocator.release(p)
        st.owned_pages = []
        dt = time.perf_counter() - t0     # host wall, async-dispatch
        self.handoff_log.append((dt, moved, chain_hits))
        rt = dec._trace(st.req.id)
        if rt is not None:
            # page counts land on the kv_handoff hop (which spans
            # prefill-done → installed here, queue wait included), then
            # the decode hop opens
            rt.hop_attrs(pages=moved, cached_pages=chain_hits,
                         move_seconds=round(dt, 6))
            rt.begin_hop("serve.decode", now)
        if dec.telemetry is not None:
            dec.telemetry.kv_handoff_seconds.observe(dt)
            dec.telemetry.kv_handoff_pages.inc(moved)
        if self.events is not None:
            self.events.emit(ev.KV_HANDOFF, request=st.req.id,
                             pages=moved, cached_pages=chain_hits,
                             seconds=dt)

    def _sweep_handoff_timeouts(self, now: float,
                                results: Dict[int, RequestResult]) -> None:
        """Expire past-deadline requests parked in the handoff queue.
        These left the prefill scheduler already (take_prefilled) but
        still hold prefill-pool page references for the pending copy —
        the one resident claim _sweep_timeouts can't see — so the drop
        happens here, against the prefill allocator, before the decode
        pool ever reserves for them."""
        pre = self.prefill
        still: List[RequestState] = []
        for st in self._handoff_q:
            if st.deadline is None or now < st.deadline:
                still.append(st)
                continue
            st.finish_reason = "timeout"
            for p in st.owned_pages:
                pre.page_allocator.release(p)
            st.owned_pages = []
            if self.events is not None:
                self.events.emit(ev.REQUEST_TIMEOUT, request=st.req.id,
                                 slot=st.slot, new_tokens=0,
                                 deadline_seconds=pre.config
                                 .request_timeout,
                                 trace=st.req.id)
            if pre.telemetry is not None:
                pre.telemetry.requests_total.inc()
            rt = pre._trace(st.req.id)
            if rt is not None:
                rt.attrs.update(finish_reason="timeout", new_tokens=0)
                rt.finish("timeout", now)
            results[st.req.id] = RequestResult(
                id=st.req.id, tokens=[], logprobs=[],
                finish_reason="timeout", ttft=-1.0, token_times=[],
                cached_tokens=st.cached_tokens,
                admitted_at=st.admitted_at)
        self._handoff_q = still

    def _drain_handoffs(self, now_fn) -> None:
        """Install every queued handoff the decode pool can take right
        now (a free slot + a full-span page reservation); the rest stay
        queued — backpressure keeps this queue short, and decode-side
        retirements free the capacity that drains it."""
        dec = self.decode
        still: List[RequestState] = []
        for st in self._handoff_q:
            reserved = None
            if dec.slots.free:
                reserved = dec.scheduler._reserve_pages(
                    st.req, dec.page_allocator)
            if reserved is None:
                still.append(st)
                continue
            self._handoff(st, reserved, now_fn())
        self._handoff_q = still

    def run(self, requests: Sequence[Request] = (),
            on_token: Optional[Callable[[Request, int], None]] = None,
            ) -> Dict[int, RequestResult]:
        """Drive both pools to completion over `requests` — same
        contract as ServingEngine.run (trace replay via future
        arrivals, on_token streaming, {id: RequestResult})."""
        pre, dec = self.prefill, self.decode
        ps = self.config.page_size
        for r in requests:
            need = Scheduler.pages_needed(r, ps)
            if need > dec.page_allocator.usable:
                raise ValueError(
                    f"request {r.id}: worst-case span needs {need} KV "
                    f"pages but the decode pool has "
                    f"{dec.page_allocator.usable} usable")
            pneed = Scheduler.prompt_pages_needed(r, ps)
            if pneed > pre.page_allocator.usable:
                raise ValueError(
                    f"request {r.id}: prompt span needs {pneed} KV pages "
                    f"but the prefill pool has "
                    f"{pre.page_allocator.usable} usable")
            pre.scheduler.submit(r)
            if self.tracer is not None:
                rt = self.tracer.begin_request(
                    r.id, t0=r.arrival, prompt_len=len(r.prompt),
                    max_new_tokens=r.max_new_tokens, disagg=True)
                if rt is not None:
                    # the facade has no front door queue: admission
                    # starts at arrival (the run clock starts at 0)
                    rt.begin_hop("serve.admission", r.arrival)
        t0 = time.perf_counter()
        now_fn = lambda: time.perf_counter() - t0   # noqa: E731
        # both pools stamp trace hops on the SAME run clock, so a
        # request's prefill/handoff/decode hops stay contiguous across
        # the pool boundary
        pre._trace_now = dec._trace_now = now_fn
        if self.tracer is not None:
            dec._session_span = self.tracer.begin_session(
                now_fn(), slots=dec.config.slots, pool="decode")
        results: Dict[int, RequestResult] = {}
        pending = None
        while not (pre.scheduler.idle and not self._handoff_q
                   and dec.scheduler.idle and pending is None):
            now = now_fn()
            # per-pool deadline sweeps plus the handoff queue (a request
            # parked between pools holds prefill-side pages — it must
            # not outlive its deadline there either)
            pre._sweep_timeouts(now, results)
            dec._sweep_timeouts(now, results)
            self._sweep_handoff_timeouts(now, results)
            with span("serve.schedule"):
                pre._note_admissions(
                    pre.scheduler.admit(pre.slots.free, now,
                                        allocator=pre.page_allocator))
            for eng, qdepth in ((pre, len(pre.scheduler.queue)),
                                (dec, len(self._handoff_q))):
                eng.occupancy_peak = max(eng.occupancy_peak,
                                         eng.slots.occupied)
                eng.pages_in_use_peak = max(eng.pages_in_use_peak,
                                            eng.page_allocator.in_use)
                if eng.telemetry is not None:
                    # the decode pool's "queue" is the handoff queue —
                    # prompts prefilled but not yet installed
                    eng.telemetry.queue_depth.set(qdepth)
                    eng.telemetry.slot_occupancy.set(eng.slots.occupied)
                    eng.telemetry.pages_in_use.set(
                        eng.page_allocator.in_use)
                    eng.telemetry.pages_cached.set(
                        eng.page_allocator.cached_pages)
            if (pre.slots.occupied == 0 and not self._handoff_q
                    and dec.slots.occupied == 0 and pending is None):
                nxt = pre.scheduler.next_arrival()
                if nxt is not None and nxt > now_fn():
                    time.sleep(min(nxt - now_fn(), 0.05))
                continue
            lead = pre.scheduler.next_prefill()
            if lead is not None:
                pre._run_prefill_batched(lead)
            self._handoff_q.extend(pre.take_prefilled())
            self._drain_handoffs(now_fn)
            planned = {}
            if (dec.config.speculative is not None
                    and dec.scheduler.decoding()):
                # the decode pool verifies; drafting is host state, so
                # the disaggregated split composes with speculation with
                # no extra machinery (see ServingEngine.run)
                if pending is not None:
                    for fin in dec._sync_decode_step(pending, now_fn,
                                                     on_token):
                        dec._retire_state(fin, results)
                    pending = None
                planned = dec._plan_drafts()
            if planned:
                for fin in dec._spec_step(planned, now_fn, on_token):
                    dec._retire_state(fin, results)
                new_pending = None
            else:
                new_pending = (dec._dispatch_decode_step()
                               if dec.scheduler.decoding() else None)
            if pending is not None:
                for fin in dec._sync_decode_step(pending, now_fn,
                                                 on_token):
                    dec._retire_state(fin, results)
                pending = None
            if self.config.async_decode:
                pending = new_pending
            elif new_pending is not None:
                for fin in dec._sync_decode_step(new_pending, now_fn,
                                                 on_token):
                    dec._retire_state(fin, results)
        for eng in (pre, dec):
            if eng.telemetry is not None:
                counts = eng.compile_counts()
                eng.telemetry.step_compiles.set(counts["step"])
                eng.telemetry.prefill_compiles.set(counts["prefill"])
                eng.telemetry.queue_depth.set(0)
                eng.telemetry.slot_occupancy.set(eng.slots.occupied)
        if dec._session_span is not None:
            dec._session_span.end(now_fn())
            dec._session_span = None
        pre._trace_now = dec._trace_now = None
        return results


__all__ = ["SAMPLE_POOL", "DecodeEngine", "DisaggEngine", "EngineConfig",
           "PrefillEngine", "RequestResult", "ServingEngine",
           "propose_ngram", "sample_slots"]
