"""Serving front door: prefix-affinity routing over an engine fleet.

One engine is HBM-bound; the fleet answer is N replicas behind a router.
This module is that router: a single-threaded dispatcher that drives N
in-process ServingEngine replicas through their steppable session API
(engine.start/submit/tick/finish) on ONE shared clock, deciding for each
arriving request

  1. whether to admit it at all (per-replica in-flight caps — the shed
     path rejects at the front door BEFORE a request strands pages or
     slots on a saturated replica), and
  2. WHICH replica serves it, by prefix-cache affinity first: the
     replica whose PageAllocator holds the deepest warm chain for the
     prompt's page-aligned prefix windows (the same
     `(parent_page, token_window)` keying slots.py uses — probed via
     PageAllocator.probe, so router and replica can never key
     differently), load-aware dispatch (queue depth x free slots x free
     pages) breaking ties and taking over entirely when affinity is off
     or cold.

Affinity NEVER overrides load saturation: a replica at its in-flight
cap is ineligible no matter how warm its cache is — a hit on a full
replica would queue behind its whole backlog and lose more TTFT than
the prefill it saves.

Failover: a replica whose submit/tick raises is marked dead, and every
request it still held in flight is resubmitted to the survivors
(idempotent at the front door — results key by request id and the
replay is a fresh Request, so the caller sees exactly one result per
request; greedy tokens are engine-independent, so the replay is
token-identical). Streamed tokens for a request that later failed over
restart from the replayed prefill.

Every decision is observable through RouterTelemetry
(telemetry/worker.py): per-replica dispatch counters, affinity
hit/miss pages, shed count, queue-wait histograms — `tpu_router_*`
series the controller's collector federates into `tpu_job_router_*`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from .engine import Request, RequestResult, ServingEngine
from .scheduler import Scheduler

__all__ = ["ReplicaHandle", "Router", "RouterConfig"]


@dataclass
class RouterConfig:
    """Front-door policy knobs.

    max_inflight: per-replica in-flight cap (dispatched, not yet
    retired). The shed path fires when EVERY live replica is at its
    cap — a bounded fleet-wide backlog, so a burst degrades to fast
    rejections instead of unbounded queueing.
    affinity: prefix-affinity scoring on/off (off = pure load-aware
    dispatch; the bench's A/B switch).
    """
    max_inflight: int = 8
    affinity: bool = True


@dataclass
class ReplicaHandle:
    """One engine replica as the router sees it: the engine itself plus
    the front door's own bookkeeping (which request ids it holds, and
    whether it is still alive)."""
    index: int
    engine: ServingEngine
    alive: bool = True
    inflight: Dict[int, Request] = field(default_factory=dict)
    dispatched_total: int = 0

    # -- scoring inputs ---------------------------------------------------

    def affinity_pages(self, prompt: Sequence[int]) -> int:
        """Warm-chain depth (pages) this replica's prefix cache holds
        for `prompt` — PageAllocator.probe, i.e. EXACTLY the keying its
        own admission lookup will walk. 0 without paging."""
        alloc = self.engine.page_allocator
        if alloc is None:
            return 0
        return alloc.probe(prompt)

    def load(self) -> tuple:
        """Load-aware dispatch key, ascending = less loaded: in-flight
        requests and queued-behind-slots depth first, then fewer free
        slots, then fewer available pages. Mirrors the
        `tpu_worker_queue_depth` / `tpu_worker_slot` /
        `tpu_worker_kv_pages_*` gauges an out-of-process router would
        scrape; in-process it reads the same state directly."""
        eng = self.engine
        alloc = eng.page_allocator
        free_pages = alloc.available if alloc is not None else 0
        return (len(self.inflight) + len(eng.scheduler.queue),
                -len(eng.slots.free),
                -free_pages)

    def fits(self, req: Request) -> bool:
        """Whether this replica could EVER hold the request's worst-case
        page span — a span the pool can't cover is submit()-rejected, so
        it is not a routing candidate."""
        alloc = self.engine.page_allocator
        if alloc is None:
            return True
        return Scheduler.pages_needed(req, alloc.page_size) <= alloc.usable


class Router:
    """Front-door dispatcher over N in-process engine replicas.

    Usage (the serve_benchmark / tier1 --router shape):
        router = Router([engine0, engine1], RouterConfig())
        results = router.run(requests)          # same contract as
                                                # ServingEngine.run()

    The loop is cooperative round-robin: each iteration admits every
    due arrival (route or shed), then ticks each live replica once.
    Replicas that raise are failed over (see module docstring). All
    replicas share one session clock, so `arrival` offsets and TTFTs
    are fleet-consistent.
    """

    def __init__(self, engines: Sequence[ServingEngine],
                 config: Optional[RouterConfig] = None,
                 telemetry=None):
        """telemetry: a telemetry.RouterTelemetry (optional,
        None-cost when absent)."""
        if not engines:
            raise ValueError("router needs at least one engine replica")
        cfg = config or RouterConfig()
        if cfg.max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{cfg.max_inflight}")
        self.config = cfg
        self.replicas = [ReplicaHandle(i, e) for i, e in enumerate(engines)]
        self.telemetry = telemetry
        self.results: Dict[int, RequestResult] = {}
        self.shed: Dict[int, RequestResult] = {}
        self.resubmitted_total = 0
        self.affinity_hit_pages = 0
        self.affinity_miss_pages = 0

    # -- routing policy ---------------------------------------------------

    def _live(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.alive]

    def _pick(self, req: Request) -> Optional[ReplicaHandle]:
        """The dispatch decision. Eligible = alive, under the in-flight
        cap, and able to ever fit the span; among those, deepest warm
        prefix chain wins (affinity on), load key breaks ties, lowest
        index makes it deterministic. Returns None = shed."""
        eligible = [r for r in self._live()
                    if len(r.inflight) < self.config.max_inflight
                    and r.fits(req)]
        if not eligible:
            return None
        if self.config.affinity:
            scored = [(-r.affinity_pages(req.prompt), r.load(), r.index, r)
                      for r in eligible]
        else:
            scored = [(0, r.load(), r.index, r) for r in eligible]
        scored.sort(key=lambda s: s[:3])
        return scored[0][3]

    def _shed(self, req: Request, now: float) -> None:
        """Front-door rejection: a result with finish_reason "shed" and
        no tokens — the request never touched a replica, so no pages or
        slots were stranded."""
        self.shed[req.id] = RequestResult(
            id=req.id, tokens=[], logprobs=[], finish_reason="shed",
            ttft=-1.0, token_times=[], cached_tokens=0, admitted_at=now)
        if self.telemetry is not None:
            self.telemetry.shed_total.inc()

    def _dispatch(self, req: Request, now: float) -> bool:
        """Route one due request: pick a replica (or shed), record the
        affinity prediction, submit. Returns False when shed."""
        rep = self._pick(req)
        if rep is None:
            self._shed(req, now)
            return False
        # measured in BOTH modes (affinity off still records how warm the
        # load-chosen replica happened to be) so the A/B hit-rate
        # comparison is honest, not affinity-counting-itself
        warm = rep.affinity_pages(req.prompt)
        alloc = rep.engine.page_allocator
        full = (max(0, (len(req.prompt) - 1) // alloc.page_size)
                if alloc is not None else 0)
        self.affinity_hit_pages += warm
        self.affinity_miss_pages += full - warm
        tel = self.telemetry
        if tel is not None:
            tel.dispatch_for(rep.index).inc()
            tel.affinity_hit_pages.inc(warm)
            tel.affinity_miss_pages.inc(full - warm)
            if now >= req.arrival:
                tel.queue_wait_seconds.observe(now - req.arrival)
        rep.engine.submit(req)
        rep.inflight[req.id] = req
        rep.dispatched_total += 1
        return True

    def _fail_replica(self, rep: ReplicaHandle, now: float,
                      backlog: List[Request]) -> None:
        """Mark a replica dead and push its in-flight requests back on
        the dispatch backlog as fresh arrivals. The dead engine's
        partial results are DISCARDED (results key by id; the replay
        produces the authoritative — and for greedy traffic identical —
        tokens)."""
        rep.alive = False
        if self.telemetry is not None:
            self.telemetry.replica_deaths.inc()
        for req in rep.inflight.values():
            replay = Request(
                id=req.id, prompt=list(req.prompt),
                max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, eos_id=req.eos_id, arrival=now)
            backlog.append(replay)
            self.resubmitted_total += 1
            if self.telemetry is not None:
                self.telemetry.resubmits_total.inc()
        rep.inflight.clear()

    # -- the loop ---------------------------------------------------------

    def run(self, requests: Sequence[Request] = (),
            on_token: Optional[Callable[[Request, int], None]] = None,
            ) -> Dict[int, RequestResult]:
        """Drive the fleet until every request completes or sheds.
        Same contract as ServingEngine.run(): returns
        {request.id: RequestResult}; shed requests appear with
        finish_reason "shed" and no tokens."""
        if any(not r.alive for r in self.replicas):
            raise RuntimeError("router already consumed (dead replicas)")
        t0 = time.perf_counter()
        now_fn = lambda: time.perf_counter() - t0   # noqa: E731
        for rep in self.replicas:
            rep.engine.start(on_token, now_fn=now_fn)
        # FCFS dispatch backlog; failover replays append at the tail
        backlog: List[Request] = sorted(requests, key=lambda r: r.arrival)
        seen = set()
        for r in backlog:
            if r.id in seen:
                raise ValueError(f"duplicate request id {r.id}")
            seen.add(r.id)
        while True:
            now = now_fn()
            # admit every due arrival this pass (route or shed) — sheds
            # happen at ARRIVAL, never after queueing on a replica
            while backlog and backlog[0].arrival <= now:
                self._dispatch(backlog.pop(0), now)
            progressed = False
            for rep in self._live():
                try:
                    progressed |= rep.engine.tick()
                except Exception:
                    self._fail_replica(rep, now_fn(), backlog)
                    backlog.sort(key=lambda r: r.arrival)
                    continue
                self._collect(rep)
            live = self._live()
            if not live:
                raise RuntimeError(
                    f"every replica died with {len(backlog)} request(s) "
                    f"outstanding")
            if not backlog and all(not r.engine.active for r in live):
                break
            if not progressed:
                # everything is waiting on a future arrival
                nxt = backlog[0].arrival if backlog else None
                for rep in live:
                    rn = rep.engine.scheduler.next_arrival()
                    if rn is not None:
                        nxt = rn if nxt is None else min(nxt, rn)
                now = now_fn()
                if nxt is not None and nxt > now:
                    time.sleep(min(nxt - now, 0.05))
        out: Dict[int, RequestResult] = {}
        for rep in self.replicas:
            if rep.alive:
                self._collect(rep, final=rep.engine.finish())
        out.update(self.results)
        out.update(self.shed)
        if self.telemetry is not None:
            self.telemetry.requests_total.inc(len(self.results))
        return out

    def _collect(self, rep: ReplicaHandle,
                 final: Optional[Dict[int, RequestResult]] = None) -> None:
        """Fan in newly retired results from one replica. Results key by
        request id — the idempotence point for failover replays (a dead
        replica's partials were dropped with it, so each id lands here
        exactly once)."""
        done = final if final is not None \
            else rep.engine.session_results()
        for rid in [r for r in rep.inflight if r in done]:
            self.results[rid] = done[rid]
            del rep.inflight[rid]

    # -- reporting --------------------------------------------------------

    def affinity_hit_rate(self) -> float:
        """Warm pages / full prompt pages over every dispatched request
        (the prediction made AT dispatch; replica-side
        prefix_hit_pages counters confirm it at admission)."""
        total = self.affinity_hit_pages + self.affinity_miss_pages
        return self.affinity_hit_pages / total if total else 0.0

    def dispatch_counts(self) -> List[int]:
        return [r.dispatched_total for r in self.replicas]

    def shed_count(self) -> int:
        return len(self.shed)

    def dead_replicas(self) -> List[int]:
        return [r.index for r in self.replicas if not r.alive]
