"""Serving front door: prefix-affinity routing over an engine fleet.

One engine is HBM-bound; the fleet answer is N replicas behind a router.
This module is that router: a single-threaded dispatcher that drives N
in-process ServingEngine replicas through their steppable session API
(engine.start/submit/tick/finish) on ONE shared clock, deciding for each
arriving request

  1. whether to admit it at all (per-replica in-flight caps — the shed
     path rejects at the front door BEFORE a request strands pages or
     slots on a saturated replica), and
  2. WHICH replica serves it, by prefix-cache affinity first: the
     replica whose PageAllocator holds the deepest warm chain for the
     prompt's page-aligned prefix windows (the same
     `(parent_page, token_window)` keying slots.py uses — probed via
     PageAllocator.probe, so router and replica can never key
     differently), load-aware dispatch (queue depth x free slots x free
     pages) breaking ties and taking over entirely when affinity is off
     or cold.

Affinity NEVER overrides load saturation: a replica at its in-flight
cap is ineligible no matter how warm its cache is — a hit on a full
replica would queue behind its whole backlog and lose more TTFT than
the prefill it saves.

Failover: a replica whose submit/tick raises is marked dead, and every
request it still held in flight is resubmitted to the survivors
(idempotent at the front door — results key by request id and the
replay is a fresh Request, so the caller sees exactly one result per
request; greedy tokens are engine-independent, so the replay is
token-identical). Streamed tokens for a request that later failed over
restart from the replayed prefill.

LIVE topology changes (the autoscaler's surgical path — no other
replica pauses, nothing recompiles):

  * attach_replica() joins a PRE-WARMED engine to the open session —
    warmup (the compile pin) happens out-of-band, which is the whole
    point: the router is single-threaded, so warming in-band would
    stall exactly the goodput the new replica is supposed to buy. A
    cold engine is refused loudly.
  * detach_replica() runs a graceful drain: admission closes
    immediately (draining replicas are never picked), requests still
    in the engine's queue (submitted, not yet admitted to slots) are
    pulled back and FAILED OVER to survivors through the same
    idempotent replay path failover uses — never shed — and requests
    already decoding finish in place. Teardown only happens once the
    replica is idle, with pages/slots verified reclaimed
    (PageAllocator.check()).
  * schedule_attach()/schedule_detach() arm either action at a session
    time, executed inside run() — the bench/chaos shape for mid-trace
    ±1 steps. Completed steps land in `live_scale_log` with their
    drain/warmup phase split (the data side of the resize ledger's
    live_scale entries).

Load visibility is push-first: with heartbeats on
(RouterConfig.heartbeat_interval), every engine publishes queue depth /
free slots / free pages into RouterTelemetry on a session-clock
heartbeat, and dispatch scoring prefers a FRESH heartbeat over probing
engine state in-process — falling back when the report is older than
the staleness threshold (the collector's scrape-staleness convention:
age since last successful report, default twice the publish interval).
In-process both sources agree; the heartbeat path is what a cross-host
router would actually see.

Every decision is observable through RouterTelemetry
(telemetry/worker.py): per-replica dispatch counters, affinity
hit/miss pages, shed count, queue-wait histograms, per-replica
heartbeat gauges, attach/detach counters — `tpu_router_*` series the
controller's collector federates into `tpu_job_router_*`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..telemetry import span
from .engine import Request, RequestResult, ServingEngine
from .scheduler import Scheduler

__all__ = ["ReplicaHandle", "Router", "RouterConfig"]


@dataclass
class RouterConfig:
    """Front-door policy knobs.

    max_inflight: per-replica in-flight cap (dispatched, not yet
    retired). The shed path fires when EVERY live replica is at its
    cap — a bounded fleet-wide backlog, so a burst degrades to fast
    rejections instead of unbounded queueing.
    affinity: prefix-affinity scoring on/off (off = pure load-aware
    dispatch; the bench's A/B switch).
    heartbeat_interval: > 0 turns on push-based replica load reports —
    every engine publishes queue depth / free slots / free pages into
    RouterTelemetry at most once per interval of session time, and
    dispatch scoring PREFERS a fresh report over probing the engine
    in-process. 0 (default) keeps the probing path.
    heartbeat_staleness: maximum report age (seconds of session time)
    before dispatch falls back to probing — the collector's
    scrape-staleness convention, age since the last successful report.
    None = 2x heartbeat_interval (one missed beat tolerated, two is a
    silent replica).
    """
    max_inflight: int = 8
    affinity: bool = True
    heartbeat_interval: float = 0.0
    heartbeat_staleness: Optional[float] = None


@dataclass
class ReplicaHandle:
    """One engine replica as the router sees it: the engine itself plus
    the front door's own bookkeeping (which request ids it holds, and
    whether it is still alive).

    Lifecycle: alive -> (draining) -> detached | dead. `draining` means
    admission is closed but resident requests are still finishing;
    `detached` marks a VOLUNTARY exit (graceful drain completed, pages
    and slots verified reclaimed) — distinct from a failover death, so
    a scaled-down fleet is not mistaken for a crashed one."""
    index: int
    engine: ServingEngine
    alive: bool = True
    draining: bool = False
    detached: bool = False
    drain_started: float = 0.0
    inflight: Dict[int, Request] = field(default_factory=dict)
    dispatched_total: int = 0

    # -- scoring inputs ---------------------------------------------------

    def affinity_pages(self, prompt: Sequence[int]) -> int:
        """Warm-chain depth (pages) this replica's prefix cache holds
        for `prompt` — PageAllocator.probe, i.e. EXACTLY the keying its
        own admission lookup will walk. 0 without paging."""
        alloc = self.engine.page_allocator
        if alloc is None:
            return 0
        return alloc.probe(prompt)

    def load(self) -> tuple:
        """Load-aware dispatch key, ascending = less loaded: in-flight
        requests and queued-behind-slots depth first, then fewer free
        slots, then fewer available pages. Mirrors the
        `tpu_worker_queue_depth` / `tpu_worker_slot` /
        `tpu_worker_kv_pages_*` gauges an out-of-process router would
        scrape; in-process it reads the same state directly."""
        eng = self.engine
        alloc = eng.page_allocator
        free_pages = alloc.available if alloc is not None else 0
        return (len(self.inflight) + len(eng.scheduler.queue),
                -len(eng.slots.free),
                -free_pages)

    def fits(self, req: Request) -> bool:
        """Whether this replica could EVER hold the request's worst-case
        page span — a span the pool can't cover is submit()-rejected, so
        it is not a routing candidate."""
        alloc = self.engine.page_allocator
        if alloc is None:
            return True
        return Scheduler.pages_needed(req, alloc.page_size) <= alloc.usable


class Router:
    """Front-door dispatcher over N in-process engine replicas.

    Usage (the serve_benchmark / tier1 --router shape):
        router = Router([engine0, engine1], RouterConfig())
        results = router.run(requests)          # same contract as
                                                # ServingEngine.run()

    The loop is cooperative round-robin: each iteration admits every
    due arrival (route or shed), then ticks each live replica once.
    Replicas that raise are failed over (see module docstring). All
    replicas share one session clock, so `arrival` offsets and TTFTs
    are fleet-consistent.
    """

    def __init__(self, engines: Sequence[ServingEngine],
                 config: Optional[RouterConfig] = None,
                 telemetry=None, tracer=None):
        """telemetry: a telemetry.RouterTelemetry (optional,
        None-cost when absent). tracer: a telemetry.Tracer — the router
        opens each request's ROOT span at intake (queue-wait hop,
        dispatch/shed/failover span events) and shares the tracer with
        every replica engine that doesn't have its own, so one request
        keeps ONE trace no matter how many replicas serve it."""
        if not engines:
            raise ValueError("router needs at least one engine replica")
        cfg = config or RouterConfig()
        if cfg.max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{cfg.max_inflight}")
        self.config = cfg
        self.replicas = [ReplicaHandle(i, e) for i, e in enumerate(engines)]
        self.telemetry = telemetry
        self.tracer = tracer
        for rep in self.replicas:
            self._share_tracer(rep.engine)
        self.results: Dict[int, RequestResult] = {}
        self.shed: Dict[int, RequestResult] = {}
        self.resubmitted_total = 0
        self.affinity_hit_pages = 0
        self.affinity_miss_pages = 0
        # completed live topology steps, in order: one dict per
        # attach/detach with its drain/warmup phase split — the data
        # side of the resize ledger's live_scale entries (the bench
        # emits these as LIVE_SCALE events)
        self.live_scale_log: List[Dict] = []
        self._scale_plan: List[Dict] = []   # armed schedule_* steps
        self._backlog: List[Request] = []   # live only inside run()
        self._on_token: Optional[Callable[[Request, int], None]] = None
        self._now_fn: Optional[Callable[[], float]] = None

    # -- routing policy ---------------------------------------------------

    def _share_tracer(self, engine) -> None:
        """Hand the router's tracer to a replica engine that has none —
        the engine-side hops (admission/prefill/decode) land in the
        SAME trace registry the router's root spans live in. Tolerates
        engines (test fakes) that don't carry the attribute."""
        if self.tracer is None \
                or getattr(engine, "tracer", None) is not None:
            return
        try:
            engine.tracer = self.tracer
        except AttributeError:
            pass

    def _trace(self, rid: int):
        return self.tracer.active(rid) if self.tracer is not None else None

    def _live(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.alive]

    def _now(self, now: Optional[float] = None) -> float:
        return now if now is not None \
            else (self._now_fn() if self._now_fn is not None else 0.0)

    def _load_key(self, rep: ReplicaHandle, now: float) -> tuple:
        """Load key for dispatch scoring: a FRESH heartbeat report when
        push-based load reporting is on (plus the router's own in-flight
        count, which the replica cannot know), falling back to probing
        engine state in-process when the report is stale — age since
        last publish beyond the staleness threshold, the collector's
        scrape-staleness convention."""
        cfg = self.config
        tel = self.telemetry
        if tel is not None and cfg.heartbeat_interval > 0:
            get = getattr(tel, "heartbeat", None)
            hb = get(rep.index) if get is not None else None
            if hb is not None:
                staleness = cfg.heartbeat_staleness
                if staleness is None:
                    staleness = 2.0 * cfg.heartbeat_interval
                if now - hb["now"] <= staleness:
                    return (len(rep.inflight) + int(hb["queue_depth"]),
                            -int(hb["free_slots"]),
                            -int(hb["free_pages"]))
        return rep.load()

    def _pick(self, req: Request,
              now: Optional[float] = None) -> Optional[ReplicaHandle]:
        """The dispatch decision. Eligible = alive, NOT draining (a
        detach closes admission the instant it is requested), under the
        in-flight cap, and able to ever fit the span; among those,
        deepest warm prefix chain wins (affinity on), load key breaks
        ties, lowest index makes it deterministic. Returns None =
        shed."""
        now = self._now(now)
        eligible = [r for r in self._live()
                    if not r.draining
                    and len(r.inflight) < self.config.max_inflight
                    and r.fits(req)]
        if not eligible:
            return None
        if self.config.affinity:
            scored = [(-r.affinity_pages(req.prompt),
                       self._load_key(r, now), r.index, r)
                      for r in eligible]
        else:
            scored = [(0, self._load_key(r, now), r.index, r)
                      for r in eligible]
        scored.sort(key=lambda s: s[:3])
        return scored[0][3]

    def _shed(self, req: Request, now: float) -> None:
        """Front-door rejection: a result with finish_reason "shed" and
        no tokens — the request never touched a replica, so no pages or
        slots were stranded."""
        self.shed[req.id] = RequestResult(
            id=req.id, tokens=[], logprobs=[], finish_reason="shed",
            ttft=-1.0, token_times=[], cached_tokens=0, admitted_at=now)
        if self.telemetry is not None:
            self.telemetry.shed_total.inc()
        rt = self._trace(req.id)
        if rt is not None:
            rt.event("shed")
            rt.finish("shed", now)

    def _dispatch(self, req: Request, now: float) -> bool:
        """Route one due request: pick a replica (or shed), record the
        affinity prediction, submit. Returns False when shed."""
        rep = self._pick(req, now)
        if rep is None:
            self._shed(req, now)
            return False
        # measured in BOTH modes (affinity off still records how warm the
        # load-chosen replica happened to be) so the A/B hit-rate
        # comparison is honest, not affinity-counting-itself
        warm = rep.affinity_pages(req.prompt)
        alloc = rep.engine.page_allocator
        full = (max(0, (len(req.prompt) - 1) // alloc.page_size)
                if alloc is not None else 0)
        self.affinity_hit_pages += warm
        self.affinity_miss_pages += full - warm
        tel = self.telemetry
        if tel is not None:
            tel.dispatch_for(rep.index).inc()
            tel.affinity_hit_pages.inc(warm)
            tel.affinity_miss_pages.inc(full - warm)
            if now >= req.arrival:
                tel.queue_wait_seconds.observe(now - req.arrival)
        rt = self._trace(req.id)
        if rt is not None:
            # the dispatch decision as a span event on the root; the
            # engine's submit() closes the queue-wait hop where its
            # admission hop begins
            rt.event("dispatch", replica=rep.index, warm_pages=warm)
        rep.engine.submit(req)
        rep.inflight[req.id] = req
        rep.dispatched_total += 1
        return True

    def _fail_replica(self, rep: ReplicaHandle, now: float,
                      backlog: List[Request]) -> None:
        """Mark a replica dead and push its in-flight requests back on
        the dispatch backlog as fresh arrivals. The dead engine's
        partial results are DISCARDED (results key by id; the replay
        produces the authoritative — and for greedy traffic identical —
        tokens). A DRAINING replica that dies mid-drain takes this same
        path: its residents fail over instead of finishing in place."""
        rep.alive = False
        rep.draining = False
        if self.telemetry is not None:
            self.telemetry.replica_deaths.inc()
        # the dead engine's per-session trace root closes as a failover
        # casualty so its batch spans keep a parent (zero orphans)
        abandon = getattr(rep.engine, "trace_abandon", None)
        if abandon is not None:
            abandon(now)
        for req in rep.inflight.values():
            replay = Request(
                id=req.id, prompt=list(req.prompt),
                max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, eos_id=req.eos_id, arrival=now)
            backlog.append(replay)
            self.resubmitted_total += 1
            if self.telemetry is not None:
                self.telemetry.resubmits_total.inc()
            rt = self._trace(req.id)
            if rt is not None:
                # ONE trace across replicas: the open hop dies with the
                # replica, the root survives into the replay's fresh
                # queue-wait hop
                rt.event("failover", replica=rep.index)
                rt.abandon(now)
                rt.begin_hop("router.queue_wait", now)
        rep.inflight.clear()

    # -- live topology (the autoscaler's surgical ±1 path) -----------------

    def active_count(self) -> int:
        """Replicas currently accepting new work (alive, not
        draining)."""
        return sum(1 for r in self.replicas
                   if r.alive and not r.draining)

    def _require_warm(self, engine) -> None:
        """The warmup compile pin: an attaching engine must have its
        decode step compiled BEFORE it joins (compile_counts()['step']
        >= 1). Warming in-band would stall the single-threaded router —
        exactly the goodput the new replica is supposed to buy — so a
        cold engine is refused loudly and the caller warms it
        out-of-band (a pinned-shape request through engine.run()).
        Engines that do not expose compile_counts (test fakes) pass."""
        counts_fn = getattr(engine, "compile_counts", None)
        if counts_fn is None:
            return
        counts = counts_fn()
        if counts.get("step", 0) < 1:
            raise ValueError(
                "attach_replica needs a PRE-WARMED engine (zero step "
                "compiles seen) — run a pinned-shape warmup request "
                "through it out-of-band first")

    def _wire_heartbeat(self, rep: ReplicaHandle) -> None:
        """Install the push-based load reporter on one replica (no-op
        when heartbeats are off, telemetry is absent, or the engine
        does not support it)."""
        cfg = self.config
        tel = self.telemetry
        if tel is None or cfg.heartbeat_interval <= 0:
            return
        setter = getattr(rep.engine, "set_heartbeat", None)
        note = getattr(tel, "note_heartbeat", None)
        if setter is None or note is None:
            return
        idx = rep.index
        setter(lambda **kw: note(idx, **kw), cfg.heartbeat_interval)

    def attach_replica(self, engine: ServingEngine,
                       now: Optional[float] = None,
                       warmup_seconds: float = 0.0) -> ReplicaHandle:
        """Join one PRE-WARMED engine to the fleet — the +1 step. No
        other replica pauses: mid-session the newcomer starts on the
        SHARED session clock and becomes dispatch-eligible immediately
        (the compile pin already happened out-of-band; `warmup_seconds`
        records how long it took, for the live_scale ledger entry).
        Outside a session the handle simply joins the roster and run()
        starts it with the rest."""
        # host-span coverage for the attach path: live-scale stalls
        # (warm check + session join) show up in XProf captures
        with span("router.attach_replica"):
            self._require_warm(engine)
            now = self._now(now)
            idx = max(r.index for r in self.replicas) + 1
            rep = ReplicaHandle(idx, engine)
            self.replicas.append(rep)
            self._share_tracer(engine)
            if self._now_fn is not None:
                engine.start(self._on_token, now_fn=self._now_fn)
                self._wire_heartbeat(rep)
        self.live_scale_log.append({
            "action": "attach", "replica": idx,
            "ts": round(now, 6),
            "drain_seconds": 0.0,
            "warmup_seconds": round(float(warmup_seconds), 6),
            "total_seconds": round(float(warmup_seconds), 6),
            "replicas": self.active_count()})
        if self.telemetry is not None:
            self.telemetry.attach_total.inc()
        return rep

    def detach_replica(self, index: int,
                       now: Optional[float] = None) -> None:
        """Begin the graceful drain of one replica — the -1 step.
        Admission closes IMMEDIATELY (draining replicas are never
        picked); requests the replica had queued behind its slots
        (submitted, not yet admitted) are pulled back and FAILED OVER
        to the survivors through the idempotent replay path — never
        shed — and residents finish in place. Teardown happens in
        _service_drains once the replica goes idle."""
        rep = next((r for r in self.replicas if r.index == index), None)
        if rep is None or not rep.alive:
            raise ValueError(f"no live replica with index {index}")
        if rep.draining:
            return
        if self.active_count() <= 1:
            raise ValueError(
                "cannot detach the last active replica (the autoscaler's "
                "minDecodeReplicas floor exists for the same reason)")
        now = self._now(now)
        rep.draining = True
        rep.drain_started = now
        # pull back everything still queued behind the slots: those
        # requests never touched pages, so re-routing them is pure
        # bookkeeping — the same fresh-Request replay failover uses
        queue = rep.engine.scheduler.queue
        pulled = [q for q in list(queue) if q.id in rep.inflight]
        for q in pulled:
            queue.remove(q)
            del rep.inflight[q.id]
            replay = Request(
                id=q.id, prompt=list(q.prompt),
                max_new_tokens=q.max_new_tokens,
                temperature=q.temperature, top_k=q.top_k,
                top_p=q.top_p, eos_id=q.eos_id,
                arrival=max(q.arrival, now))
            self._backlog.append(replay)
            self.resubmitted_total += 1
            if self.telemetry is not None:
                self.telemetry.resubmits_total.inc()
            rt = self._trace(q.id)
            if rt is not None:
                rt.event("drain_requeue", replica=rep.index)
                rt.abandon(now)
                rt.begin_hop("router.queue_wait", replay.arrival)
        self._backlog.sort(key=lambda r: r.arrival)

    def schedule_attach(self, at: float, engine,
                        warmup_seconds: float = 0.0) -> None:
        """Arm a +1 step at session time `at`. `engine` is the
        pre-warmed engine, or a zero-arg factory returning one (built
        out-of-band — construction cost must not land on the trace
        clock, that is gang-restart's failure mode, not live
        scaling's)."""
        self._scale_plan.append({"at": float(at), "kind": "attach",
                                 "engine": engine,
                                 "warmup_seconds": float(warmup_seconds)})
        self._scale_plan.sort(key=lambda s: s["at"])

    def schedule_detach(self, at: float, index: int) -> None:
        """Arm a -1 step (graceful drain of `index`) at session time
        `at`."""
        self._scale_plan.append({"at": float(at), "kind": "detach",
                                 "index": index})
        self._scale_plan.sort(key=lambda s: s["at"])

    def _execute_scale(self, step: Dict, now: float) -> None:
        if step["kind"] == "attach":
            engine = step["engine"]
            if callable(engine) and not hasattr(engine, "submit"):
                engine = engine()
            self.attach_replica(engine, now=now,
                                warmup_seconds=step["warmup_seconds"])
        else:
            self.detach_replica(step["index"], now=now)

    def _service_drains(self, now: float) -> None:
        """Finish any drain whose replica has gone idle: close its
        session, fan in the last results, VERIFY pages and slots came
        back (PageAllocator.check() plus zero pinned pages and a full
        free-slot list — a leak here is a correctness bug, not a
        capacity nit), and mark it detached."""
        for rep in self.replicas:
            if not (rep.alive and rep.draining):
                continue
            if rep.inflight or rep.engine.active:
                continue
            # host-span coverage for the drain finalize (session close
            # + reclaim audit) — the other half of a live-scale stall
            with span("router.service_drain"):
                self._collect(rep, final=rep.engine.finish())
                self._verify_reclaim(rep)
            rep.alive = False
            rep.draining = False
            rep.detached = True
            drain = max(0.0, now - rep.drain_started)
            self.live_scale_log.append({
                "action": "detach", "replica": rep.index,
                "ts": round(now, 6),
                "drain_seconds": round(drain, 6),
                "warmup_seconds": 0.0,
                "total_seconds": round(drain, 6),
                "replicas": self.active_count()})
            if self.telemetry is not None:
                self.telemetry.detach_total.inc()

    @staticmethod
    def _verify_reclaim(rep: ReplicaHandle) -> None:
        eng = rep.engine
        alloc = getattr(eng, "page_allocator", None)
        if alloc is not None:
            alloc.check()
            if alloc.in_use != 0:
                raise RuntimeError(
                    f"detach leak: replica {rep.index} still pins "
                    f"{alloc.in_use} KV page(s) after drain")
        slots = getattr(eng, "slots", None)
        total = getattr(slots, "n", None)
        if total is not None and len(slots.free) != total:
            raise RuntimeError(
                f"detach leak: replica {rep.index} drained with "
                f"{total - len(slots.free)} slot(s) still bound")

    # -- the loop ---------------------------------------------------------

    def run(self, requests: Sequence[Request] = (),
            on_token: Optional[Callable[[Request, int], None]] = None,
            ) -> Dict[int, RequestResult]:
        """Drive the fleet until every request completes or sheds AND
        every armed scale step has executed (drains included). Same
        contract as ServingEngine.run(): returns
        {request.id: RequestResult}; shed requests appear with
        finish_reason "shed" and no tokens. Replicas that exited by
        graceful detach do NOT poison the router the way failover
        deaths do."""
        if any(not r.alive and not r.detached for r in self.replicas):
            raise RuntimeError("router already consumed (dead replicas)")
        t0 = time.perf_counter()
        now_fn = lambda: time.perf_counter() - t0   # noqa: E731
        self._now_fn = now_fn
        self._on_token = on_token
        for rep in self.replicas:
            if rep.alive:
                rep.engine.start(on_token, now_fn=now_fn)
                self._wire_heartbeat(rep)
        # FCFS dispatch backlog; failover/drain replays append at the
        # tail (held on self so detach_replica can reach it mid-loop)
        backlog = self._backlog = sorted(requests, key=lambda r: r.arrival)
        seen = set()
        for r in backlog:
            if r.id in seen:
                raise ValueError(f"duplicate request id {r.id}")
            seen.add(r.id)
            if self.tracer is not None:
                # ROOT span at the front door, t0 = arrival; the
                # queue-wait hop runs until dispatch closes it
                rt = self.tracer.begin_request(
                    r.id, t0=r.arrival, prompt_len=len(r.prompt),
                    max_new_tokens=r.max_new_tokens)
                if rt is not None:
                    rt.begin_hop("router.queue_wait", r.arrival)
        try:
            while True:
                now = now_fn()
                # due scale steps FIRST: an arrival racing a detach must
                # see the post-step fleet (route to survivors — the
                # failover path's job, not the shed path's)
                while self._scale_plan and self._scale_plan[0]["at"] <= now:
                    self._execute_scale(self._scale_plan.pop(0), now)
                # admit every due arrival this pass (route or shed) —
                # sheds happen at ARRIVAL, never after queueing on a
                # replica
                while backlog and backlog[0].arrival <= now:
                    self._dispatch(backlog.pop(0), now)
                progressed = False
                for rep in self._live():
                    try:
                        progressed |= rep.engine.tick()
                    except Exception:
                        self._fail_replica(rep, now_fn(), backlog)
                        backlog.sort(key=lambda r: r.arrival)
                        continue
                    self._collect(rep)
                self._service_drains(now_fn())
                live = self._live()
                if not live:
                    raise RuntimeError(
                        f"every replica died with {len(backlog)} "
                        f"request(s) outstanding")
                if (not backlog and not self._scale_plan
                        and all(not r.engine.active for r in live)):
                    break
                if not progressed:
                    # everything is waiting on a future arrival or a
                    # future scale step
                    nxt = backlog[0].arrival if backlog else None
                    if self._scale_plan:
                        at = self._scale_plan[0]["at"]
                        nxt = at if nxt is None else min(nxt, at)
                    for rep in live:
                        rn = rep.engine.scheduler.next_arrival()
                        if rn is not None:
                            nxt = rn if nxt is None else min(nxt, rn)
                    now = now_fn()
                    if nxt is not None and nxt > now:
                        time.sleep(min(nxt - now, 0.05))
        finally:
            self._now_fn = None
            self._on_token = None
            self._backlog = []
        out: Dict[int, RequestResult] = {}
        for rep in self.replicas:
            if rep.alive:
                self._collect(rep, final=rep.engine.finish())
        out.update(self.results)
        out.update(self.shed)
        if self.telemetry is not None:
            self.telemetry.requests_total.inc(len(self.results))
        return out

    def _collect(self, rep: ReplicaHandle,
                 final: Optional[Dict[int, RequestResult]] = None) -> None:
        """Fan in newly retired results from one replica. Results key by
        request id — the idempotence point for failover replays (a dead
        replica's partials were dropped with it, so each id lands here
        exactly once)."""
        done = final if final is not None \
            else rep.engine.session_results()
        for rid in [r for r in rep.inflight if r in done]:
            self.results[rid] = done[rid]
            del rep.inflight[rid]

    # -- reporting --------------------------------------------------------

    def affinity_hit_rate(self) -> float:
        """Warm pages / full prompt pages over every dispatched request
        (the prediction made AT dispatch; replica-side
        prefix_hit_pages counters confirm it at admission)."""
        total = self.affinity_hit_pages + self.affinity_miss_pages
        return self.affinity_hit_pages / total if total else 0.0

    def dispatch_counts(self) -> List[int]:
        return [r.dispatched_total for r in self.replicas]

    def shed_count(self) -> int:
        return len(self.shed)

    def dead_replicas(self) -> List[int]:
        """Replicas lost to FAILOVER — voluntary detaches are not
        deaths."""
        return [r.index for r in self.replicas
                if not r.alive and not r.detached]

    def detached_replicas(self) -> List[int]:
        """Replicas that exited by graceful drain (scale-down steps)."""
        return [r.index for r in self.replicas if r.detached]
