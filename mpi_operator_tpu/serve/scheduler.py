"""Host-side request scheduling for the continuous-batching engine.

Everything here is plain Python over plain numbers — no jax — so the
policy (FCFS admission, chunk planning, retirement) is unit-testable
without tracing anything, and the engine's device code stays a fixed
set of compiled programs that this module merely feeds.

The prefill trick worth knowing: a request's prompt of length P is
prefilled as prompt[:P-1] only. The LAST prompt token becomes the first
decode-step input (the "bonus token"), so the first NEW token comes out
of the same compiled decode step as every later one — no separate
"prefill tail + sample" program, and time-to-first-token is exactly one
decode step after the last chunk lands.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Request:
    """One generation request. `arrival` is seconds relative to the
    engine run's t0 (0.0 = already waiting when the run starts) — the
    bench replays traces by submitting requests with future arrivals.
    Sampling params mirror generate(): temperature 0 = greedy argmax
    (top_k/top_p ignored), top_k 0 = disabled, top_p 1.0 = disabled."""
    id: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    arrival: float = 0.0


@dataclasses.dataclass
class RequestState:
    """A request's life inside a slot. `pos` counts cache positions
    WRITTEN so far — it is both the slot's decode cursor and the next
    write offset. `chunks` are the pending prefill windows (start,
    size); once drained, `next_input` (initially the bonus token) flows
    through the shared decode step."""
    req: Request
    slot: int
    pos: int = 0
    chunks: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    next_input: int = 0
    # decode steps DISPATCHED for this request (>= len(generated): with
    # the async engine the newest step's token is still on the device).
    # dispatched >= 1 means the next step chains its input from the
    # previous step's device output (slots.step_arrays use_prev); once
    # dispatched reaches max_new_tokens the request stops consuming
    # steps and retires at the next sync.
    dispatched: int = 0
    # slot row already returned to the free pool (length exhaustion is
    # known at DISPATCH time, so the engine frees the row before the
    # final sync delivers the last token — the guard keeps the sync-side
    # retirement from releasing a row that may already be re-bound)
    slot_released: bool = False
    # the last emitted token lives on the HOST (next_input), not in the
    # device-side _prev_tok chain — set after a speculative verify step
    # (its targets return to the host for acceptance), cleared when a
    # plain decode step re-establishes the device chain. step_arrays
    # keeps use_prev False while set.
    host_next: bool = False
    generated: List[int] = dataclasses.field(default_factory=list)
    logprobs: List[float] = dataclasses.field(default_factory=list)
    token_times: List[float] = dataclasses.field(default_factory=list)
    admitted_at: float = 0.0
    finish_reason: Optional[str] = None   # "eos" | "length" | "timeout"
    # wall-clock (run-relative) deadline stamped at admission when
    # EngineConfig.request_timeout is set; None = no deadline. The
    # engine's timeout sweep retires a past-deadline request with
    # finish_reason "timeout" through the NORMAL retire path — slot and
    # KV pages reclaimed like any EOS, so one wedged request can neither
    # freeze the serving progress frontier nor leak pages.
    deadline: Optional[float] = None
    # paged-KV mode only (all None/zero otherwise): `page_table` maps the
    # slot's logical KV blocks to physical pages (length max_len //
    # page_size, unallocated entries = trash page 0); `owned_pages` are
    # the references this request holds — pinned shared prefix pages plus
    # its private pages — each release()d exactly once at retirement.
    # The request's whole worst-case span is reserved at ADMISSION
    # (ceil((P-1 + max_new) / page_size) pages, minus prefix hits), so
    # decode never allocates mid-flight and can never deadlock.
    page_table: Optional[List[int]] = None
    owned_pages: List[int] = dataclasses.field(default_factory=list)
    # prompt positions [0, cached_tokens) resolved from the prefix cache:
    # prefill starts at the cached span (TTFT win of a hit)
    cached_tokens: int = 0
    # prefix-publishing cursor: this request's prompt pages [0,
    # published_pages) are already in the prefix cache (hits count —
    # they were published by their original prefiller); the engine
    # advances it as prefill completes pages. publish_parent is the
    # chain key's parent page for the NEXT page to publish.
    published_pages: int = 0
    publish_parent: int = -1

    @property
    def prefilling(self) -> bool:
        return bool(self.chunks)

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


def plan_chunks(n: int, buckets: Sequence[int],
                start: int = 0) -> List[Tuple[int, int]]:
    """Windows (start, size) covering prompt positions [start, n), sizes
    drawn from the ≤3 compiled `buckets` (ascending). Full largest-bucket
    windows walk left→right; the ragged tail takes the smallest bucket
    that fits, RIGHT-ALIGNED (start = n - size) so no window writes past
    n — the overlap recomputes a suffix of already-written positions,
    which writes back identical values (same params, tokens, positions)
    instead of writing junk into the decode region. Only a prompt
    shorter than every bucket pads (one window at 0; the engine
    right-pads the tokens, and those pad writes land past the prompt
    where the decode cursor overwrites them before they are ever
    attended).

    `start` > 0 is the prefix-cache span (positions already resolved to
    shared pages): windows begin there, and the ragged tail is LEFT-
    aligned with padding instead of right-aligned — reaching backwards
    would rewrite SHARED pages, which other requests may be attending
    concurrently. The pad writes land past n where the decode cursor
    overwrites them, same as the short-prompt case."""
    if n < 0:
        raise ValueError(f"negative prefill length {n}")
    if not 0 <= start <= n:
        raise ValueError(f"prefill start {start} outside [0, {n}]")
    out: List[Tuple[int, int]] = []
    done = start
    big = buckets[-1]
    while n - done >= big:
        out.append((done, big))
        done += big
    if done < n:
        size = next(b for b in buckets if b >= n - done)
        if start > 0:
            out.append((done, size))            # left-aligned, padded
        else:
            out.append((max(0, n - size), size))
    return out


class Scheduler:
    """FCFS arrival queue + admission. The engine asks it two questions
    per loop: who newly fits into a free slot (`admit`), and which
    admitted request should run its next prefill chunk
    (`next_prefill`, oldest-admitted first so a burst of long prompts
    drains in arrival order while decode steps interleave).

    In paged mode admission also reserves KV pages (the binding
    resource): a request needs its worst-case page span free — minus
    whatever its prompt prefix resolves to in the cache — before it gets
    a slot. When the head of the queue doesn't fit, `admit` looks ahead
    up to `admit_lookahead` arrived requests for one whose page demand
    DOES fit (prompt-length packing): a burst of long prompts no longer
    head-of-line-blocks the short requests that would ride along in the
    pages left over. FCFS order is preserved whenever the head fits."""

    def __init__(self, chunk_buckets: Sequence[int], max_len: int,
                 admit_lookahead: int = 8, reserve: str = "full"):
        buckets = tuple(chunk_buckets)
        if not 1 <= len(buckets) <= 3:
            raise ValueError(f"chunk_buckets must have 1-3 entries "
                             f"(compiled prefill shapes), got {buckets}")
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(f"chunk_buckets must be strictly ascending, "
                             f"got {buckets}")
        if buckets[-1] > max_len:
            raise ValueError(f"largest chunk bucket {buckets[-1]} exceeds "
                             f"max_len={max_len}")
        if admit_lookahead < 1:
            raise ValueError(f"admit_lookahead must be >= 1, "
                             f"got {admit_lookahead}")
        if reserve not in ("full", "prompt"):
            raise ValueError(f"reserve must be 'full' or 'prompt', "
                             f"got {reserve!r}")
        self.chunk_buckets = buckets
        self.max_len = max_len
        self.admit_lookahead = admit_lookahead
        # "full" reserves a request's whole worst-case span at admission
        # (colocated serving: decode must never allocate mid-flight);
        # "prompt" reserves only the pages prefill will write — the
        # disaggregated PREFILL pool's mode, where the decode span is
        # the decode pool's problem (serve/engine.py PrefillEngine).
        self.reserve = reserve
        # optional admission gate: a predicate over the candidate
        # request checked before any reservation work. The
        # disaggregated facade installs the decode-pool backpressure
        # here — when the decode pool's free pages cannot absorb the
        # in-flight handoffs plus this request, the candidate stays
        # queued (lookahead still lets a smaller request behind it try,
        # the same packing rule as a failed page reservation).
        self.gate = None
        self.queue: deque[Request] = deque()
        self.active: List[RequestState] = []
        # slot-aware reserve-ahead (paged mode): page reservations made
        # while NO slot was free, keyed by request id — see admit().
        # Dies with the scheduler (engine reset() also resets the
        # allocator, so no pins leak).
        self.staged: Dict[int, Tuple[List[int], List[int], List[int]]] = {}

    def submit(self, req: Request) -> None:
        p = len(req.prompt)
        if p < 1:
            raise ValueError(f"request {req.id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(f"request {req.id}: max_new_tokens must be "
                             f">= 1")
        if p + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {req.id}: prompt ({p}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_len={self.max_len} "
                f"(the per-slot KV budget)")
        # keep the queue sorted by arrival (traces submit in order; the
        # insort tolerates out-of-order submission)
        if self.queue and req.arrival < self.queue[-1].arrival:
            items = sorted([*self.queue, req], key=lambda r: r.arrival)
            self.queue = deque(items)
        else:
            self.queue.append(req)

    def next_arrival(self) -> Optional[float]:
        return self.queue[0].arrival if self.queue else None

    @staticmethod
    def pages_needed(req: Request, page_size: int) -> int:
        """Worst-case page span of a request: prefill writes positions
        [0, P-1) and decode writes [P-1, P-1 + max_new) — the last
        written position is P-2+max_new, so the span is its page + 1."""
        return (len(req.prompt) - 2 + req.max_new_tokens) // page_size + 1

    @staticmethod
    def prompt_pages_needed(req: Request, page_size: int) -> int:
        """Prompt-only page span: prefill writes positions [0, P-1), so
        the last written position is P-2. This is what a disaggregated
        PREFILL pool reserves (reserve="prompt") — the decode span never
        touches its pages, which is exactly the capacity win of the
        split (serve/engine.py PrefillEngine)."""
        p1 = len(req.prompt) - 1
        return 0 if p1 < 1 else (p1 - 1) // page_size + 1

    def _reserve_pages(self, req: Request, allocator):
        """Try to reserve `req`'s page span: pin its cached prefix
        chain, then allocate the rest — or undo the pins and return None
        when the pool (free + evictable) can't cover it. The span is the
        worst case for this scheduler's reserve mode (full request or
        prompt only); reserving up-front is what makes the steady state
        allocation-free: a request that gets a slot can always finish
        its phase here."""
        ps = allocator.page_size
        p1 = len(req.prompt) - 1              # bonus token excluded
        full = p1 // ps                       # complete PROMPT pages
        total = (self.pages_needed(req, ps) if self.reserve == "full"
                 else self.prompt_pages_needed(req, ps))
        chain = allocator.lookup(req.prompt, full)
        if allocator.available < total - len(chain):
            for p in reversed(chain):
                allocator.release(p)
            return None
        private = [allocator.alloc() for _ in range(total - len(chain))]
        table = [allocator.TRASH] * (self.max_len // ps)
        table[:len(chain)] = chain
        table[len(chain):total] = private
        return chain, private, table

    def admit(self, free_slots: List[int], now: float,
              allocator=None) -> List[RequestState]:
        """Move arrived requests into free slots, FCFS. With a
        PageAllocator, a request is admitted only when its page span
        reserves (see `_reserve_pages`); a head that doesn't fit lets up
        to `admit_lookahead` arrived requests behind it try (packing).
        Returns the new RequestStates (also tracked in self.active).

        Slot-aware reserve-ahead (the dual of the lookahead above): when
        pages FIT but no slot is free, up to `admit_lookahead` arrived
        requests reserve their page spans NOW and park them in
        `self.staged`. Two wins: the reservation pins their cached
        prefix chains before decode-side allocations can evict them, and
        the moment a slot frees the head admits instantly — no
        reservation work on that step's critical path."""
        out = []
        while free_slots and self.queue and self.queue[0].arrival <= now:
            picked = None
            for idx, req in enumerate(self.queue):
                if idx >= self.admit_lookahead or req.arrival > now:
                    break
                if self.gate is not None and not self.gate(req):
                    continue              # backpressured; let others try
                if allocator is None:
                    picked = (idx, req, None)
                    break
                reserved = self.staged.pop(req.id, None)
                if reserved is None:
                    reserved = self._reserve_pages(req, allocator)
                if reserved is not None:
                    picked = (idx, req, reserved)
                    break
            if picked is None:
                break
            idx, req, reserved = picked
            del self.queue[idx]
            slot = free_slots.pop(0)
            p1 = len(req.prompt) - 1          # bonus token excluded
            st = RequestState(
                req=req, slot=slot, pos=0,
                chunks=plan_chunks(p1, self.chunk_buckets),
                next_input=int(req.prompt[-1]), admitted_at=now)
            if reserved is not None:
                chain, private, table = reserved
                ps = allocator.page_size
                span = len(chain) * ps        # prefix-cache hit span
                st.page_table = table
                st.owned_pages = chain + private
                st.cached_tokens = span
                st.published_pages = len(chain)
                st.publish_parent = chain[-1] if chain else -1
                st.pos = span                 # prefill starts past the hits
                st.chunks = plan_chunks(p1, self.chunk_buckets,
                                        start=span)
            self.active.append(st)
            out.append(st)
        if allocator is not None and not free_slots:
            for idx, req in enumerate(self.queue):
                if idx >= self.admit_lookahead or req.arrival > now:
                    break
                if req.id in self.staged:
                    continue
                if self.gate is not None and not self.gate(req):
                    continue
                reserved = self._reserve_pages(req, allocator)
                if reserved is not None:
                    self.staged[req.id] = reserved
        return out

    def next_prefill(self) -> Optional[RequestState]:
        for st in self.active:            # admission order = FCFS
            if st.prefilling:
                return st
        return None

    def decoding(self) -> List[RequestState]:
        return [st for st in self.active if not st.prefilling]

    def retire(self, st: RequestState) -> None:
        self.active.remove(st)

    @property
    def idle(self) -> bool:
        return not self.queue and not self.active


__all__ = ["Request", "RequestState", "Scheduler", "plan_chunks"]
