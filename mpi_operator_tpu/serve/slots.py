"""Slot and page bookkeeping for the fixed-shape serving cache.

The device cache is [SLOTS, KV, L, D] per layer (transformer.py
decode_slots mode) and NEVER changes shape: requests come and go by
host-side bookkeeping only — a freed slot is just a row whose cursor
resets, and the stale K/V it leaves behind is unreachable (every row
attends only positions <= its own cursor, and a new occupant rewrites
[0, len) before its cursor gets there). That is the whole trick that
makes admission/retirement free of recompiles.

In paged mode (EngineConfig.paged) the cache is instead a global pool of
fixed-size pages (transformer.py decode_page_size) and `PageAllocator`
here owns the physical pages: a free list, per-page refcounts, and the
prefix cache that lets requests sharing a prompt prefix resolve to the
SAME physical pages and skip prefilling them. The same junk-write
argument carries over page-by-page: a page's stale content is
unreachable until a new owner's cursor crosses it, and the owner rewrites
each position before the cursor does.

This module owns which row belongs to which request and builds the
per-step cursor/token/sampling arrays the compiled decode step consumes.
"""
from __future__ import annotations

from bisect import insort
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .scheduler import RequestState


#: chained prefix-cache key: (parent physical page id, the page's token
#: window). Chaining matters because K/V at position j depends on the
#: WHOLE token prefix (layers > 0 attend backwards), so two pages holding
#: identical tokens are interchangeable only when everything before them
#: matched too — which the parent link encodes transitively. Exact tuple
#: equality (dict keys), never a lossy hash: a collision would silently
#: serve another prompt's K/V.
PrefixKey = Tuple[int, Tuple[int, ...]]


def prefix_chain_windows(prompt: Sequence[int], page_size: int,
                         full_pages: Optional[int] = None,
                         ) -> List[Tuple[int, ...]]:
    """The page-aligned token windows of `prompt`'s complete pages — the
    token half of each chained PrefixKey, in chain order. This is the
    SINGLE source of the keying both sides of the front door use: the
    allocator's lookup/probe walk these windows against its cache, and
    the serving router scores replica affinity over the same windows —
    so a change to the keying here moves router and replica together
    (no silent divergence)."""
    if full_pages is None:
        full_pages = max(0, (len(prompt) - 1) // page_size)
    return [tuple(int(t) for t in prompt[k * page_size:(k + 1) * page_size])
            for k in range(full_pages)]


class PageAllocator:
    """Physical KV pages for the paged serving cache: a free list,
    per-page refcounts, and the prompt-prefix cache.

    Page 0 is the reserved TRASH page — unallocated page-table entries
    point at it so the fixed-shape decode/prefill programs always have a
    legal write target for masked rows; it is never handed out.

    Lifecycle of a page:
      free list ──alloc()──▶ live (ref 1) ──release()──▶
        · uncached page: straight back to the free list;
        · cached page (published prompt prefix): into the EVICTABLE LRU —
          still matchable by future lookups (pin() revives it, ref 0→1),
          reclaimed oldest-first only when alloc() finds the free list
          empty. Evicting a cached page cascades over its descendants in
          the prefix chain (they are unreachable without it) — and the
          cascade is also what keeps a recycled page id from falsely
          matching stale child keys.

    Sharing: `lookup(prompt)` walks the chained keys and PINS every page
    it matches (ref +1 per sharing request); `publish()` registers a
    fully-prefilled prompt page under its chain key. Shared pages are
    immutable by construction — only FULL prompt pages are ever
    published, and the divergence/partial page of a new request is always
    a freshly allocated private page (copy-on-write at page granularity).
    """

    TRASH = 0

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages}: need >= 2 (page 0 "
                             f"is the reserved trash sink)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.free: List[int] = list(range(1, num_pages))   # sorted
        self.ref: List[int] = [0] * num_pages
        self._cache: Dict[PrefixKey, int] = {}       # key → physical page
        self._key_of: Dict[int, PrefixKey] = {}      # published page → key
        self._children: Dict[int, set] = {}          # parent → child pages
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # ref-0 cached
        self.hits = 0          # prompt pages served from the prefix cache
        self.misses = 0        # prompt pages that had to prefill cold
        self.evictions = 0

    # -- capacity ---------------------------------------------------------

    @property
    def usable(self) -> int:
        """Pages a single request could ever hold (pool minus trash)."""
        return self.num_pages - 1

    @property
    def available(self) -> int:
        """Pages alloc() can currently produce: truly free + evictable."""
        return len(self.free) + len(self._lru)

    @property
    def in_use(self) -> int:
        """Pages referenced by live requests (pinned shared + private)."""
        return self.usable - self.available

    @property
    def cached_pages(self) -> int:
        """Ref-0 prefix-cache pages retained for future lookups."""
        return len(self._lru)

    # -- alloc / release --------------------------------------------------

    def alloc(self) -> int:
        """Hand out one private page (ref 1), evicting the oldest idle
        prefix-cache page if the free list is dry. Raises when nothing is
        free OR evictable — callers must check `available` first (the
        scheduler reserves a request's whole worst-case page span at
        admission, so allocation never fails mid-flight)."""
        if self.free:
            p = self.free.pop(0)        # lowest-first, like slot rows
        elif self._lru:
            p, _ = self._lru.popitem(last=False)
            self._evict(p)
        else:
            raise RuntimeError("out of KV pages (none free or evictable)")
        self.ref[p] = 1
        return p

    def release(self, p: int) -> None:
        """Drop one reference. At ref 0 a published page parks in the
        evictable LRU (still matchable); an unpublished one returns to
        the free list."""
        if p == self.TRASH:
            raise ValueError("released the trash page")
        if self.ref[p] <= 0:
            raise RuntimeError(f"double-free of page {p}")
        self.ref[p] -= 1
        if self.ref[p] == 0:
            if p in self._key_of:
                self._lru[p] = None     # most-recently-used end
            else:
                insort(self.free, p)

    def _evict(self, p: int) -> None:
        """Remove page p's prefix-cache entry and cascade over its
        descendants (all ref 0 — a pinned child implies a pinned parent,
        because lookups pin whole chains and publishers hold their own
        chain). Descendants go straight to the free list."""
        key = self._key_of.pop(p)
        del self._cache[key]
        self._children.get(key[0], set()).discard(p)
        self.evictions += 1
        self._cascade_children(p)

    def _cascade_children(self, p: int) -> None:
        for child in sorted(self._children.pop(p, ())):
            assert self.ref[child] == 0, \
                f"evicting page {p} with referenced child {child}"
            del self._lru[child]
            del self._cache[self._key_of.pop(child)]
            self.evictions += 1
            self._cascade_children(child)
            insort(self.free, child)

    # -- prefix cache -----------------------------------------------------

    def pin(self, p: int) -> None:
        """Take a reference on a page (reviving it from the evictable
        LRU when idle)."""
        if self.ref[p] == 0:
            del self._lru[p]
        self.ref[p] += 1

    def lookup(self, prompt: Sequence[int], full_pages: int) -> List[int]:
        """Walk the prefix chain for `prompt`'s first `full_pages`
        complete pages and PIN every match. Returns the matched chain
        (physical page ids, possibly empty); callers release() each page
        if they end up not admitting."""
        chain: List[int] = []
        parent = -1
        for window in prefix_chain_windows(prompt, self.page_size,
                                           full_pages):
            p = self._cache.get((parent, window))
            if p is None:
                break
            self.pin(p)
            chain.append(p)
            parent = p
        self.hits += len(chain)
        self.misses += full_pages - len(chain)
        return chain

    def probe(self, prompt: Sequence[int],
              full_pages: Optional[int] = None) -> int:
        """Depth of the warm prefix chain for `prompt` WITHOUT pinning
        pages or touching the hit/miss counters — the read-only variant
        of lookup() the serving router's affinity scoring uses. Walks
        the same prefix_chain_windows keying, so probe depth k promises
        a later lookup() of the same prompt at least k hit pages
        (barring eviction in between)."""
        depth = 0
        parent = -1
        for window in prefix_chain_windows(prompt, self.page_size,
                                           full_pages):
            p = self._cache.get((parent, window))
            if p is None:
                break
            depth += 1
            parent = p
        return depth

    def publish(self, page: int, parent: int,
                tokens: Sequence[int]) -> bool:
        """Register a fully-prefilled prompt page under its chain key.
        Returns False when the key is already cached (another request
        prefilled the identical prefix concurrently) — the caller's page
        stays private and the caller must stop publishing descendants
        (they would be unreachable through the cached chain)."""
        key: PrefixKey = (parent, tuple(int(t) for t in tokens))
        if key in self._cache:
            return False
        if page in self._key_of:
            raise RuntimeError(f"page {page} published twice")
        self._cache[key] = page
        self._key_of[page] = key
        self._children.setdefault(parent, set()).add(page)
        return True

    def reset(self) -> None:
        """Rewind to the freshly-constructed state: every page free, no
        refcounts, no cached prefixes (ServingEngine.reset)."""
        self.free = list(range(1, self.num_pages))
        self.ref = [0] * self.num_pages
        self._cache.clear()
        self._key_of.clear()
        self._children.clear()
        self._lru.clear()
        self.hits = self.misses = self.evictions = 0

    def check(self) -> None:
        """Invariant audit (tests): {free} ⊔ {evictable} ⊔ {ref>0}
        partitions pages 1..N-1; cache maps are mutually consistent."""
        free, lru = set(self.free), set(self._lru)
        live = {p for p in range(1, self.num_pages) if self.ref[p] > 0}
        assert not (free & lru) and not (free & live) and not (lru & live)
        assert free | lru | live == set(range(1, self.num_pages))
        assert self.ref[self.TRASH] == 0
        assert all(r >= 0 for r in self.ref)
        vals = list(self._cache.values())
        assert len(vals) == len(set(vals)), "one page under two keys"
        assert set(vals) == set(self._key_of)
        assert all(self._cache[self._key_of[p]] == p for p in self._key_of)
        assert lru <= set(self._key_of), "evictable page not published"
        for parent, kids in self._children.items():
            for c in kids:
                assert self._key_of[c][0] == parent


class SlotManager:
    """Fixed pool of `n` slots. Rows are handed out lowest-first (keeps
    small active sets contiguous — friendlier to batch-sharded caches)
    and returned on retirement."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"need at least one slot, got {n}")
        self.n = n
        self.free: List[int] = list(range(n))
        self.states: List[Optional[RequestState]] = [None] * n

    def bind(self, st: RequestState) -> None:
        if self.states[st.slot] is not None:
            raise RuntimeError(f"slot {st.slot} is already occupied")
        self.states[st.slot] = st

    def release(self, st: RequestState) -> None:
        self.states[st.slot] = None
        self.free.append(st.slot)
        self.free.sort()

    def rewind(self, slot: int, n: int,
               page_size: Optional[int] = None) -> None:
        """Roll slot's cursor back `n` positions after a speculative
        verify step rejected the tail of its writes. The rejected K/V
        stays in place as dead weight — every reader masks positions
        >= the cursor and the next write lands exactly there, so rewind
        is pure host bookkeeping (no cache mutation, no page traffic; a
        rejected span that crossed into a fresh page leaves that page
        allocated — it is still inside the request's reserved span).

        In paged mode (`page_size` given) the cursor must not drop below
        the published-page frontier: published pages are immutable prefix
        -cache entries other requests may already share, so un-publishing
        is refused loudly rather than corrupting shared state. The engine
        never trips this (decode tokens are never published), but the
        guard keeps a buggy caller from silently poisoning the cache."""
        st = self.states[slot]
        if st is None:
            raise ValueError(f"rewind on free slot {slot}")
        if n < 0:
            raise ValueError(f"rewind by negative n={n}")
        new = st.pos - n
        if new < 0:
            raise ValueError(
                f"rewind({slot}, {n}) would move the cursor to {new} < 0")
        if page_size is not None:
            floor = st.published_pages * page_size
            if new < floor:
                raise ValueError(
                    f"rewind({slot}, {n}) would un-publish: cursor {new} "
                    f"< published frontier {floor} "
                    f"({st.published_pages} pages x {page_size})")
        st.pos = new

    @property
    def occupied(self) -> int:
        return self.n - len(self.free)

    def step_arrays(self):
        """The decode step's host-built inputs: tokens, cursors, use_prev
        flags, and per-slot sampling params, plus which states actually
        consume this step's samples. Slots mid-prefill or free still get
        a row (the step is fixed-shape): their position is their own next
        write offset, so the one junk K/V they write lands exactly
        where the next real write (chunk or cursor) overwrites it, and
        their sampled token is simply discarded.

        use_prev marks rows whose input token is the PREVIOUS step's
        device output for the same slot (st.dispatched >= 1: a decoding
        slot consumes every subsequent step, so the previous step's row
        is guaranteed to be its token) — the device-side chain that lets
        the engine dispatch step N+1 before step N's tokens reach the
        host. Rows with use_prev False read the host token (the bonus
        token after prefill), as do rows whose last tokens came from a
        speculative verify step (host_next: the verify program returned
        its targets to the host, so the device-side chain token of the
        last PLAIN step is stale for this row). States that have
        dispatched all
        max_new_tokens steps stop consuming: the engine already returned
        their row to the free pool at dispatch time (slot_released), so
        a drained state still tracked here is skipped — only the final
        sync's bookkeeping remains for it."""
        toks = np.zeros((self.n,), np.int32)
        pos = np.zeros((self.n,), np.int32)
        use_prev = np.zeros((self.n,), bool)
        temps = np.zeros((self.n,), np.float32)
        top_ks = np.zeros((self.n,), np.int32)
        top_ps = np.ones((self.n,), np.float32)
        consumers: List[RequestState] = []
        for st in self.states:
            if st is None:
                continue
            if not st.prefilling and st.dispatched >= st.req.max_new_tokens:
                continue                  # drained: awaiting final sync
            pos[st.slot] = st.pos
            if st.prefilling:
                continue
            toks[st.slot] = st.next_input
            use_prev[st.slot] = st.dispatched >= 1 and not st.host_next
            temps[st.slot] = st.req.temperature
            top_ks[st.slot] = st.req.top_k
            top_ps[st.slot] = st.req.top_p
            consumers.append(st)
        return toks, pos, use_prev, temps, top_ks, top_ps, consumers


__all__ = ["PageAllocator", "SlotManager", "prefix_chain_windows"]
