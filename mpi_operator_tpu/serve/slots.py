"""Slot bookkeeping for the fixed-shape serving cache.

The device cache is [SLOTS, KV, L, D] per layer (transformer.py
decode_slots mode) and NEVER changes shape: requests come and go by
host-side bookkeeping only — a freed slot is just a row whose cursor
resets, and the stale K/V it leaves behind is unreachable (every row
attends only positions <= its own cursor, and a new occupant rewrites
[0, len) before its cursor gets there). That is the whole trick that
makes admission/retirement free of recompiles.

This module owns which row belongs to which request and builds the
per-step cursor/token/sampling arrays the compiled decode step consumes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .scheduler import RequestState


class SlotManager:
    """Fixed pool of `n` slots. Rows are handed out lowest-first (keeps
    small active sets contiguous — friendlier to batch-sharded caches)
    and returned on retirement."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"need at least one slot, got {n}")
        self.n = n
        self.free: List[int] = list(range(n))
        self.states: List[Optional[RequestState]] = [None] * n

    def bind(self, st: RequestState) -> None:
        if self.states[st.slot] is not None:
            raise RuntimeError(f"slot {st.slot} is already occupied")
        self.states[st.slot] = st

    def release(self, st: RequestState) -> None:
        self.states[st.slot] = None
        self.free.append(st.slot)
        self.free.sort()

    @property
    def occupied(self) -> int:
        return self.n - len(self.free)

    def step_arrays(self):
        """The decode step's host-built inputs: tokens, cursors, use_prev
        flags, and per-slot sampling params, plus which states actually
        consume this step's samples. Slots mid-prefill or free still get
        a row (the step is fixed-shape): their position is their own next
        write offset, so the one junk K/V they write lands exactly
        where the next real write (chunk or cursor) overwrites it, and
        their sampled token is simply discarded.

        use_prev marks rows whose input token is the PREVIOUS step's
        device output for the same slot (st.dispatched >= 1: a decoding
        slot consumes every subsequent step, so the previous step's row
        is guaranteed to be its token) — the device-side chain that lets
        the engine dispatch step N+1 before step N's tokens reach the
        host. Rows with use_prev False read the host token (the bonus
        token after prefill). States that have dispatched all
        max_new_tokens steps stop consuming: the engine already returned
        their row to the free pool at dispatch time (slot_released), so
        a drained state still tracked here is skipped — only the final
        sync's bookkeeping remains for it."""
        toks = np.zeros((self.n,), np.int32)
        pos = np.zeros((self.n,), np.int32)
        use_prev = np.zeros((self.n,), bool)
        temps = np.zeros((self.n,), np.float32)
        top_ks = np.zeros((self.n,), np.int32)
        top_ps = np.ones((self.n,), np.float32)
        consumers: List[RequestState] = []
        for st in self.states:
            if st is None:
                continue
            if not st.prefilling and st.dispatched >= st.req.max_new_tokens:
                continue                  # drained: awaiting final sync
            pos[st.slot] = st.pos
            if st.prefilling:
                continue
            toks[st.slot] = st.next_input
            use_prev[st.slot] = st.dispatched >= 1
            temps[st.slot] = st.req.temperature
            top_ks[st.slot] = st.req.top_k
            top_ps[st.slot] = st.req.top_p
            consumers.append(st)
        return toks, pos, use_prev, temps, top_ks, top_ps, consumers


__all__ = ["SlotManager"]
