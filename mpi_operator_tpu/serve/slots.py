"""Slot bookkeeping for the fixed-shape serving cache.

The device cache is [SLOTS, KV, L, D] per layer (transformer.py
decode_slots mode) and NEVER changes shape: requests come and go by
host-side bookkeeping only — a freed slot is just a row whose cursor
resets, and the stale K/V it leaves behind is unreachable (every row
attends only positions <= its own cursor, and a new occupant rewrites
[0, len) before its cursor gets there). That is the whole trick that
makes admission/retirement free of recompiles.

This module owns which row belongs to which request and builds the
per-step cursor/token/sampling arrays the compiled decode step consumes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .scheduler import RequestState


class SlotManager:
    """Fixed pool of `n` slots. Rows are handed out lowest-first (keeps
    small active sets contiguous — friendlier to batch-sharded caches)
    and returned on retirement."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"need at least one slot, got {n}")
        self.n = n
        self.free: List[int] = list(range(n))
        self.states: List[Optional[RequestState]] = [None] * n

    def bind(self, st: RequestState) -> None:
        if self.states[st.slot] is not None:
            raise RuntimeError(f"slot {st.slot} is already occupied")
        self.states[st.slot] = st

    def release(self, st: RequestState) -> None:
        self.states[st.slot] = None
        self.free.append(st.slot)
        self.free.sort()

    @property
    def occupied(self) -> int:
        return self.n - len(self.free)

    def step_arrays(self):
        """The decode step's host-built inputs: tokens, cursors, and
        per-slot sampling params, plus which states actually consume
        this step's samples. Slots mid-prefill or free still get a row
        (the step is fixed-shape): their position is their own next
        write offset, so the one junk K/V they write lands exactly
        where the next real write (chunk or cursor) overwrites it, and
        their sampled token is simply discarded."""
        toks = np.zeros((self.n,), np.int32)
        pos = np.zeros((self.n,), np.int32)
        temps = np.zeros((self.n,), np.float32)
        top_ks = np.zeros((self.n,), np.int32)
        top_ps = np.ones((self.n,), np.float32)
        consumers: List[RequestState] = []
        for st in self.states:
            if st is None:
                continue
            pos[st.slot] = st.pos
            if st.prefilling:
                continue
            toks[st.slot] = st.next_input
            temps[st.slot] = st.req.temperature
            top_ks[st.slot] = st.req.top_k
            top_ps[st.slot] = st.req.top_p
            consumers.append(st)
        return toks, pos, temps, top_ks, top_ps, consumers


__all__ = ["SlotManager"]
