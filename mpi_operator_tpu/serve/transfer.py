"""Device-to-device paged-KV handoff between serving pools.

The disaggregated engine (serve/engine.py DisaggEngine) runs prefill
and decode on SEPARATE device pools; when a prompt finishes prefilling,
its KV lives in the prefill pool's page arrays and must move into the
decode pool's. Because the paged cache layout puts the page axis first
on EVERY leaf — cached_key/cached_value are [num_pages, KV, page_size,
D] and the int8 scale planes are [num_pages, KV, page_size] — one
generic axis-0 gather/scatter over the cache pytree moves a page list
uniformly for all dtypes: int8 payloads travel WITH their scale rows,
nothing is dequantized in flight.

Three dispatches per handoff, all async:

    payload = gather(src_cache, src_ids)     # jit on the source device
    payload = jax.device_put(payload, dst)   # the actual D2D copy
    dst_cache = scatter(dst_cache, dst_ids, payload)   # jit on dest

Only OCCUPIED pages move — the caller passes the physical ids of pages
holding written prompt positions, minus any the destination resolved
from its own prefix cache (those need no bytes at all). On real
hardware the device_put rides ICI/DCN; on the CPU smoke it is a
host-memory copy between two single-device "meshes" in one process —
same program structure, same token math.

Compile discipline: a traced id-vector length is a program shape, so a
naive per-request transfer would compile one gather+scatter pair per
distinct page count. Id lists are padded to the next power of two
instead — source padding re-reads page 0 (the allocator's reserved
trash page), destination padding re-writes it, and duplicate trash
scatters are harmless because nothing ever reads trash — pinning the
compile count at ≤ log2(pool size) + 1 per direction, independent of
the trace (tests/test_disagg.py holds the pin).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..telemetry import span


def _bucket(n: int) -> int:
    """Smallest power of two >= n (>= 1)."""
    w = 1
    while w < n:
        w <<= 1
    return w


class PageTransfer:
    """Moves occupied KV pages from a source pool's cache into a
    destination pool's. Stateless apart from the two jitted programs
    and a moved-pages odometer; one instance serves every handoff of a
    DisaggEngine, so its compile caches ARE the transfer pins."""

    TRASH = 0     # PageAllocator's reserved junk page, the padding sink

    def __init__(self, src_num_pages: int, dst_num_pages: int):
        self.src_num_pages = src_num_pages
        self.dst_num_pages = dst_num_pages
        self.pages_moved = 0

        def gather(cache, ids):
            # page-pool leaves all carry the pool's page count on axis
            # 0; anything else (none today) passes through untouched
            return jax.tree.map(
                lambda x: x[ids] if x.shape[0] == src_num_pages else x,
                cache)

        def scatter(cache, ids, rows):
            return jax.tree.map(
                lambda x, r: (x.at[ids].set(r)
                              if x.shape[0] == dst_num_pages else x),
                cache, rows)

        # donating the destination cache keeps the scatter in-place on
        # real hardware; CPU jit ignores donation (and warns), so gate
        # it the same way the engine gates its decode-step donation
        donate = (0,) if jax.default_backend() in ("tpu", "gpu") else ()
        self._gather = jax.jit(gather)
        self._scatter = jax.jit(scatter, donate_argnums=donate)

    def move(self, src_cache, dst_cache, src_ids: Sequence[int],
             dst_ids: Sequence[int]) -> Tuple[object, int]:
        """Copy src_cache pages src_ids[i] -> dst_cache pages dst_ids[i]
        and return (new dst_cache, pages moved). Dispatch-async like
        every engine program: the gather captures the source buffers at
        dispatch, so the caller may release the source page REFERENCES
        immediately after this returns."""
        if len(src_ids) != len(dst_ids):
            raise ValueError(f"src/dst page lists disagree: "
                             f"{len(src_ids)} vs {len(dst_ids)}")
        n = len(src_ids)
        if n == 0:
            return dst_cache, 0
        width = _bucket(n)
        pad = [self.TRASH] * (width - n)
        sids = jnp.asarray(list(src_ids) + pad, jnp.int32)
        dids = jnp.asarray(list(dst_ids) + pad, jnp.int32)
        # the same span name the request trace's kv_handoff hop uses
        # (telemetry/trace.py taxonomy), scoped to the actual page move
        # so an XProf capture attributes gather/copy/scatter separately
        # from the install bookkeeping around it
        with span("serve.kv_handoff.move"):
            payload = self._gather(src_cache, sids)
            dst_dev = self._device_of(dst_cache)
            if dst_dev is not None:
                payload = jax.device_put(payload, dst_dev)
            dst_cache = self._scatter(dst_cache, dids, payload)
        self.pages_moved += n
        return dst_cache, n

    @staticmethod
    def _device_of(cache):
        """The destination pool's (single) device, so the payload is
        committed there before the scatter — jit would otherwise refuse
        operands committed to two different devices."""
        for leaf in jax.tree.leaves(cache):
            devs = getattr(leaf, "devices", None)
            if devs is None:
                continue
            ds = devs()
            if len(ds) == 1:
                return next(iter(ds))
        return None

    def compile_counts(self) -> Dict[str, int]:
        """Compiled program variants per direction — one per distinct
        padded width, so ≤ log2(pool size) + 1 each (the test pin)."""
        return {"gather": self._gather._cache_size(),
                "scatter": self._scatter._cache_size()}


__all__ = ["PageTransfer"]
