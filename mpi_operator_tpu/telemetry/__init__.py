"""Data-plane telemetry: metrics core, Prometheus /metrics, event log,
and XProf span annotations. See core.py for the design constraints."""
from .core import Counter, Gauge, Histogram, Registry
from .events import (EventLog, read_events, PREEMPTION_DRAIN,
                     EMERGENCY_CHECKPOINT, DIVERGENCE_ROLLBACK, INIT_RETRY,
                     SLOT_ADMIT, SLOT_RETIRE)
from .prometheus import (CONTENT_TYPE, TelemetryServer, escape_label_value,
                         format_value, histogram_lines, render_registry)
from .spans import span
from .worker import ServeTelemetry, TrainTelemetry, WorkerTelemetry

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "EventLog", "read_events", "PREEMPTION_DRAIN", "EMERGENCY_CHECKPOINT",
    "DIVERGENCE_ROLLBACK", "INIT_RETRY", "SLOT_ADMIT", "SLOT_RETIRE",
    "CONTENT_TYPE", "TelemetryServer", "escape_label_value", "format_value",
    "histogram_lines", "render_registry",
    "span",
    "ServeTelemetry", "TrainTelemetry", "WorkerTelemetry",
]
