"""Data-plane telemetry: metrics core, Prometheus /metrics, event log,
job-level collector, and XProf span annotations. See core.py for the
design constraints and collector.py for the operator-side job view."""
from .collector import (ClockSync, JobObservatory, MetricsFederation,
                        TraceFederation, goodput_ledger, merge_timeline,
                        parse_prometheus, resize_ledger, resize_lines)
from .core import Counter, Gauge, Histogram, Registry
from .events import (BoundEventLog, EventLog, read_events,
                     PREEMPTION_DRAIN, EMERGENCY_CHECKPOINT,
                     DIVERGENCE_ROLLBACK, INIT_RETRY, SLOT_ADMIT,
                     SLOT_RETIRE, CHECKPOINT_RESTORE, CHECKPOINT_SAVED,
                     CLOCK_ANCHOR, FAULT_INJECTED, REPLICA_FROZEN,
                     RUN_COMPLETE, JOB_CREATED, GANG_RESTART, PODS_READY,
                     FIRST_STEP_OBSERVED, JOB_PACKED, JOB_RESIZED,
                     GANG_RESIZE, FIRST_RESUME_STEP,
                     JOB_SUCCEEDED, JOB_FAILED)
from .prometheus import (CONTENT_TYPE, TelemetryServer, escape_label_value,
                         format_value, histogram_lines, render_registry)
from .spans import span
from .trace import (RequestTrace, SessionSpan, Tracer, build_trees,
                    hop_percentiles, read_trace_spans, render_tree)
from .worker import (
    RouterTelemetry, ServeTelemetry, TrainTelemetry, WorkerTelemetry,
)

__all__ = [
    "ClockSync", "JobObservatory", "MetricsFederation", "TraceFederation",
    "goodput_ledger",
    "merge_timeline", "parse_prometheus", "resize_ledger", "resize_lines",
    "Counter", "Gauge", "Histogram", "Registry",
    "BoundEventLog", "EventLog", "read_events",
    "PREEMPTION_DRAIN", "EMERGENCY_CHECKPOINT",
    "DIVERGENCE_ROLLBACK", "INIT_RETRY", "SLOT_ADMIT", "SLOT_RETIRE",
    "CHECKPOINT_RESTORE", "CHECKPOINT_SAVED", "CLOCK_ANCHOR",
    "FAULT_INJECTED", "REPLICA_FROZEN", "RUN_COMPLETE", "JOB_CREATED",
    "GANG_RESTART", "PODS_READY", "FIRST_STEP_OBSERVED", "JOB_PACKED",
    "JOB_RESIZED", "GANG_RESIZE", "FIRST_RESUME_STEP",
    "JOB_SUCCEEDED", "JOB_FAILED",
    "CONTENT_TYPE", "TelemetryServer", "escape_label_value", "format_value",
    "histogram_lines", "render_registry",
    "span",
    "RequestTrace", "SessionSpan", "Tracer", "build_trees",
    "hop_percentiles", "read_trace_spans", "render_tree",
    "RouterTelemetry", "ServeTelemetry", "TrainTelemetry",
    "WorkerTelemetry",
]
