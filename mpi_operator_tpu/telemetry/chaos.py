"""Deterministic data-plane fault injection for collector scrapes.

cluster/chaos.py injects faults into the CONTROL plane (API-server
verbs); this module injects them into the DATA plane: the per-pod
/metrics + /events fetches the JobObservatory makes each scrape pass.
The failure taxonomy follows what pod-scale operation actually sees
(PAPERS.md, "Exploring the limits of Concurrency in ML Training on
Google TPUs"): partial-host degradation — stragglers, flaky links,
asymmetric partitions — dominates over clean whole-job deaths.

Rule syntax mirrors cluster/chaos.py (`<verb>/<kind>=<rate>:<error>`
there): here a rule is ``<rank>/<kind>=<rate>`` where `<rank>` is a
worker rank or ``*`` and `<kind>` is one of

  fail              the fetch raises (one flaky scrape; the collector's
                    existing scrape_failed path absorbs it)
  delay             the fetch returns the PREVIOUS fetch's payload and
                    stashes the fresh one for next time (a slow link:
                    data arrives, one cycle late; the first delayed
                    fetch has nothing lagged yet and times out instead)
  stale-replay      the fetch replays the last payload this url ever
                    returned (a stuck proxy/cache: the frontier reads
                    the same step twice — must NOT look like progress)
  partition-window  the fetch raises AND opens a window: the next
                    `partition_fetches` fetches of this rank all raise
                    too (an asymmetric network partition — one rank
                    dark for a stretch while its peers keep reporting)

Determinism: one seeded random.Random, rolled once per fetch in the
collector's sorted-rank fetch order — a given (seed, rules, lifecycle
sequence) replays the identical fault sequence, which is what lets the
chaos soak print a reproducer seed that actually reproduces.

Like FaultingAPIServer, the first matching rule wins and every injected
error message carries ``(seed=N)`` so a failure in a larger harness is
attributable to its soak at a glance.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

#: the data-plane fault taxonomy (see module docstring)
SCRAPE_FAULT_KINDS = ("fail", "delay", "stale-replay", "partition-window")

#: fetches a partition-window fault keeps a rank dark for, by default —
#: long enough to span several scrape passes, short enough that a soak
#: sees the heal
DEFAULT_PARTITION_FETCHES = 3


@dataclasses.dataclass(frozen=True)
class ScrapeFaultRule:
    """``<rank>/<kind>=<rate>`` — rank ``*`` matches every rank."""
    rank: str
    kind: str
    rate: float

    def __post_init__(self):
        if self.kind not in SCRAPE_FAULT_KINDS:
            raise ValueError(
                f"unknown scrape fault kind {self.kind!r}; known: "
                f"{', '.join(SCRAPE_FAULT_KINDS)}")
        if not (self.rank == "*" or self.rank.isdigit()):
            raise ValueError(
                f"rank must be '*' or a non-negative integer, "
                f"got {self.rank!r}")
        if not (0.0 < self.rate <= 1.0):
            raise ValueError(
                f"rate must be in (0, 1], got {self.rate}")

    @classmethod
    def parse(cls, text: str) -> "ScrapeFaultRule":
        head, sep, rate = text.partition("=")
        rank, sep2, kind = head.partition("/")
        if not sep or not sep2 or not rank or not kind or not rate:
            raise ValueError(
                f"bad scrape fault rule {text!r}; want "
                f"'<rank>/<kind>=<rate>' (e.g. '*/fail=0.2', "
                f"'3/partition-window=0.05')")
        try:
            rate_f = float(rate)
        except ValueError:
            raise ValueError(f"bad rate in scrape fault rule {text!r}")
        return cls(rank=rank.strip(), kind=kind.strip(), rate=rate_f)

    def matches(self, rank: int) -> bool:
        return self.rank == "*" or int(self.rank) == rank


class ScrapeFaultInjector:
    """Seeded fault layer between the JobObservatory and its fetcher.

    The observatory calls ``fetch(rank, url, real_fetch)`` for every
    per-pod fetch; this either passes through to ``real_fetch(url)``,
    raises an injected IOError, or returns a delayed/replayed payload,
    per the rules. State (last payloads, open partition windows) is per
    injector — one injector per soak, like one FaultingAPIServer per
    harness.
    """

    def __init__(self, rules: Sequence[Union[str, ScrapeFaultRule]] = (),
                 seed: int = 0,
                 partition_fetches: int = DEFAULT_PARTITION_FETCHES):
        self.rules: Tuple[ScrapeFaultRule, ...] = tuple(
            r if isinstance(r, ScrapeFaultRule) else ScrapeFaultRule.parse(r)
            for r in rules)
        self.seed = seed
        self.rng = random.Random(seed)
        self.partition_fetches = int(partition_fetches)
        #: url -> last payload actually handed to the collector
        self._last: Dict[str, str] = {}
        #: url -> fresh payload held back by a delay fault
        self._lag: Dict[str, str] = {}
        #: rank -> failing fetches remaining in its partition window
        self._partition: Dict[int, int] = {}
        #: (rank, kind) -> injections, the soak-report evidence that the
        #: configured mix actually fired (mirrors FaultingAPIServer)
        self.faults_injected: Dict[Tuple[int, str], int] = {}

    # -- bookkeeping ------------------------------------------------------

    def _count(self, rank: int, kind: str) -> None:
        key = (rank, kind)
        self.faults_injected[key] = self.faults_injected.get(key, 0) + 1

    def fault_count(self, kind: Optional[str] = None) -> int:
        """Total injections, optionally restricted to one kind."""
        return sum(n for (_, k), n in self.faults_injected.items()
                   if kind is None or k == kind)

    def partitioned_ranks(self) -> List[int]:
        """Ranks whose partition window is currently open."""
        return sorted(r for r, n in self._partition.items() if n > 0)

    def _roll(self, rank: int) -> Optional[str]:
        for rule in self.rules:
            if rule.matches(rank) and self.rng.random() < rule.rate:
                return rule.kind
        return None

    # -- the fetch wrapper ------------------------------------------------

    def fetch(self, rank: int, url: str,
              real_fetch: Callable[[str], str]) -> str:
        """One per-pod fetch, faults applied. An OPEN partition window
        dominates any roll (the rank is dark, full stop); otherwise the
        first matching rule that fires decides the fault."""
        left = self._partition.get(rank, 0)
        if left > 0:
            self._partition[rank] = left - 1
            self._count(rank, "partition-window")
            raise IOError(
                f"injected: rank {rank} partitioned, {url} unreachable "
                f"(seed={self.seed})")
        kind = self._roll(rank)
        if kind == "fail":
            self._count(rank, "fail")
            raise IOError(
                f"injected: scrape of rank {rank} failed ({url}) "
                f"(seed={self.seed})")
        if kind == "partition-window":
            self._partition[rank] = self.partition_fetches
            self._count(rank, "partition-window")
            raise IOError(
                f"injected: rank {rank} partition window opened "
                f"({self.partition_fetches} fetches dark) "
                f"(seed={self.seed})")
        if kind == "stale-replay" and url in self._last:
            # replay WITHOUT refreshing _last: consecutive stale-replays
            # keep serving the same snapshot, like a genuinely stuck
            # cache would
            self._count(rank, "stale-replay")
            return self._last[url]
        if kind == "delay":
            # the slow link still delivers: hold the fresh payload back
            # one cycle and serve the previously held one. First delay
            # on a url has nothing held yet — that one times out.
            fresh = real_fetch(url)
            lagged = self._lag.pop(url, None)
            self._lag[url] = fresh
            self._count(rank, "delay")
            if lagged is None:
                raise IOError(
                    f"injected: scrape of rank {rank} timed out ({url}) "
                    f"(seed={self.seed})")
            self._last[url] = lagged
            return lagged
        text = real_fetch(url)
        self._last[url] = text
        return text


__all__ = ["DEFAULT_PARTITION_FETCHES", "SCRAPE_FAULT_KINDS",
           "ScrapeFaultInjector", "ScrapeFaultRule"]
