"""Deterministic data-plane fault injection for collector scrapes.

cluster/chaos.py injects faults into the CONTROL plane (API-server
verbs); this module injects them into the DATA plane: the per-pod
/metrics + /events fetches the JobObservatory makes each scrape pass.
The failure taxonomy follows what pod-scale operation actually sees
(PAPERS.md, "Exploring the limits of Concurrency in ML Training on
Google TPUs"): partial-host degradation — stragglers, flaky links,
asymmetric partitions — dominates over clean whole-job deaths.

Rule syntax mirrors cluster/chaos.py (`<verb>/<kind>=<rate>:<error>`
there): here a rule is ``<rank>/<kind>=<rate>`` where `<rank>` is a
worker rank or ``*`` and `<kind>` is one of

  fail              the fetch raises (one flaky scrape; the collector's
                    existing scrape_failed path absorbs it)
  delay             the fetch returns the PREVIOUS fetch's payload and
                    stashes the fresh one for next time (a slow link:
                    data arrives, one cycle late; the first delayed
                    fetch has nothing lagged yet and times out instead)
  stale-replay      the fetch replays the last payload this url ever
                    returned (a stuck proxy/cache: the frontier reads
                    the same step twice — must NOT look like progress)
  partition-window  the fetch raises AND opens a window: the next
                    `partition_fetches` fetches of this rank all raise
                    too (an asymmetric network partition — one rank
                    dark for a stretch while its peers keep reporting)

A rule may carry a time-varying **burst** modifier —
``<rank>/<kind>=<rate>:burst:<period>/<duty>`` — which turns the flat
rate into a square wave over the rank's own fetch count: within every
window of ``period`` fetches the rule is live for the first
``duty * period`` fetches (rate applies) and silent for the rest (rate
0). A soak under ``*/fail=0.6:burst:8/0.25`` therefore oscillates
between fault storms and calm stretches, which is exactly the shape
that exercises lease re-arm paths: a lease must survive the storm
without a false-positive expiry AND re-arm promptly in the calm.

Determinism: one seeded random.Random, rolled once per matching live
rule in the collector's sorted-rank fetch order, plus per-rank fetch
counters that advance on every fetch — a given (seed, rules, lifecycle
sequence) replays the identical fault sequence AND burst phasing, which
is what lets the chaos soak print a reproducer seed that actually
reproduces.

Like FaultingAPIServer, the first matching rule wins and every injected
error message carries ``(seed=N)`` so a failure in a larger harness is
attributable to its soak at a glance. Burst-windowed injections
additionally name their window index — ``(seed=N, burst=W)`` — because
a seed alone pins the roll sequence but not WHICH oscillation the fault
landed in; with the index a reproducer can fast-forward straight to the
offending burst instead of replaying the whole soak.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

#: the data-plane fault taxonomy (see module docstring)
SCRAPE_FAULT_KINDS = ("fail", "delay", "stale-replay", "partition-window")

#: fetches a partition-window fault keeps a rank dark for, by default —
#: long enough to span several scrape passes, short enough that a soak
#: sees the heal
DEFAULT_PARTITION_FETCHES = 3


@dataclasses.dataclass(frozen=True)
class ScrapeFaultRule:
    """``<rank>/<kind>=<rate>[:burst:<period>/<duty>]`` — rank ``*``
    matches every rank. With a burst modifier the rule is only live
    during the leading ``duty`` fraction of every ``period``-fetch
    window of the rank's own fetch count (a square wave; see module
    docstring)."""
    rank: str
    kind: str
    rate: float
    burst_period: Optional[int] = None
    burst_duty: Optional[float] = None

    def __post_init__(self):
        if self.kind not in SCRAPE_FAULT_KINDS:
            raise ValueError(
                f"unknown scrape fault kind {self.kind!r}; known: "
                f"{', '.join(SCRAPE_FAULT_KINDS)}")
        if not (self.rank == "*" or self.rank.isdigit()):
            raise ValueError(
                f"rank must be '*' or a non-negative integer, "
                f"got {self.rank!r}")
        if not (0.0 < self.rate <= 1.0):
            raise ValueError(
                f"rate must be in (0, 1], got {self.rate}")
        if (self.burst_period is None) != (self.burst_duty is None):
            raise ValueError(
                "burst_period and burst_duty must be set together")
        if self.burst_period is not None:
            if self.burst_period < 2:
                raise ValueError(
                    f"burst period must be >= 2 fetches, "
                    f"got {self.burst_period}")
            if not (0.0 < self.burst_duty < 1.0):
                raise ValueError(
                    f"burst duty must be in (0, 1), got {self.burst_duty} "
                    f"(duty 1 is just a flat rate — drop the modifier)")

    @classmethod
    def parse(cls, text: str) -> "ScrapeFaultRule":
        head, sep, tail = text.partition("=")
        rank, sep2, kind = head.partition("/")
        rate, _, modifier = tail.partition(":")
        if not sep or not sep2 or not rank or not kind or not rate:
            raise ValueError(
                f"bad scrape fault rule {text!r}; want "
                f"'<rank>/<kind>=<rate>[:burst:<period>/<duty>]' "
                f"(e.g. '*/fail=0.2', '3/partition-window=0.05', "
                f"'*/fail=0.6:burst:8/0.25')")
        try:
            rate_f = float(rate)
        except ValueError:
            raise ValueError(f"bad rate in scrape fault rule {text!r}")
        period = duty = None
        if modifier:
            mkind, _, spec = modifier.partition(":")
            p_s, psep, d_s = spec.partition("/")
            if mkind != "burst" or not psep or not p_s or not d_s:
                raise ValueError(
                    f"bad modifier in scrape fault rule {text!r}; want "
                    f":burst:<period>/<duty> (e.g. ':burst:8/0.25')")
            try:
                period, duty = int(p_s), float(d_s)
            except ValueError:
                raise ValueError(
                    f"bad burst period/duty in scrape fault rule {text!r}")
        return cls(rank=rank.strip(), kind=kind.strip(), rate=rate_f,
                   burst_period=period, burst_duty=duty)

    def matches(self, rank: int) -> bool:
        return self.rank == "*" or int(self.rank) == rank

    # -- burst phasing ----------------------------------------------------

    def burst_index(self, fetch_index: int) -> Optional[int]:
        """Which oscillation window a fetch lands in (None: no burst)."""
        if self.burst_period is None:
            return None
        return fetch_index // self.burst_period

    def live(self, fetch_index: int) -> bool:
        """Whether the rule's rate applies at this fetch of the rank.
        Rules without a burst modifier are always live; burst rules are
        live for the leading ceil-free ``duty * period`` fetches of each
        window (at least one fetch per window, by the duty bounds)."""
        if self.burst_period is None:
            return True
        phase = fetch_index % self.burst_period
        return phase < self.burst_duty * self.burst_period


class ScrapeFaultInjector:
    """Seeded fault layer between the JobObservatory and its fetcher.

    The observatory calls ``fetch(rank, url, real_fetch)`` for every
    per-pod fetch; this either passes through to ``real_fetch(url)``,
    raises an injected IOError, or returns a delayed/replayed payload,
    per the rules. State (last payloads, open partition windows) is per
    injector — one injector per soak, like one FaultingAPIServer per
    harness.
    """

    def __init__(self, rules: Sequence[Union[str, ScrapeFaultRule]] = (),
                 seed: int = 0,
                 partition_fetches: int = DEFAULT_PARTITION_FETCHES):
        self.rules: Tuple[ScrapeFaultRule, ...] = tuple(
            r if isinstance(r, ScrapeFaultRule) else ScrapeFaultRule.parse(r)
            for r in rules)
        self.seed = seed
        self.rng = random.Random(seed)
        self.partition_fetches = int(partition_fetches)
        #: url -> last payload actually handed to the collector
        self._last: Dict[str, str] = {}
        #: url -> fresh payload held back by a delay fault
        self._lag: Dict[str, str] = {}
        #: rank -> failing fetches remaining in its partition window
        self._partition: Dict[int, int] = {}
        #: rank -> fetches seen, the clock burst phasing runs on
        self._fetches: Dict[int, int] = {}
        #: (rank, kind) -> injections, the soak-report evidence that the
        #: configured mix actually fired (mirrors FaultingAPIServer)
        self.faults_injected: Dict[Tuple[int, str], int] = {}
        #: (rank, burst window index) pairs that actually injected — a
        #: soak asserts len(set of windows) >= 2 to prove the oscillation
        #: spanned storms, not one lucky streak
        self.bursts_fired: List[Tuple[int, int]] = []

    # -- bookkeeping ------------------------------------------------------

    def _count(self, rank: int, kind: str) -> None:
        key = (rank, kind)
        self.faults_injected[key] = self.faults_injected.get(key, 0) + 1

    def fault_count(self, kind: Optional[str] = None) -> int:
        """Total injections, optionally restricted to one kind."""
        return sum(n for (_, k), n in self.faults_injected.items()
                   if kind is None or k == kind)

    def partitioned_ranks(self) -> List[int]:
        """Ranks whose partition window is currently open."""
        return sorted(r for r, n in self._partition.items() if n > 0)

    def burst_windows_hit(self) -> int:
        """Distinct (rank, window index) bursts that actually injected."""
        return len(set(self.bursts_fired))

    def _roll(self, rank: int,
              fetch_index: int) -> Tuple[Optional[str], Optional[int]]:
        """(kind, burst window index) of the first rule that fires, or
        (None, None). The rng is only rolled for LIVE rules so a burst
        rule's silent phase consumes no randomness — phasing and rolls
        stay independently reproducible."""
        for rule in self.rules:
            if not (rule.matches(rank) and rule.live(fetch_index)):
                continue
            if self.rng.random() < rule.rate:
                burst = rule.burst_index(fetch_index)
                if burst is not None:
                    self.bursts_fired.append((rank, burst))
                return rule.kind, burst
        return None, None

    def _tag(self, burst: Optional[int]) -> str:
        """The reproducer suffix every injected message carries: the
        seed always, plus the burst window index when the fault came
        from an oscillating rule (a seed pins the roll sequence; the
        index pins WHICH storm, so a reproducer can skip straight
        there)."""
        if burst is None:
            return f"(seed={self.seed})"
        return f"(seed={self.seed}, burst={burst})"

    # -- the fetch wrapper ------------------------------------------------

    def fetch(self, rank: int, url: str,
              real_fetch: Callable[[str], str]) -> str:
        """One per-pod fetch, faults applied. An OPEN partition window
        dominates any roll (the rank is dark, full stop); otherwise the
        first matching live rule that fires decides the fault."""
        fetch_index = self._fetches.get(rank, 0)
        self._fetches[rank] = fetch_index + 1
        left = self._partition.get(rank, 0)
        if left > 0:
            self._partition[rank] = left - 1
            self._count(rank, "partition-window")
            raise IOError(
                f"injected: rank {rank} partitioned, {url} unreachable "
                f"{self._tag(None)}")
        kind, burst = self._roll(rank, fetch_index)
        if kind == "fail":
            self._count(rank, "fail")
            raise IOError(
                f"injected: scrape of rank {rank} failed ({url}) "
                f"{self._tag(burst)}")
        if kind == "partition-window":
            self._partition[rank] = self.partition_fetches
            self._count(rank, "partition-window")
            raise IOError(
                f"injected: rank {rank} partition window opened "
                f"({self.partition_fetches} fetches dark) "
                f"{self._tag(burst)}")
        if kind == "stale-replay" and url in self._last:
            # replay WITHOUT refreshing _last: consecutive stale-replays
            # keep serving the same snapshot, like a genuinely stuck
            # cache would
            self._count(rank, "stale-replay")
            return self._last[url]
        if kind == "delay":
            # the slow link still delivers: hold the fresh payload back
            # one cycle and serve the previously held one. First delay
            # on a url has nothing held yet — that one times out.
            fresh = real_fetch(url)
            lagged = self._lag.pop(url, None)
            self._lag[url] = fresh
            self._count(rank, "delay")
            if lagged is None:
                raise IOError(
                    f"injected: scrape of rank {rank} timed out ({url}) "
                    f"{self._tag(burst)}")
            self._last[url] = lagged
            return lagged
        text = real_fetch(url)
        self._last[url] = text
        return text


__all__ = ["DEFAULT_PARTITION_FETCHES", "SCRAPE_FAULT_KINDS",
           "ScrapeFaultInjector", "ScrapeFaultRule"]
