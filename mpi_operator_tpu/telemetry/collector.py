"""Controller-side observability collector: metrics federation, merged
event timelines, and restart-aware goodput — the job-level view.

PR 5 gave every *process* a `/metrics` endpoint and an fsync'd
events.jsonl; a gang is N pods plus a controller. This module is the
operator-side half that turns N per-process views into one per-job
view:

* `parse_prometheus` / `MetricsFederation` — scrape each worker pod's
  exposition text and re-export aggregated ``tpu_job_*`` series
  (counters summed, gauges max'd or summed by semantics, histograms
  bucket-merged at the shared log-spaced edges) with ``job`` labels,
  plus per-pod ``tpu_job_up`` / ``tpu_job_scrape_staleness_seconds`` /
  ``tpu_job_scrape_failures_total`` meta-series so a dead worker is
  visible, not invisible.

* `ClockSync` / `merge_timeline` — merge controller + worker event
  records by ``ts`` with per-host clock-offset correction. The offset
  is anchored at bootstrap: each worker emits a `clock_anchor` event
  with a fresh ``boot_id``, and the /events pull ships a server-side
  ``now`` stamp; offset = controller_now − worker_now is pinned once
  per boot_id so a mid-run scrape hiccup cannot re-skew history.

* `goodput_ledger` — every executed step is either useful or lost.
  A `checkpoint_restore` after which work had already advanced past
  the restored step charges ``last observed step − restore step`` to
  the lost column (the gang re-executes them); divergence rollbacks
  charge ``from_step − to_step`` (same rule, intra-process). Goodput
  is useful / (useful + lost).

* `JobObservatory` — the stateful controller attachment: its own
  EventLog (job_created, gang_restart, pods_ready, packed/resize,
  first_step_observed, terminal), the scrape loop, and
  ``<job>/timeline.jsonl`` writing.

Also a CLI for harness use (scripts/tier1.sh --resilience plays the
controller's role out-of-process):

    python -m mpi_operator_tpu.telemetry.collector emit  --log L --job J EVENT [k=v ...]
    python -m mpi_operator_tpu.telemetry.collector merge --job J --controller L \
        [--worker HOST=PATH ...] [--offset HOST=SECS ...] \
        --out timeline.jsonl [--metrics-out federated.prom]
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import re
import sys
import time
import urllib.parse
import urllib.request
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import events as ev
from .events import EventLog, _env_int, read_events, rotate_chain
from .prometheus import escape_label_value, format_value
from .trace import (REQUEST_ROOT, SPAN, TRACE_HOP_BUCKETS, build_trees,
                    hop_name)

logger = logging.getLogger("mpi_operator_tpu.telemetry.collector")

WORKER_PREFIX = "tpu_worker_"
ROUTER_PREFIX = "tpu_router_"
JOB_PREFIX = "tpu_job_"

# timeline.jsonl size cap (0/unset = the historical full-rewrite mode).
# Capped mode switches write_timeline to incremental appends rotated
# through the SAME .N chain events.py uses, so event_files/read_events
# (and postmortem.read_timeline) span the generations transparently.
ENV_TIMELINE_MAX_BYTES = "TPU_TIMELINE_MAX_BYTES"
ENV_TIMELINE_KEEP = "TPU_TIMELINE_KEEP"

# Fields that carry a global-step position; the running max across a
# merged timeline is "the furthest the gang has ever trained" — the
# useful-step frontier the goodput ledger charges restores against.
STEP_FIELDS = ("step", "from_step", "to_step", "last_observed_step")


# ---------------------------------------------------------------------------
# exposition-format parsing
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")


def parse_prometheus(text: str) -> Tuple[List[Tuple[str, Dict[str, str],
                                                    float]],
                                         Dict[str, str]]:
    """Parse exposition 0.0.4 text into (samples, types).

    samples: [(name, labels, value)]; types: metric name -> kind from
    the ``# TYPE`` comments (histogram base names, not _bucket/_sum).
    Unparseable lines are skipped — federation of a half-written scrape
    should degrade, not abort."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelblob, raw = m.groups()
        labels = ({k: _unescape(v) for k, v in _LABEL_RE.findall(labelblob)}
                  if labelblob else {})
        try:
            value = float(raw)
        except ValueError:
            continue
        samples.append((name, labels, value))
    return samples, types


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------

# Gauges whose job-level meaning is a SUM across pods (rates, occupancy);
# everything else federates as MAX (steps, ratios, watermarks).
_SUM_GAUGE_SUFFIXES = ("_per_sec",)
_SUM_GAUGE_MARKERS = ("queue_depth", "slot", "kv_pages", "batch_size")


def _gauge_is_summed(name: str) -> bool:
    return (name.endswith(_SUM_GAUGE_SUFFIXES)
            or any(m in name for m in _SUM_GAUGE_MARKERS))


def _fed_out(name: str) -> Optional[str]:
    """Federated output name for a scraped series, or None when the
    series does not federate. ``tpu_worker_X`` → ``tpu_job_X``;
    ``tpu_router_X`` → ``tpu_job_router_X`` (the front door is one
    process, not a gang member — keeping its series in their own
    ``router_`` namespace means a fleet's queue_wait can never collide
    with a worker series of the same name)."""
    if name.startswith(WORKER_PREFIX):
        return JOB_PREFIX + name[len(WORKER_PREFIX):]
    if name.startswith(ROUTER_PREFIX):
        return JOB_PREFIX + "router_" + name[len(ROUTER_PREFIX):]
    return None


def _lkey(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))


def _hist_base(name: str, types: Dict[str, str]) -> Optional[str]:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if types.get(base) == "histogram":
                return base
    return None


class MetricsFederation:
    """Aggregate per-pod scrapes of one job into ``tpu_job_*`` series.

    Feed the latest scrape per replica_rank via ingest(); render() emits
    the aggregate plus per-pod scrape-health meta-series. Only
    ``tpu_worker_*`` and ``tpu_router_*`` names federate (the latter as
    ``tpu_job_router_*``) — operator and meta series are not
    re-aggregated."""

    def __init__(self, job: str, clock: Callable[[], float] = time.time,
                 extra_labels: Optional[Dict[str, str]] = None):
        self.job = job
        self.clock = clock
        self.extra_labels = dict(extra_labels or {})
        # rank -> {"samples", "types", "last_success", "first_attempt",
        #          "failures", "ok"}
        self.pods: Dict[int, Dict] = {}

    def _pod(self, rank: int) -> Dict:
        return self.pods.setdefault(rank, {
            "samples": [], "types": {}, "last_success": None,
            "first_attempt": self.clock(), "failures": 0, "ok": False})

    def ingest(self, rank: int, text: str) -> None:
        pod = self._pod(rank)
        samples, types = parse_prometheus(text)
        pod["samples"], pod["types"] = samples, types
        pod["last_success"] = self.clock()
        pod["ok"] = True

    def scrape_failed(self, rank: int) -> None:
        pod = self._pod(rank)
        pod["failures"] += 1
        pod["ok"] = False

    def observed_step(self) -> int:
        """Max step frontier visible in the latest scrapes (live step
        gauge or last checkpointed step, whichever is further)."""
        best = 0
        for pod in self.pods.values():
            for name, _labels, value in pod["samples"]:
                if name in (WORKER_PREFIX + "step",
                            WORKER_PREFIX + "last_checkpoint_step"):
                    best = max(best, int(value))
        return best

    def observed_tokens(self) -> int:
        """Serving progress frontier: retired requests + emitted tokens
        (ServeTelemetry counters) summed across pods and label sets
        (per-pool labels included). Counters only grow per pod and a
        partitioned pod's last-known counts are RETAINED, so a partial
        partition can never move this frontier backward — and pure
        scrape flakiness can never advance it."""
        total = 0.0
        for pod in self.pods.values():
            for name, _labels, value in pod["samples"]:
                if name in (WORKER_PREFIX + "requests_total",
                            WORKER_PREFIX + "tokens_total"):
                    total += value
        return int(total)

    def unreachable_ranks(self) -> List[int]:
        """Ranks whose LATEST scrape attempt failed — the partial-
        partition evidence. A rank that has never been scraped at all
        is absent (no attempt, no verdict)."""
        return sorted(r for r, p in self.pods.items() if not p["ok"])

    def histogram_quantile(self, base: str, q: float) -> Optional[float]:
        """Bucket-walk quantile over the federated histogram `base`
        (scraped-side name, e.g. ``tpu_worker_ttft_seconds``), label
        sets merged. Returns the upper bound of the first cumulative
        bucket covering the target rank — the conservative (over-)
        estimate an SLO comparison wants — or None when the histogram
        is empty or every observation landed in +Inf."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        _counters, _gauges, hists, _kinds = self._aggregate()
        buckets: Dict[str, float] = {}
        for (name, _lk), h in hists.items():
            if name != base:
                continue
            for le, v in h["buckets"].items():
                buckets[le] = buckets.get(le, 0.0) + v
        total = buckets.get("+Inf", 0.0)
        if total <= 0:
            return None
        target = q * total
        for le in sorted(buckets, key=self._le_sort_key):
            if buckets[le] >= target and le != "+Inf":
                return float(le)
        return None

    def gauge_value(self, name: str) -> Optional[float]:
        """The federated value of one gauge (scraped-side name), label
        sets folded with the same SUM/MAX rule _aggregate applies
        across pods. None when no pod reported it."""
        _counters, gauges, _hists, _kinds = self._aggregate()
        vals = [v for (n, _lk), v in gauges.items() if n == name]
        if not vals:
            return None
        return sum(vals) if _gauge_is_summed(name) else max(vals)

    def _aggregate(self):
        counters: Dict[Tuple, float] = {}
        gauges: Dict[Tuple, float] = {}
        hists: Dict[Tuple, Dict] = {}
        kinds: Dict[str, str] = {}
        for pod in self.pods.values():
            types = pod["types"]
            for name, labels, value in pod["samples"]:
                base = _hist_base(name, types)
                if base is not None:
                    if _fed_out(base) is None:
                        continue
                    key = (base, _lkey(labels))
                    h = hists.setdefault(key, {"buckets": {}, "sum": 0.0,
                                               "count": 0.0})
                    if name.endswith("_bucket"):
                        le = labels.get("le", "+Inf")
                        h["buckets"][le] = h["buckets"].get(le, 0.0) + value
                    elif name.endswith("_sum"):
                        h["sum"] += value
                    else:
                        h["count"] += value
                    kinds[base] = "histogram"
                    continue
                if _fed_out(name) is None:
                    continue
                kind = types.get(name, "gauge")
                key = (name, _lkey(labels))
                if kind == "counter":
                    counters[key] = counters.get(key, 0.0) + value
                    kinds[name] = "counter"
                else:
                    if _gauge_is_summed(name):
                        gauges[key] = gauges.get(key, 0.0) + value
                    else:
                        gauges[key] = max(gauges.get(key, float("-inf")),
                                          value)
                    kinds[name] = "gauge"
        return counters, gauges, hists, kinds

    def _out_labels(self, lkey: Tuple,
                    extra: Optional[Dict] = None) -> str:
        merged = {"job": self.job, **self.extra_labels, **dict(lkey)}
        if extra:
            merged.update(extra)
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in merged.items())
        return "{" + inner + "}"

    @staticmethod
    def _le_sort_key(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    def render_lines(self) -> List[str]:
        counters, gauges, hists, kinds = self._aggregate()
        lines: List[str] = []
        seen = set()

        def head(out_name: str, kind: str, src: str):
            if out_name not in seen:
                seen.add(out_name)
                lines.append(f"# HELP {out_name} federated from "
                             f"{src} across the gang")
                lines.append(f"# TYPE {out_name} {kind}")

        for (name, lkey), value in sorted(counters.items()):
            out = _fed_out(name)
            head(out, "counter", name)
            lines.append(f"{out}{self._out_labels(lkey)} "
                         f"{format_value(value)}")
        for (name, lkey), value in sorted(gauges.items()):
            out = _fed_out(name)
            head(out, "gauge", name)
            lines.append(f"{out}{self._out_labels(lkey)} "
                         f"{format_value(value)}")
        for (base, lkey), h in sorted(hists.items()):
            out = _fed_out(base)
            head(out, "histogram", base)
            for le in sorted(h["buckets"], key=self._le_sort_key):
                lines.append(f"{out}_bucket"
                             f"{self._out_labels(lkey, {'le': le})} "
                             f"{format_value(h['buckets'][le])}")
            lines.append(f"{out}_sum{self._out_labels(lkey)} "
                         f"{format_value(h['sum'])}")
            lines.append(f"{out}_count{self._out_labels(lkey)} "
                         f"{format_value(h['count'])}")

        # per-pod scrape health: a dead worker must be VISIBLE
        meta = [("tpu_job_up",
                 "gauge", "last scrape of this pod succeeded",
                 lambda p: 1 if p["ok"] else 0),
                ("tpu_job_scrape_staleness_seconds",
                 "gauge", "seconds since this pod was last scraped ok",
                 lambda p: round(self.clock() - (p["last_success"]
                                                 or p["first_attempt"]), 3)),
                ("tpu_job_scrape_failures_total",
                 "counter", "failed scrapes of this pod",
                 lambda p: p["failures"])]
        for name, kind, help_text, fn in meta:
            if not self.pods:
                continue
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for rank in sorted(self.pods):
                lines.append(
                    f"{name}"
                    f"{self._out_labels((), {'replica_rank': str(rank)})}"
                    f" {format_value(fn(self.pods[rank]))}")
        if self.pods:
            # job-level partition gauge: how many ranks the collector
            # currently cannot reach (0 = fully connected). The per-rank
            # tpu_job_up series above names WHICH; this is the one number
            # an alert rule wants.
            down = len(self.unreachable_ranks())
            lines.append("# HELP tpu_job_partitioned_ranks worker ranks "
                         "currently unreachable to the collector")
            lines.append("# TYPE tpu_job_partitioned_ranks gauge")
            lines.append(f"tpu_job_partitioned_ranks{self._out_labels(())}"
                         f" {down}")
        return lines


# ---------------------------------------------------------------------------
# clock-offset correction + timeline merge
# ---------------------------------------------------------------------------

class ClockSync:
    """Per-host clock offsets, pinned once per worker boot.

    note() is called on every successful /events pull with the pull's
    local receive time, the worker's self-reported ``now``, and the
    boot_id of the newest `clock_anchor` record in the payload. The
    offset (local − remote) is (re)pinned only when the boot_id changes
    — a restarted pod gets a fresh anchor; a jittery scrape does not
    re-skew already-merged history."""

    def __init__(self):
        self.offsets: Dict[str, float] = {}
        self.boot_ids: Dict[str, Optional[str]] = {}

    def note(self, host: str, local_now: float, remote_now: float,
             boot_id: Optional[str] = None) -> float:
        if host not in self.offsets or self.boot_ids.get(host) != boot_id:
            self.offsets[host] = local_now - remote_now
            self.boot_ids[host] = boot_id
        return self.offsets[host]

    def offset(self, host: str) -> float:
        return self.offsets.get(host, 0.0)


def latest_boot_id(records: Iterable[Dict]) -> Optional[str]:
    boot = None
    for rec in records:
        if rec.get("event") == ev.CLOCK_ANCHOR and "boot_id" in rec:
            boot = rec["boot_id"]
    return boot


def merge_timeline(sources: List[Tuple[Optional[str], List[Dict]]],
                   offsets: Optional[Dict[str, float]] = None,
                   out_path: Optional[str] = None) -> List[Dict]:
    """Merge per-source event records into one ts-ordered timeline.

    ``sources`` is [(host, records)]; host None/"controller" records are
    the reference clock and pass through unshifted. Worker records get
    their host's offset added; the original stamp is preserved as
    ``ts_raw`` (plus ``clock_offset``) so a postmortem can always see
    what the host itself believed. Every record gains a ``host`` field.
    Returns the merged list; optionally writes it as JSONL."""
    offsets = offsets or {}
    merged: List[Dict] = []
    for host, records in sources:
        off = offsets.get(host, 0.0) if host else 0.0
        for rec in records:
            out = dict(rec)
            out["host"] = host or "controller"
            if off and "ts" in out:
                out["ts_raw"] = out["ts"]
                out["clock_offset"] = round(off, 3)
                out["ts"] = round(out["ts"] + off, 3)
            merged.append(out)
    merged.sort(key=lambda r: (r.get("ts", 0.0)))
    if out_path:
        parent = os.path.dirname(out_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for rec in merged:
                fh.write(json.dumps(rec) + "\n")
        os.replace(tmp, out_path)
    return merged


# ---------------------------------------------------------------------------
# cross-pod request-trace federation
# ---------------------------------------------------------------------------

class TraceFederation:
    """Per-job span-record federation: cross-pod trace trees, hop-latency
    histograms, and slowest-trace exemplars.

    ingest() takes one pod's batch of span records (telemetry/trace.py
    schema, straight from traces.jsonl / a /traces pull / a push report)
    plus that pod's clock offset from the SAME ClockSync the event
    timeline uses, so a span's wall ``ts`` lands on the controller clock.
    Re-ingesting a file every scrape is the normal mode — dedup is by
    (pod, trace, span), so repeated pulls are idempotent and a replayed
    failover span (same ids, emitted once by construction) can never
    double-count a hop.

    Hop durations come from the span's own ``seconds`` (one monotonic
    session clock per pod — no correction needed); only cross-pod
    ORDERING uses the corrected wall stamp. Aggregates:

    * ``tpu_job_trace_hop_seconds{hop=...}`` histograms over the shared
      TRACE_HOP_BUCKETS edges, one label set per hop name
    * slowest-K completed request traces in the trailing ``window``
      seconds, the SLO-breach exemplar pool (``slowest_trace()``)
    """

    EXEMPLAR_K = 5

    def __init__(self, job: str, clock: Callable[[], float] = time.time,
                 window: float = 600.0,
                 extra_labels: Optional[Dict[str, str]] = None):
        self.job = job
        self.clock = clock
        self.window = float(window)
        self.extra_labels = dict(extra_labels or {})
        self._seen: set = set()
        #: trace id -> every span record federated for it (pod-stamped)
        self.spans: Dict[int, List[Dict]] = {}
        #: hop name -> {"buckets": [per TRACE_HOP_BUCKETS edge], "sum",
        #: "count"} — cumulative render happens at render_lines time
        self.hops: Dict[str, Dict] = {}
        #: [(root seconds, trace id, arrival wall ts)] slowest-first
        self._exemplars: List[Tuple[float, int, float]] = []

    def ingest(self, pod: str, records: Iterable[Dict],
               offset: float = 0.0) -> int:
        """Fold one pod's span batch in; returns the count of NEW spans
        (already-seen ids skip everything, including the histograms)."""
        fresh = 0
        for rec in records:
            if rec.get("event") != SPAN:
                continue
            trace, span_id = rec.get("trace"), rec.get("span")
            key = (pod, trace, span_id)
            if trace is None or span_id is None or key in self._seen:
                continue
            self._seen.add(key)
            fresh += 1
            out = dict(rec)
            out["pod"] = pod
            if offset and "ts" in out:
                out["ts_raw"] = out["ts"]
                out["ts"] = round(out["ts"] + offset, 3)
            self.spans.setdefault(trace, []).append(out)
            if trace < 0:           # session spans carry no request hops
                continue
            if out.get("parent") is not None:
                self._observe_hop(hop_name(out), float(out["seconds"]))
            elif out.get("name") == REQUEST_ROOT:
                self._note_exemplar(trace, float(out["seconds"]),
                                    float(out.get("ts", self.clock())))
        return fresh

    def _observe_hop(self, hop: str, seconds: float) -> None:
        h = self.hops.setdefault(hop, {
            "buckets": [0] * len(TRACE_HOP_BUCKETS), "sum": 0.0,
            "count": 0})
        for i, edge in enumerate(TRACE_HOP_BUCKETS):
            if seconds <= edge:
                h["buckets"][i] += 1
                break
        h["sum"] += seconds
        h["count"] += 1

    def _note_exemplar(self, trace: int, seconds: float, ts: float) -> None:
        self._exemplars.append((seconds, trace, ts))
        self._exemplars.sort(key=lambda e: -e[0])
        self._prune(self.clock())

    def _prune(self, now: float) -> None:
        live = [e for e in self._exemplars if now - e[2] <= self.window]
        del self._exemplars[:]
        self._exemplars.extend(live[:self.EXEMPLAR_K])

    # -- accessors --------------------------------------------------------

    def exemplars(self) -> List[Tuple[float, int]]:
        """[(root seconds, trace id)] slowest-first, window-pruned."""
        self._prune(self.clock())
        return [(s, t) for s, t, _ts in self._exemplars]

    def slowest_trace(self) -> Optional[int]:
        """Trace id of the slowest completed request in the window —
        what an SLO-breach record attaches as its exemplar."""
        ex = self.exemplars()
        return ex[0][1] if ex else None

    def tree(self, trace: int) -> Optional[Dict]:
        """build_trees-shaped {"root", "spans"} for one trace id, or
        None when no span of it has federated yet."""
        spans = self.spans.get(trace)
        if not spans:
            return None
        return build_trees(spans).get(trace)

    def trees(self) -> Dict[int, Dict]:
        """Every federated trace reconstructed (sessions included)."""
        return build_trees(s for lst in self.spans.values() for s in lst)

    # -- rendering --------------------------------------------------------

    def _labels(self, extra: Optional[Dict] = None) -> str:
        merged = {"job": self.job, **self.extra_labels}
        if extra:
            merged.update(extra)
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in merged.items())
        return "{" + inner + "}"

    def render_lines(self) -> List[str]:
        if not self.hops:
            return []
        name = "tpu_job_trace_hop_seconds"
        lines = [f"# HELP {name} request-trace hop duration by hop name, "
                 f"federated across pods",
                 f"# TYPE {name} histogram"]
        for hop in sorted(self.hops):
            h = self.hops[hop]
            cum = 0
            for edge, c in zip(TRACE_HOP_BUCKETS, h["buckets"]):
                cum += c
                lines.append(
                    f"{name}_bucket"
                    f"{self._labels({'hop': hop, 'le': format_value(edge)})}"
                    f" {cum}")
            lines.append(f"{name}_bucket"
                         f"{self._labels({'hop': hop, 'le': '+Inf'})}"
                         f" {h['count']}")
            lines.append(f"{name}_sum{self._labels({'hop': hop})} "
                         f"{format_value(round(h['sum'], 6))}")
            lines.append(f"{name}_count{self._labels({'hop': hop})} "
                         f"{h['count']}")
        return lines


# ---------------------------------------------------------------------------
# restart-aware goodput
# ---------------------------------------------------------------------------

def goodput_ledger(records: Iterable[Dict]) -> Dict:
    """Fold a (merged) timeline into the job goodput ledger.

    Every executed step is useful or lost. The useful frontier is the
    running max over step-carrying fields; a `checkpoint_restore` to a
    step behind that frontier charges the gap to the lost column (the
    gang re-executes those steps), and a `divergence_rollback` charges
    from_step − to_step. goodput = useful / (useful + lost)."""
    observed = 0
    lost = 0
    restarts = 0
    restores = 0
    rollbacks = 0
    for rec in sorted(records, key=lambda r: r.get("ts", 0.0)):
        kind = rec.get("event")
        if kind == ev.CHECKPOINT_RESTORE:
            restores += 1
            try:
                lost += max(0, observed - int(rec.get("step", 0)))
            except (TypeError, ValueError):
                pass
        elif kind == ev.DIVERGENCE_ROLLBACK:
            rollbacks += 1
            try:
                lost += max(0, int(rec.get("from_step", 0))
                            - int(rec.get("to_step", 0)))
            except (TypeError, ValueError):
                pass
        elif kind == ev.GANG_RESTART:
            restarts += 1
        for field in STEP_FIELDS:
            if field in rec:
                try:
                    observed = max(observed, int(rec[field]))
                except (TypeError, ValueError):
                    pass
    total = observed + lost
    return {"useful_steps": observed, "lost_steps": lost,
            "total_steps": total,
            "goodput": (observed / total) if total else 1.0,
            "restarts": restarts, "restores": restores,
            "rollbacks": rollbacks}


def resize_ledger(records: Iterable[Dict]) -> List[Dict]:
    """Split each ``gang_resize`` into drain / restore / recompile phases.

    drain     = the preemption_drain -> emergency_checkpoint wall time of
                the drain that handed the gang over to the resize;
    restore   = the first post-resize checkpoint_restore's ``seconds``
                (shard read + assembly, resharded or not);
    recompile = the first post-resume step's ``seconds``
                (first_resume_step: restore-done -> step completion, i.e.
                jit recompilation at the new world size plus one step);
    total     = drain start -> first post-resume step completion — the
                goodput hole the resize punched into the run.
    Entries missing a phase (job died mid-resize) keep whatever phases
    were observed; ``total_seconds`` is only set once the gang stepped.

    Every entry carries ``kind``: ``"gang_resize"`` for the phase-pair
    machinery above, ``"live_scale"`` for surgical decode-pool steps.
    A ``live_scale`` record is SELF-CONTAINED (the survivors never
    paused, so there is no checkpoint/restore/recompile to pair): its
    entry copies the record's drain_seconds (graceful detach drain) /
    warmup_seconds (attach compile pin) and total_seconds (defaulting
    to drain + warmup when the emitter measured only the phases).
    Cooldown readers MUST filter on kind — pricing a live step off a
    gang total (or a gang preemption off a live step) inverts the
    whole point of the split."""
    resizes: List[Dict] = []
    drain_open: Optional[float] = None
    last_drain: Optional[Tuple[float, float]] = None
    current: Optional[Dict] = None
    for rec in sorted(records, key=lambda r: r.get("ts", 0.0)):
        kind = rec.get("event")
        ts = rec.get("ts", 0.0)
        if kind == ev.PREEMPTION_DRAIN:
            drain_open = ts
        elif kind == ev.EMERGENCY_CHECKPOINT and drain_open is not None:
            last_drain = (drain_open, round(ts - drain_open, 3))
            drain_open = None
        elif kind == ev.LIVE_SCALE:
            entry = {"ts": ts, "kind": ev.LIVE_SCALE}
            for key in ("action", "replicas", "decode_replicas", "reason",
                        "token"):
                if key in rec:
                    entry[key] = rec[key]
            phases = 0.0
            for key in ("drain_seconds", "warmup_seconds"):
                try:
                    entry[key] = float(rec[key])
                    phases += entry[key]
                except (KeyError, TypeError, ValueError):
                    pass
            try:
                entry["total_seconds"] = float(rec["total_seconds"])
            except (KeyError, TypeError, ValueError):
                if "drain_seconds" in entry or "warmup_seconds" in entry:
                    entry["total_seconds"] = round(phases, 3)
            resizes.append(entry)
        elif kind == ev.GANG_RESIZE:
            if current is not None:
                resizes.append(current)
            current = {"ts": ts, "kind": ev.GANG_RESIZE}
            for key in ("workers", "tpus", "replicas", "num_slices",
                        "reason"):
                if key in rec:
                    current[key] = rec[key]
            if last_drain is not None:
                current["drain_start_ts"] = last_drain[0]
                current["drain_seconds"] = last_drain[1]
                last_drain = None
        elif (current is not None and kind == ev.CHECKPOINT_RESTORE
              and "restore_seconds" not in current):
            try:
                current["restore_seconds"] = float(rec["seconds"])
            except (KeyError, TypeError, ValueError):
                pass
        elif current is not None and kind == ev.FIRST_RESUME_STEP:
            try:
                current["recompile_seconds"] = float(rec["seconds"])
            except (KeyError, TypeError, ValueError):
                pass
            start = current.get("drain_start_ts", current["ts"])
            current["total_seconds"] = round(ts - start, 3)
            resizes.append(current)
            current = None
    if current is not None:
        resizes.append(current)
    return resizes


#: log-spaced upper bounds for tpu_job_resize_seconds. A resize is drain
#: + restore + recompile: sub-second on toy runs, minutes when a large
#: model recompiles, so the buckets span both regimes.
RESIZE_BUCKETS = (1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)


def resize_lines(job: str, resizes: List[Dict],
                 extra_labels: Optional[Dict[str, str]] = None) -> List[str]:
    """Render the resize ledger as Prometheus text: one
    tpu_job_resize_seconds histogram over completed resizes plus
    per-phase gauges for the most recent one."""
    labels = {"job": job, **(extra_labels or {})}

    def ls(extra: Optional[Dict[str, str]] = None) -> str:
        merged = {**labels, **(extra or {})}
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in merged.items())
        return "{" + inner + "}"

    # the histogram prices GANG resizes only: mixing sub-second live
    # scale steps into the same series would drag the p99 an alert rule
    # reads off the distribution it is actually alarming on (entries
    # predating the kind field are all gang — live_scale always stamps)
    gang = [r for r in resizes
            if r.get("kind", ev.GANG_RESIZE) == ev.GANG_RESIZE]
    live = [r for r in resizes if r.get("kind") == ev.LIVE_SCALE]
    totals = sorted(float(r["total_seconds"]) for r in gang
                    if "total_seconds" in r)
    lines = [
        "# HELP tpu_job_resize_seconds wall time of a gang resize, drain "
        "start to first post-resume step",
        "# TYPE tpu_job_resize_seconds histogram",
    ]
    for bound in RESIZE_BUCKETS:
        count = sum(1 for t in totals if t <= bound)
        lines.append(f'tpu_job_resize_seconds_bucket{ls({"le": repr(bound)})}'
                     f" {count}")
    lines.append(f'tpu_job_resize_seconds_bucket{ls({"le": "+Inf"})}'
                 f" {len(totals)}")
    lines.append(f"tpu_job_resize_seconds_sum{ls()}"
                 f" {format_value(round(sum(totals), 3))}")
    lines.append(f"tpu_job_resize_seconds_count{ls()} {len(totals)}")
    lines += [
        "# HELP tpu_job_resizes_total gang resizes observed",
        "# TYPE tpu_job_resizes_total counter",
        f"tpu_job_resizes_total{ls()} {len(gang)}",
    ]
    for phase in ("drain", "restore", "recompile"):
        key = f"{phase}_seconds"
        value = next((r[key] for r in reversed(gang) if key in r), None)
        if value is None:
            continue
        lines += [
            f"# HELP tpu_job_resize_{key} {phase} phase of the most "
            "recent gang resize",
            f"# TYPE tpu_job_resize_{key} gauge",
            f"tpu_job_resize_{key}{ls()} "
            f"{format_value(round(float(value), 3))}",
        ]
    if live:
        lines += [
            "# HELP tpu_job_live_scales_total surgical decode-pool "
            "scale steps (no gang restart)",
            "# TYPE tpu_job_live_scales_total counter",
            f"tpu_job_live_scales_total{ls()} {len(live)}",
        ]
        value = next((r["total_seconds"] for r in reversed(live)
                      if "total_seconds" in r), None)
        if value is not None:
            lines += [
                "# HELP tpu_job_live_scale_seconds drain+warmup of the "
                "most recent live scale step",
                "# TYPE tpu_job_live_scale_seconds gauge",
                f"tpu_job_live_scale_seconds{ls()} "
                f"{format_value(round(float(value), 3))}",
            ]
    return lines


def ledger_lines(job: str, ledger: Dict,
                 extra_labels: Optional[Dict[str, str]] = None) -> List[str]:
    labels = {"job": job, **(extra_labels or {})}
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    ls = "{" + inner + "}"
    return [
        "# HELP tpu_job_goodput useful steps over total steps "
        "including restart- and rollback-lost work",
        "# TYPE tpu_job_goodput gauge",
        f"tpu_job_goodput{ls} {format_value(round(ledger['goodput'], 6))}",
        "# HELP tpu_job_steps_lost_total steps re-executed after gang "
        "restarts and rollbacks",
        "# TYPE tpu_job_steps_lost_total counter",
        f"tpu_job_steps_lost_total{ls} {ledger['lost_steps']}",
        "# HELP tpu_job_useful_steps furthest step frontier reached",
        "# TYPE tpu_job_useful_steps gauge",
        f"tpu_job_useful_steps{ls} {ledger['useful_steps']}",
        "# HELP tpu_job_restarts_total gang restarts observed",
        "# TYPE tpu_job_restarts_total counter",
        f"tpu_job_restarts_total{ls} {ledger['restarts']}",
    ]


# ---------------------------------------------------------------------------
# the controller attachment
# ---------------------------------------------------------------------------

def _http_get(url: str, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


class JobObservatory:
    """Per-job observability state the controller carries.

    One controller-side EventLog (each record stamped with its ``job``),
    one MetricsFederation + ClockSync + worker-record cache per job, and
    the scrape loop. All note_* methods are idempotent where the event
    is once-per-lifecycle (created, pods_ready per incarnation,
    first_step, terminal)."""

    def __init__(self, events_dir: Optional[str] = None,
                 events: Optional[EventLog] = None,
                 clock: Callable[[], float] = time.time,
                 fetch: Callable[[str], str] = _http_get,
                 scrape_interval: float = 10.0,
                 scrape_injector=None,
                 serving_rate_floor: Optional[float] = None):
        self.events_dir = events_dir
        if events is None and events_dir:
            events = EventLog(os.path.join(events_dir,
                                           "controller-events.jsonl"),
                              clock=clock)
        self.events = events
        self.clock = clock
        self.fetch = fetch
        self.scrape_interval = scrape_interval
        #: telemetry.chaos.ScrapeFaultInjector — when set, every per-pod
        #: fetch routes through it (data-plane chaos). Rank-aware by
        #: construction: URL→rank parsing is ambiguous for serving pools
        #: (prefill-0 and decode-0 both exist), so the injector is fed
        #: the rank the observe loop already knows.
        self.scrape_injector = scrape_injector
        #: TPOT-slope floor for SERVING jobs (observed tokens+requests
        #: per second, measured between frontier advances). None keeps
        #: the lease purely wall-clock. With a floor set, a frontier
        #: that advances but below the floor does NOT slide progress_ts:
        #: an engine degraded to a trickle (per-token rate collapsed)
        #: arms the lease exactly like a frozen one, instead of buying
        #: itself an indefinite lease one token at a time.
        self.serving_rate_floor = serving_rate_floor
        self.jobs: Dict[str, Dict] = {}

    def view(self, job: str) -> Dict:
        return self.jobs.setdefault(job, {
            "created": False, "pods_ready": False, "first_step": False,
            "terminal": False, "labels": {},
            "federation": MetricsFederation(job, clock=self.clock),
            "traces": TraceFederation(job, clock=self.clock),
            "clock_sync": ClockSync(),
            "controller_records": [], "worker_records": {},
            "last_scrape": 0.0,
            # progress lease (stuck-gang detection): the highest step
            # frontier ever observed for this gang incarnation and WHEN it
            # last moved. progress_ts None = lease disarmed (not observed
            # yet, or reset by a gang restart).
            "progress_step": -1, "progress_ts": None,
            # TPOT-slope tracking (serving_rate_floor): the frontier and
            # wall time of the last frontier ADVANCE, regardless of
            # whether that advance was fast enough to renew the lease —
            # consecutive advances measure the between-advance rate
            "rate_step": -1, "rate_ts": None,
            # serving gangs watch the retired-request/token frontier
            # instead of the step frontier (observe(serving=True))
            "serving": False,
            # open partial-partition window: the unreachable rank set the
            # last gang_degraded record named, None when fully connected
            "degraded_ranks": None})

    # -- controller lifecycle events ------------------------------------
    def record(self, job: str, event: str, **fields) -> Dict:
        view = self.view(job)
        fields = {**view["labels"], **fields}
        if self.events is not None:
            rec = self.events.emit(event, job=job, **fields)
        else:
            rec = {"ts": round(self.clock(), 3), "event": event,
                   "job": job, **fields}
        view["controller_records"].append(rec)
        return rec

    def note_created(self, job: str, **fields) -> None:
        view = self.view(job)
        if not view["created"]:
            view["created"] = True
            self.record(job, ev.JOB_CREATED, **fields)

    def note_pods_ready(self, job: str, **fields) -> None:
        view = self.view(job)
        if not view["pods_ready"]:
            view["pods_ready"] = True
            self.record(job, ev.PODS_READY, **fields)

    def note_restart(self, job: str, exit_code: Optional[int],
                     restart: int) -> None:
        view = self.view(job)
        view["pods_ready"] = False      # next readiness is a new event
        self.record(job, ev.GANG_RESTART, exit_code=exit_code,
                    restart=restart,
                    last_observed_step=view["federation"].observed_step())
        # the restarted gang re-executes from its checkpoint: the old
        # frontier must not keep an expired lease armed against it
        self.reset_progress_lease(job)

    def reset_progress_lease(self, job: str) -> None:
        """Disarm the progress lease; the next observe() re-arms it at
        whatever frontier the restarted gang actually reports. Idempotent
        — crash-replayed restart syncs call this again harmlessly."""
        view = self.view(job)
        view["progress_step"] = -1
        view["progress_ts"] = None
        view["rate_step"] = -1
        view["rate_ts"] = None

    def stall_seconds(self, job: str) -> Optional[float]:
        """Seconds since this job's observed step frontier last advanced
        (all scrapes failing keeps the frontier frozen, so a dead metrics
        plane reads as a stall too — by design: an unobservable gang
        cannot prove liveness). None while the lease is disarmed."""
        view = self.jobs.get(job)
        if view is None or view.get("progress_ts") is None:
            return None
        return max(0.0, self.clock() - view["progress_ts"])

    def note_stuck(self, job: str, stall_seconds: float,
                   deadline: int) -> None:
        """Record the gang_stuck verdict on the timeline with its stall
        window — the postmortem renders stuck -> restart as an incident."""
        view = self.view(job)
        self.record(job, ev.GANG_STUCK, stall_seconds=stall_seconds,
                    progress_deadline_seconds=deadline,
                    last_observed_step=self._observed_step(view))

    def partition_state(self, job: str) -> Tuple[List[int], int]:
        """(unreachable ranks, total ranks attempted) — the controller's
        partial-partition evidence after a scrape pass."""
        view = self.jobs.get(job)
        if view is None:
            return [], 0
        fed = view["federation"]
        return fed.unreachable_ranks(), len(fed.pods)

    def note_degraded(self, job: str, ranks: List[int],
                      total: int) -> None:
        """Open (or update) a partial-partition window: some ranks dark,
        the rest still reporting. Idempotent per rank set — re-observing
        the same dark set does not re-emit; a CHANGED set does (the
        window's shape is part of the incident)."""
        view = self.view(job)
        key = tuple(ranks)
        if view.get("degraded_ranks") == key:
            return
        view["degraded_ranks"] = key
        self.record(job, ev.GANG_DEGRADED, ranks=list(ranks),
                    partitioned_ranks=len(ranks), total_ranks=total,
                    last_observed_step=self._observed_step(view))

    def note_degraded_healed(self, job: str) -> None:
        """Close an open partial-partition window (every rank scraped
        again). No-op when no window is open."""
        view = self.view(job)
        if view.get("degraded_ranks"):
            view["degraded_ranks"] = None
            self.record(job, ev.GANG_DEGRADED, healed=True, ranks=[],
                        partitioned_ranks=0,
                        last_observed_step=self._observed_step(view))

    def note_packed(self, job: str, group: str, members: List[str],
                    k: int,
                    labels: Optional[Dict[str, str]] = None) -> None:
        view = self.view(job)
        if view["labels"].get("pack_group") != group:
            # PackPlan.labels() when the controller drives this; every
            # later timeline record and federated series carries them
            view["labels"].update(labels or {"pack_group": group})
            view["federation"].extra_labels.update(view["labels"])
            self.record(job, ev.JOB_PACKED, members=members, k=k)

    def note_resize(self, job: str, gang: bool = False, **fields) -> None:
        # gang=True is a user-driven spec.resize (a deliberate gang
        # resize: drain -> rescale -> resharded resume); False is the
        # elastic controller shrinking/growing around capacity.
        self.record(job, ev.GANG_RESIZE if gang else ev.JOB_RESIZED,
                    **fields)

    def note_sched(self, job: str, event: str, token: str,
                   **fields) -> None:
        """Record one fleet-scheduler decision (a sched_* event kind),
        idempotent per (event, token): the controller replays syncs
        after every crash, and each decision's status write carries the
        same token the replay re-derives — so the timeline shows each
        preempt/grow-back/migration exactly once however many times the
        sync re-runs. sched_skip is the exception (token "" = always
        emit is wrong — skips also dedupe, the hysteresis would spam one
        per sync otherwise)."""
        view = self.view(job)
        seen = view.setdefault("sched_tokens", set())
        mark = (event, token)
        if mark in seen:
            return
        seen.add(mark)
        self.record(job, event, **fields)

    def note_live_scale(self, job: str, token: str, **fields) -> None:
        """Record one surgical decode-pool scale step (LIVE_SCALE),
        idempotent per token — the note_sched discipline applied to
        live scaling: the controller writes the ``scalingReplica``
        status marker BEFORE touching the decode StatefulSet and emits
        with that marker as the token, so a crash replay (marker still
        set, replicas already landed) re-emits at most once however
        many times the sync re-runs."""
        view = self.view(job)
        seen = view.setdefault("live_scale_tokens", set())
        if token in seen:
            return
        seen.add(token)
        self.record(job, ev.LIVE_SCALE, token=token, **fields)

    def note_terminal(self, job: str, succeeded: bool, **fields) -> None:
        view = self.view(job)
        if view["terminal"]:
            return
        view["terminal"] = True
        self.record(job, ev.JOB_SUCCEEDED if succeeded else ev.JOB_FAILED,
                    **fields)
        try:
            self.write_timeline(job)
        except OSError:
            logger.warning("timeline write failed for job %s", job,
                           exc_info=True)

    # -- scraping -------------------------------------------------------
    def _scrape(self, rank: int, url: str) -> str:
        """One per-pod fetch, routed through the scrape-fault injector
        when one is installed (telemetry/chaos.py)."""
        if self.scrape_injector is not None:
            return self.scrape_injector.fetch(rank, url, self.fetch)
        return self.fetch(url)

    def observe(self, job: str, targets: Dict[int, str],
                force: bool = False, serving: bool = False) -> None:
        """Scrape each pod's /metrics and /events. ``targets`` maps
        replica_rank -> base URL (http://host:port). Rate-limited by
        scrape_interval unless forced. ``serving=True`` switches the
        job's progress frontier from the step counter to the
        retired-request/token counters (the serving progress lease)."""
        view = self.view(job)
        view["serving"] = bool(serving)
        now = self.clock()
        if not force and now - view["last_scrape"] < self.scrape_interval:
            return
        view["last_scrape"] = now
        fed = view["federation"]
        for rank, base in sorted(targets.items()):
            # netloc, not hostname: local test gangs share an IP and
            # differ only by port, and each listener is its own clock
            host = urllib.parse.urlparse(base).netloc or str(rank)
            try:
                fed.ingest(rank, self._scrape(rank, base + "/metrics"))
            except Exception:
                fed.scrape_failed(rank)
                continue
            try:
                payload = json.loads(
                    self._scrape(rank, base + "/events"))
            except Exception:
                # metrics landed; treat the events pull as best-effort
                continue
            records = payload.get("records", [])
            view["clock_sync"].note(host, self.clock(),
                                    payload.get("now", self.clock()),
                                    latest_boot_id(records))
            view["worker_records"][host] = records
            try:
                tpayload = json.loads(
                    self._scrape(rank, base + "/traces"))
            except Exception:
                # best-effort like /events: a pod without a trace sink
                # 404s here and its metrics still count
                continue
            view["traces"].ingest(host, tpayload.get("records", []),
                                  offset=view["clock_sync"].offset(host))
        self._advance_frontier(job, view, now)

    def _advance_frontier(self, job: str, view: Dict, now: float) -> None:
        """Post-ingest progress bookkeeping, shared by the scrape loop
        and ingest_push so a pushed report renews the progress lease
        exactly like a scraped one."""
        step = self._observed_step(view)
        if step > 0 and not view["first_step"]:
            view["first_step"] = True
            self.record(job, ev.FIRST_STEP_OBSERVED, step=step)
        # progress lease: (re-)arm on the first scrape of an incarnation,
        # then slide forward only when the frontier actually moves — zero
        # advance (or every scrape failing) leaves progress_ts frozen and
        # stall_seconds() growing
        if step > view["progress_step"]:
            # TPOT-slope check (serving + serving_rate_floor): an
            # advance only renews the lease when the frontier moved at
            # >= floor tokens/sec since its LAST advance. A degraded
            # engine emitting a trickle keeps advancing rate_step (so
            # the measurement window stays honest) while progress_ts
            # stays frozen — it goes stuck by the same wall-clock
            # deadline as a fully wedged one. The first advance of an
            # incarnation (rate_ts None) always arms: there is no
            # window to measure yet.
            slope_ok = True
            if (view.get("serving") and self.serving_rate_floor is not None
                    and view["rate_ts"] is not None
                    and now > view["rate_ts"]):
                rate = (step - view["rate_step"]) / (now - view["rate_ts"])
                slope_ok = rate >= self.serving_rate_floor
            view["rate_step"] = step
            view["rate_ts"] = now
            if slope_ok:
                view["progress_step"] = step
                view["progress_ts"] = now

    def ingest_push(self, job: str, rank: int, payload: Dict,
                    host: Optional[str] = None,
                    serving: Optional[bool] = None) -> bool:
        """Accept one pushed worker report (WorkerTelemetry.push_report())
        with scrape-identical bookkeeping: the metrics text feeds the
        same federation, the ``now`` stamp anchors the same clock
        correction, event records land in the same per-host cache (same
        staleness convention — a pod that stops pushing goes stale just
        like one that stops answering scrapes), and trace spans federate
        the same way. The payload is routed through the scrape-fault
        injector when one is installed, so --chaos drops/replays pushes
        on the exact surface it drops scrapes. Returns False when the
        report was lost or unparseable (counted as a failed scrape)."""
        view = self.view(job)
        if serving is not None:
            view["serving"] = bool(serving)
        now = self.clock()
        host = host or f"push-{rank}"
        fed = view["federation"]
        body = json.dumps(payload)
        try:
            if self.scrape_injector is not None:
                body = self.scrape_injector.fetch(
                    rank, f"push://{host}/report", lambda _url: body)
            report = json.loads(body)
            fed.ingest(rank, report.get("metrics", ""))
        except Exception:
            fed.scrape_failed(rank)
            return False
        records = report.get("events") or []
        view["clock_sync"].note(host, now, report.get("now", now),
                                latest_boot_id(records))
        if records:
            view["worker_records"][host] = records
        traces = report.get("traces") or []
        if traces:
            view["traces"].ingest(host, traces,
                                  offset=view["clock_sync"].offset(host))
        self._advance_frontier(job, view, now)
        return True

    def slowest_trace(self, job: str) -> Optional[int]:
        """The job's slowest completed request trace in the exemplar
        window — what an SLO-breach record attaches as its exemplar."""
        return self.view(job)["traces"].slowest_trace()

    def _observed_step(self, view: Dict) -> int:
        if view.get("serving"):
            # serving gangs have no training step: the progress frontier
            # is the retired-request/token counter sum — a wedged engine
            # stops retiring and the frontier freezes exactly like a
            # stalled step counter would
            return view["federation"].observed_tokens()
        best = view["federation"].observed_step()
        for records in view["worker_records"].values():
            for rec in records:
                for field in STEP_FIELDS:
                    if field in rec:
                        try:
                            best = max(best, int(rec[field]))
                        except (TypeError, ValueError):
                            pass
        return best

    # -- outputs --------------------------------------------------------
    def merged_records(self, job: str) -> List[Dict]:
        view = self.view(job)
        sources: List[Tuple[Optional[str], List[Dict]]] = [
            (None, view["controller_records"])]
        sources += [(host, recs)
                    for host, recs in sorted(view["worker_records"].items())]
        return merge_timeline(sources, offsets=view["clock_sync"].offsets)

    def write_timeline(self, job: str,
                       out_path: Optional[str] = None) -> str:
        if out_path is None:
            root = self.events_dir or "."
            out_path = os.path.join(root, job, "timeline.jsonl")
        view = self.view(job)
        sources = ([(None, view["controller_records"])] +
                   [(host, recs) for host, recs
                    in sorted(view["worker_records"].items())])
        max_bytes = _env_int(ENV_TIMELINE_MAX_BYTES, 0)
        if not max_bytes:
            merge_timeline(sources, offsets=view["clock_sync"].offsets,
                           out_path=out_path)
            return out_path
        # Size-capped mode: a long-lived job's full rewrite grows without
        # bound, so instead append only records not yet persisted and
        # shift the chain (events.py rotate_chain — same .N layout) when
        # the live file would blow the cap. Per-source high-water marks
        # make the append duplicate-free: the pull loop only ever extends
        # each source's record list. The batch is ts-sorted within
        # itself; cross-batch ordering is arrival order, and every
        # chain-spanning reader (postmortem.read_timeline, read_events)
        # re-sorts by ts anyway.
        keep = max(1, _env_int(ENV_TIMELINE_KEEP, 1))
        consumed: Dict[str, int] = view.setdefault("timeline_consumed", {})
        fresh = [(host, recs[consumed.get(host or "controller", 0):])
                 for host, recs in sources]
        batch = merge_timeline([(h, r) for h, r in fresh if r],
                               offsets=view["clock_sync"].offsets)
        for host, recs in sources:
            consumed[host or "controller"] = len(recs)
        parent = os.path.dirname(out_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        payload = "".join(json.dumps(rec) + "\n" for rec in batch)
        try:
            size = os.path.getsize(out_path)
        except OSError:
            size = 0
        if size and size + len(payload) > max_bytes:
            try:
                rotate_chain(out_path, keep)
            except OSError:
                logger.warning("timeline rotation failed for %s", out_path,
                               exc_info=True)
        with open(out_path, "a", encoding="utf-8") as fh:
            fh.write(payload)
        return out_path

    def render_lines(self) -> List[str]:
        lines: List[str] = []
        for job in sorted(self.jobs):
            view = self.jobs[job]
            merged = self.merged_records(job)
            lines += view["federation"].render_lines()
            lines += view["traces"].render_lines()
            lines += ledger_lines(job, goodput_ledger(merged),
                                  extra_labels=view["labels"])
            resizes = resize_ledger(merged)
            if resizes:
                lines += resize_lines(job, resizes,
                                      extra_labels=view["labels"])
        return lines

    def render(self) -> str:
        lines = self.render_lines()
        return ("\n".join(lines) + "\n") if lines else ""

    def close(self) -> None:
        if self.events is not None:
            self.events.close()


# ---------------------------------------------------------------------------
# CLI — the harness-side controller stand-in
# ---------------------------------------------------------------------------

def _parse_kv(pairs: List[str]) -> Dict:
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"expected k=v, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_operator_tpu.telemetry.collector",
        description="job-level event collection: emit controller events, "
                    "merge timelines, compute the goodput ledger")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_emit = sub.add_parser("emit", help="append one controller event")
    p_emit.add_argument("--log", required=True)
    p_emit.add_argument("--job", required=True)
    p_emit.add_argument("event")
    p_emit.add_argument("fields", nargs="*", help="k=v extra fields")

    p_merge = sub.add_parser("merge", help="merge controller + worker "
                             "event logs into one timeline")
    p_merge.add_argument("--job", required=True)
    p_merge.add_argument("--controller", required=True,
                         help="controller events.jsonl")
    p_merge.add_argument("--worker", action="append", default=[],
                         metavar="HOST=PATH", help="worker event log")
    p_merge.add_argument("--offset", action="append", default=[],
                         metavar="HOST=SECONDS",
                         help="clock offset to ADD to that host's ts")
    p_merge.add_argument("--out", required=True, help="timeline.jsonl")
    p_merge.add_argument("--metrics-out", default=None,
                         help="write federated goodput series here")

    args = parser.parse_args(argv)
    if args.cmd == "emit":
        with EventLog(args.log) as log:
            log.emit(args.event, job=args.job, **_parse_kv(args.fields))
        return 0

    # merge
    controller = [r for r in read_events(args.controller)
                  if r.get("job", args.job) == args.job]
    sources: List[Tuple[Optional[str], List[Dict]]] = [(None, controller)]
    for spec in args.worker:
        if "=" not in spec:
            raise SystemExit(f"--worker expects HOST=PATH, got {spec!r}")
        host, path = spec.split("=", 1)
        sources.append((host, read_events(path)))
    offsets = {k: float(v) for k, v in _parse_kv(args.offset).items()}
    merged = merge_timeline(sources, offsets=offsets, out_path=args.out)
    ledger = goodput_ledger(merged)
    resizes = resize_ledger(merged)
    if args.metrics_out:
        lines = ledger_lines(args.job, ledger)
        if resizes:
            lines += resize_lines(args.job, resizes)
        with open(args.metrics_out, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
    print(json.dumps({"job": args.job, "records": len(merged),
                      "timeline": args.out, "resizes": resizes, **ledger}))
    return 0


if __name__ == "__main__":
    sys.exit(main())


__all__ = ["parse_prometheus", "MetricsFederation", "ClockSync",
           "TraceFederation", "merge_timeline", "goodput_ledger",
           "ledger_lines", "resize_ledger", "resize_lines",
           "RESIZE_BUCKETS", "JobObservatory", "latest_boot_id", "main",
           "WORKER_PREFIX", "ROUTER_PREFIX", "JOB_PREFIX"]
