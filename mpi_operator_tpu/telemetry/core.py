"""Zero-dependency metrics core for the data plane.

The control plane grew a Prometheus endpoint (controller/metrics.py)
while the data plane — the part ROADMAP says must run "as fast as the
hardware allows" — reported nothing but a post-hoc bench JSONL line.
This module is the missing half: counters, gauges, and streaming
histograms cheap enough to live INSIDE the hot loops (train step, decode
step) without moving the numbers they measure.

Design constraints, in order:

  * **No per-step allocation on the hot path.** `Histogram.observe` is a
    bisect into a precomputed edge tuple plus two integer bumps — no new
    lists, dicts, or strings per call. Rendering (the slow path) is the
    only place that builds text.
  * **Fixed log-spaced buckets.** Latencies span decades (a 50 µs decode
    dispatch to a 30 s compile); log-spaced edges give constant RELATIVE
    resolution everywhere on that range, and fixing them at construction
    means observe never rebalances anything (contrast HDR/t-digest style
    adaptive sketches — better tails, but allocation and branching on
    every record). With the default 10 buckets/decade the edge ratio is
    10^(1/10) ≈ 1.26, so any quantile estimate is within ~26% of truth —
    the right trade for wall-time telemetry read as p50/p99 summaries.
  * **Thread-safe.** The serving engine's host loop, checkpoint threads,
    and the /metrics scrape thread all touch the same registry; every
    mutation takes a per-metric lock (uncontended CPython lock ≈ 100 ns,
    invisible next to a millisecond step).

Exporters live next door: prometheus.py (worker /metrics, text format)
and events.py (fsync'd JSONL for discrete events).
"""
from __future__ import annotations

import os
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name}={v!r} is not a number") from None


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """Monotone counter (`*_total` naming convention)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Streaming histogram over fixed log-spaced buckets.

    Edges are ``lo * r^i`` with ``r = 10^(1/per_decade)``, spanning
    [lo, hi]; observations below lo land in the first bucket and
    observations above hi in the overflow (+Inf) bucket, so no value is
    ever dropped. Defaults (100 µs … 1000 s, 10/decade = 71 edges) cover
    everything from a decode-step dispatch to a cold compile.

    `percentile(p)` log-interpolates inside the covering bucket — an
    estimate with relative error bounded by the edge ratio (~26% at the
    default resolution), which is what a p50/p99 summary needs; exact
    quantiles would require keeping every sample.

    Deploy-time overrides: ``TPU_HIST_LO``, ``TPU_HIST_HI`` (seconds) and
    ``TPU_HIST_PER_DECADE`` (int) replace the constructor's range/
    resolution for EVERY histogram in the process — the operator knob for
    re-ranging telemetry on hardware whose latencies fall off the baked-in
    edges (e.g. sub-10 µs decode steps, or coarser buckets to shrink
    scrape payloads) without touching call sites. Unset or empty
    variables leave the code-specified values alone.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 lo: float = 1e-4, hi: float = 1e3,
                 per_decade: int = 10,
                 labels: Optional[Dict[str, str]] = None):
        env_lo = _env_float("TPU_HIST_LO")
        env_hi = _env_float("TPU_HIST_HI")
        env_pd = _env_float("TPU_HIST_PER_DECADE")
        if env_lo is not None:
            lo = env_lo
        if env_hi is not None:
            hi = env_hi
        if env_pd is not None:
            per_decade = int(env_pd)
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if per_decade < 1:
            raise ValueError(f"per_decade must be >= 1, got {per_decade}")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        r = 10.0 ** (1.0 / per_decade)
        # rounded to 6 significant figures: keeps the `le` labels human-
        # readable and strictly increasing (ratio ~1.26 >> rounding error)
        edges: List[float] = [float(f"{lo:.6g}")]
        while edges[-1] < hi * (1 - 1e-9):
            edges.append(float(f"{lo * r ** len(edges):.6g}"))
        self.edges: Tuple[float, ...] = tuple(edges)   # bucket UPPER bounds
        self._lock = threading.Lock()
        # one extra slot: the +Inf overflow bucket
        self._counts = [0] * (len(self.edges) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, x: float) -> None:
        # bisect_left: first edge >= x, i.e. the Prometheus `le` bucket;
        # x past the last edge indexes the overflow slot
        i = bisect_left(self.edges, x)
        with self._lock:
            self._counts[i] += 1
            self._sum += x
            self._count += 1

    def observe_n(self, x: float, n: int) -> None:
        """Fold n identical observations in one lock acquisition — for
        windowed loops that only learn a per-step AVERAGE at the window
        fetch (async dispatch makes per-iteration host time meaningless;
        the window average is the true device step time)."""
        if n <= 0:
            return
        i = bisect_left(self.edges, x)
        with self._lock:
            self._counts[i] += n
            self._sum += x * n
            self._count += n

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. overflow, sum, count) — one lock."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def percentile(self, p: float) -> Optional[float]:
        """Estimated p-th percentile (0-100), None when empty."""
        counts, _sum, total = self.snapshot()
        if total == 0:
            return None
        target = max(1, min(total, -(-total * p // 100)))  # ceil, clamped
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i >= len(self.edges):        # overflow: best we can say
                    return self.edges[-1]
                upper = self.edges[i]
                lower = self.edges[i - 1] if i > 0 else upper / 1.26
                frac = (target - (cum - c)) / c
                return lower * (upper / lower) ** frac
        return self.edges[-1]                   # unreachable


class Registry:
    """Named metric store, get-or-create semantics.

    Re-requesting a (name, labels) pair returns the EXISTING instrument —
    repeated benchmark legs in one process accumulate into the same
    series instead of colliding on registration. Asking for the same name
    with a different kind raises: that's a naming bug, not a merge.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], object] = {}

    def _get_or_create(self, cls, name, help, labels, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, requested "
                        f"{cls.__name__}")
                return existing
            m = cls(name, help, labels=labels, **kw)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  lo: float = 1e-4, hi: float = 1e3, per_decade: int = 10,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   lo=lo, hi=hi, per_decade=per_decade)

    def collect(self) -> Iterable[object]:
        """Metrics in registration order (stable scrape output)."""
        with self._lock:
            return list(self._metrics.values())


__all__ = ["Counter", "Gauge", "Histogram", "Registry"]
