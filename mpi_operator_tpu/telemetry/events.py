"""Structured event log: fsync'd JSONL for discrete data-plane events.

Metrics answer "how fast"; events answer "what happened". Preemption
drains, emergency checkpoints, divergence rollbacks, init retries, and
slot admissions are rare, discrete, and individually precious — exactly
the records a post-mortem needs after the process is already dead.

The record discipline is bench.py's mid-kill-survivable one: each event
is a single JSON line written, flushed, AND os.fsync'd before emit()
returns. A SIGKILL between two emits loses nothing; a SIGKILL in the
middle of a write can at worst truncate the LAST line, which
`read_events` tolerates by skipping a trailing partial record. This is
what makes the resilience contract honest: the `preemption_drain` event
is durable on disk BEFORE the emergency checkpoint starts, so even a
save that dies mid-write leaves evidence of why.

Records: {"ts": <unix seconds>, "event": <kind>, ...fields}. One file
per process — multi-host runs should point each worker at its own path
(aggregation is a ROADMAP follow-up).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

# Event kinds. Constants, not an enum: the log is a plain-text contract
# read by shell greps (scripts/tier1.sh --resilience) and jq alike.
PREEMPTION_DRAIN = "preemption_drain"
EMERGENCY_CHECKPOINT = "emergency_checkpoint"
DIVERGENCE_ROLLBACK = "divergence_rollback"
INIT_RETRY = "init_retry"
SLOT_ADMIT = "slot_admit"
SLOT_RETIRE = "slot_retire"


class EventLog:
    """Append-only JSONL event sink with per-record durability."""

    def __init__(self, path: str, clock=time.time):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._clock = clock
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, event: str, **fields) -> Dict:
        """Write one event record; durable on disk when this returns.

        No-op after close() — shutdown paths (resilience __exit__,
        benchmark finally blocks) may race a late checkpoint thread, and
        losing a post-close event beats crashing the drain.
        """
        rec = {"ts": round(self._clock(), 3), "event": event, **fields}
        with self._lock:
            if self._fh.closed:
                return rec
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def flush(self) -> None:
        """Force-durability barrier. emit() already fsyncs per record, so
        this only matters for buffered writes from a future batched mode;
        kept explicit so shutdown paths can state their ordering."""
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                try:
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_events(path: str, kind: Optional[str] = None) -> List[Dict]:
    """Parse an event log, skipping a trailing partial record (the only
    corruption a mid-write SIGKILL can produce). Optionally filter by
    event kind."""
    out: List[Dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().split("\n")
    except FileNotFoundError:
        return out
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:    # torn final write — expected
                continue
            raise
        if kind is None or rec.get("event") == kind:
            out.append(rec)
    return out


__all__ = ["EventLog", "read_events", "PREEMPTION_DRAIN",
           "EMERGENCY_CHECKPOINT", "DIVERGENCE_ROLLBACK", "INIT_RETRY",
           "SLOT_ADMIT", "SLOT_RETIRE"]
