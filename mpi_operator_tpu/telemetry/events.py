"""Structured event log: fsync'd JSONL for discrete data-plane events.

Metrics answer "how fast"; events answer "what happened". Preemption
drains, emergency checkpoints, divergence rollbacks, init retries, and
slot admissions are rare, discrete, and individually precious — exactly
the records a post-mortem needs after the process is already dead.

The record discipline is bench.py's mid-kill-survivable one: each event
is a single JSON line written, flushed, AND os.fsync'd before emit()
returns. A SIGKILL between two emits loses nothing; a SIGKILL in the
middle of a write can at worst truncate the LAST line. `read_events`
skips any undecodable line (counting them in DECODE_ERRORS) so a torn
tail — or a concurrent writer caught mid-record — never aborts a live
postmortem read.

Records: {"ts": <unix seconds>, "event": <kind>, ...fields}. One file
per process; the controller-side collector (telemetry/collector.py)
merges per-host files into a job timeline. Long-running sinks can cap
growth with TPU_EVENTS_MAX_BYTES (size-based rotation to .1, .2, ...;
off by default), and packed trainers stamp replica/pack_group into
every record via bind().
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("mpi_operator_tpu.telemetry.events")

# Event kinds. Constants, not an enum: the log is a plain-text contract
# read by shell greps (scripts/tier1.sh --resilience) and jq alike.
#
# Worker-side kinds (emitted under <train-dir>/events.jsonl):
PREEMPTION_DRAIN = "preemption_drain"
EMERGENCY_CHECKPOINT = "emergency_checkpoint"
DIVERGENCE_ROLLBACK = "divergence_rollback"
INIT_RETRY = "init_retry"
SLOT_ADMIT = "slot_admit"
SLOT_RETIRE = "slot_retire"
CHECKPOINT_RESTORE = "checkpoint_restore"
CHECKPOINT_SAVED = "checkpoint_saved"
# first step completed after a restore (resilience.ResilienceContext):
# carries seconds-since-restore, i.e. the recompile phase of a resume —
# restore_done -> first post-resume step, compile time included
FIRST_RESUME_STEP = "first_resume_step"
CLOCK_ANCHOR = "clock_anchor"
FAULT_INJECTED = "fault_injected"
REPLICA_FROZEN = "replica_frozen"
RUN_COMPLETE = "run_complete"
# disaggregated serving: one record per prefill→decode page handoff
# (serve/engine.py DisaggEngine), with pages moved/cached and seconds
KV_HANDOFF = "kv_handoff"
# a serving request blew its per-request deadline
# (EngineConfig.request_timeout): retired with finish_reason "timeout",
# slot + KV pages reclaimed through the normal retire path — carries
# request id, tokens generated, and the deadline that expired
REQUEST_TIMEOUT = "request_timeout"
# Controller-side kinds (the operator's own EventLog; stamped with a
# "job" field and merged with worker records into <job>/timeline.jsonl):
JOB_CREATED = "job_created"
GANG_RESTART = "gang_restart"
# progress lease expired (spec.progressDeadlineSeconds): a Running gang
# whose federated step frontier advanced by zero across the window —
# carries stall_seconds + last_observed_step; a GANG_RESTART (or
# job_failed with reason StuckGang) ordinarily follows
GANG_STUCK = "gang_stuck"
# partial partition: SOME worker scrapes unreachable while the reachable
# remainder's frontier still advances — a DegradedGang condition, never
# a restart (scrape flakiness alone must not kill a healthy gang).
# Carries the unreachable rank set + partitioned_ranks/total_ranks;
# a follow-up record with healed=True closes the window.
GANG_DEGRADED = "gang_degraded"
PODS_READY = "pods_ready"
FIRST_STEP_OBSERVED = "first_step_observed"
JOB_PACKED = "packed"
JOB_RESIZED = "resize"
# user-driven gang resize (spec.resize / worker-count edit): the drain ->
# rescale -> re-bootstrap cycle, distinct from the capacity-driven
# elastic JOB_RESIZED shrink above. scripts/tier1.sh --elastic greps for
# this literal.
GANG_RESIZE = "gang_resize"
# surgical decode-pool scale step (serving): ONE replica attached or
# drained while the rest of the fleet keeps serving — no checkpoint, no
# fleet recompile, so unlike GANG_RESIZE this is a single self-contained
# record, not an open/close phase pair. Carries action="attach"|"detach",
# the decode target, and the measured phase split (drain_seconds for a
# detach's graceful drain, warmup_seconds for an attach's compile pin,
# total_seconds = the goodput hole — survivors never pause, so it prices
# only the stepped replica's own transition). The resize ledger files
# these under kind="live_scale"; the autoscaler's cooldown reads the
# newest entry OF ITS OWN KIND so one expensive gang resize cannot pin
# live-scale reaction times. scripts/tier1.sh greps for this literal.
LIVE_SCALE = "live_scale"
# SLO-breach-driven autoscale decision (controller/autoscale.py): a
# persisted p99/queue breach the controller acted on. Carries the
# decision target + reason and, when the trace federation had a
# completed trace in its exemplar window, exemplar_trace= — the trace
# id of the slowest request behind the breached percentile, which the
# postmortem's "slow traces:" section renders as a hop tree
AUTOSCALE_BREACH = "autoscale_breach"
# Fleet-scheduler decisions (controller/scheduler.py). Every record
# carries the action's principals so the postmortem can explain WHY a
# gang shrank: victim/beneficiary job names, chip targets, and the
# ledger-predicted cost the gate charged.
#   sched_queue    — a job was held at admission (pool full); carries
#                    needed/free chips
#   sched_preempt  — a low-priority elastic gang was shrunk to admit a
#                    higher-priority job (victim=, beneficiary=,
#                    from_tpus=, to_tpus=, predicted_cost_seconds=)
#   sched_admit    — a queued job got in (beneficiary=, free chips,
#                    via="capacity"|"preempt")
#   sched_grow_back— a preempted gang was restored to full size
#                    (victim=, to_tpus=)
#   sched_skip     — the cost gate or hysteresis declined an otherwise
#                    legal action (reason=, predicted_cost_seconds=,
#                    reclaim_seconds=) — the anti-thrash evidence
#   sched_migrate  — a DegradedGang dark pod was deleted so the
#                    StatefulSet reschedules it (rank=, pod=,
#                    migration_count=) — distinct from gang restarts
SCHED_QUEUE = "sched_queue"
SCHED_PREEMPT = "sched_preempt"
SCHED_ADMIT = "sched_admit"
SCHED_GROW_BACK = "sched_grow_back"
SCHED_SKIP = "sched_skip"
SCHED_MIGRATE = "sched_migrate"
JOB_SUCCEEDED = "job_succeeded"
JOB_FAILED = "job_failed"

# Rotation knobs: TPU_EVENTS_MAX_BYTES caps the live file (0/unset =
# rotation off, the historical behaviour); TPU_EVENTS_KEEP is how many
# rotated generations (.1 oldest-kept ... highest newest) survive.
ENV_MAX_BYTES = "TPU_EVENTS_MAX_BYTES"
ENV_KEEP = "TPU_EVENTS_KEEP"

# Module-level tally of undecodable lines skipped by read_events since
# import — a warning counter, not an error channel: mid-file garbage is
# logged and skipped so a live read never aborts on a concurrent write.
DECODE_ERRORS = 0


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class EventLog:
    """Append-only JSONL event sink with per-record durability."""

    def __init__(self, path: str, clock=time.time,
                 max_bytes: Optional[int] = None,
                 keep: Optional[int] = None):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._clock = clock
        self._lock = threading.Lock()
        self.max_bytes = _env_int(ENV_MAX_BYTES, 0) if max_bytes is None \
            else max_bytes
        self.keep = max(1, _env_int(ENV_KEEP, 1) if keep is None else keep)
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, event: str, **fields) -> Dict:
        """Write one event record; durable on disk when this returns.

        No-op after close() — shutdown paths (resilience __exit__,
        benchmark finally blocks) may race a late checkpoint thread, and
        losing a post-close event beats crashing the drain.
        """
        rec = {"ts": round(self._clock(), 3), "event": event, **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._fh.closed:
                return rec
            if self.max_bytes and self._fh.tell() + len(line) > self.max_bytes:
                self._rotate_locked()
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def _rotate_locked(self) -> None:
        """Shift events.jsonl -> .1 -> .2 ... keeping the newest `keep`
        rotated generations. Caller holds the lock; the live handle is
        reopened on the (now empty) base path. Rotation is best-effort:
        an OSError (read-only dir mid-teardown) falls back to appending
        past the cap rather than dropping the record."""
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            rotate_chain(self.path, self.keep)
        except OSError:
            logger.warning("event log rotation failed for %s", self.path,
                           exc_info=True)
        self._fh = open(self.path, "a", encoding="utf-8")

    def bind(self, **fields) -> "BoundEventLog":
        """A view of this log that stamps `fields` into every record —
        how HFTA packed replicas get a `replica` (and `pack_group`)
        field without threading labels through every emit site."""
        return BoundEventLog(self, fields)

    def flush(self) -> None:
        """Force-durability barrier. emit() already fsyncs per record, so
        this only matters for buffered writes from a future batched mode;
        kept explicit so shutdown paths can state their ordering."""
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                try:
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BoundEventLog:
    """EventLog view with pre-bound fields (see EventLog.bind).

    Duck-type compatible with EventLog at the emit/flush/close/path
    surface; close() and flush() delegate to the SHARED underlying log,
    so ownership stays with whoever opened it. Explicit emit() kwargs
    win over bound fields."""

    def __init__(self, log, fields: Dict):
        self._log = log
        self.fields = dict(fields)

    @property
    def path(self) -> str:
        return self._log.path

    def emit(self, event: str, **fields) -> Dict:
        return self._log.emit(event, **{**self.fields, **fields})

    def bind(self, **fields) -> "BoundEventLog":
        return BoundEventLog(self._log, {**self.fields, **fields})

    def flush(self) -> None:
        self._log.flush()

    def close(self) -> None:
        self._log.close()


def rotate_chain(path: str, keep: int) -> None:
    """Shift `path` -> .1 -> .2 ... keeping the newest `keep` rotated
    generations; the base path no longer exists on return (the caller
    reopens or rewrites it). ONE chain layout shared by every size-
    capped JSONL sink — EventLog above and the collector's
    timeline.jsonl — so event_files/read_events span them all."""
    oldest = path + ".%d" % keep
    if os.path.exists(oldest):
        os.remove(oldest)
    for i in range(keep - 1, 0, -1):
        src = path + ".%d" % i
        if os.path.exists(src):
            os.replace(src, path + ".%d" % (i + 1))
    if os.path.exists(path):
        os.replace(path, path + ".1")


def event_files(path: str) -> List[str]:
    """The rotation chain for `path`, oldest first: highest-numbered
    .N down to .1, then the live file. Only existing files returned."""
    suffixes = []
    for name in os.listdir(os.path.dirname(path) or "."):
        full = os.path.join(os.path.dirname(path) or ".", name)
        prefix = os.path.basename(path) + "."
        if name.startswith(prefix):
            tail = name[len(prefix):]
            if tail.isdigit():
                suffixes.append((int(tail), full))
    out = [full for _, full in sorted(suffixes, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def read_events(path: str, kind: Optional[str] = None) -> List[Dict]:
    """Parse an event log — including any rotated generations (.N files,
    oldest first) — skipping ANY undecodable line. A mid-write SIGKILL
    tears at most the final line; a concurrent writer can expose a
    half-record anywhere a reader races it. Either way the skip is
    counted in DECODE_ERRORS and logged, never raised, so a live
    postmortem read cannot abort. Optionally filter by event kind."""
    global DECODE_ERRORS
    out: List[Dict] = []
    try:
        files = event_files(path)
    except FileNotFoundError:
        return out
    for fname in files:
        try:
            with open(fname, "r", encoding="utf-8") as fh:
                lines = fh.read().split("\n")
        except FileNotFoundError:
            continue
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                DECODE_ERRORS += 1
                logger.warning("skipping undecodable event line in %s "
                               "(%d skipped since import)",
                               fname, DECODE_ERRORS)
                continue
            if kind is None or rec.get("event") == kind:
                out.append(rec)
    return out


__all__ = ["EventLog", "BoundEventLog", "read_events", "event_files",
           "rotate_chain", "DECODE_ERRORS", "PREEMPTION_DRAIN",
           "EMERGENCY_CHECKPOINT", "DIVERGENCE_ROLLBACK", "INIT_RETRY",
           "SLOT_ADMIT", "SLOT_RETIRE", "CHECKPOINT_RESTORE",
           "CHECKPOINT_SAVED", "CLOCK_ANCHOR", "FAULT_INJECTED",
           "REPLICA_FROZEN", "RUN_COMPLETE", "REQUEST_TIMEOUT",
           "JOB_CREATED", "GANG_RESTART", "GANG_STUCK", "GANG_DEGRADED",
           "PODS_READY", "FIRST_STEP_OBSERVED",
           "JOB_PACKED", "JOB_RESIZED", "GANG_RESIZE", "LIVE_SCALE",
           "AUTOSCALE_BREACH",
           "SCHED_QUEUE", "SCHED_PREEMPT", "SCHED_ADMIT",
           "SCHED_GROW_BACK", "SCHED_SKIP", "SCHED_MIGRATE",
           "FIRST_RESUME_STEP", "JOB_SUCCEEDED", "JOB_FAILED"]
