"""Prometheus text-format exporter for the worker data plane.

Renders a `core.Registry` in exposition format 0.0.4 and serves it over
the same zero-dependency ThreadingHTTPServer pattern as the operator's
`controller/metrics.py`, so Kubernetes scrapes workers exactly like it
scrapes the operator: a `/metrics` GET plus a `/healthz` liveness probe.

The renderer is shared with the control plane: `escape_label_value` and
`histogram_lines` are imported by `controller/metrics.py` so both
endpoints speak identical text format (one bug surface, not two).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .core import Histogram, Registry
from .events import read_events

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_label_value(v: str) -> str:
    """Exposition-format label escaping: backslash, double-quote, newline."""
    return (str(v).replace("\\", "\\\\")
                  .replace('"', '\\"')
                  .replace("\n", "\\n"))


def format_value(v) -> str:
    """Prometheus sample value: integers bare, floats via repr (full
    precision, no locale)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: Dict[str, str], extra: Optional[Dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in merged.items())
    return "{" + inner + "}"


def histogram_lines(h: Histogram, help_type: bool = True) -> List[str]:
    """Render one histogram: cumulative ``_bucket{le=...}`` series (the
    +Inf bucket equal to ``_count`` by construction), then _sum/_count."""
    counts, total_sum, total = h.snapshot()
    lines: List[str] = []
    if help_type:
        lines += [f"# HELP {h.name} {h.help}", f"# TYPE {h.name} histogram"]
    cum = 0
    for edge, c in zip(h.edges, counts):
        cum += c
        le = format_value(edge)
        lines.append(f"{h.name}_bucket"
                     f"{_labels_str(h.labels, {'le': le})} {cum}")
    lines.append(f"{h.name}_bucket"
                 f"{_labels_str(h.labels, {'le': '+Inf'})} {total}")
    lines.append(f"{h.name}_sum{_labels_str(h.labels)} "
                 f"{format_value(total_sum)}")
    lines.append(f"{h.name}_count{_labels_str(h.labels)} {total}")
    return lines


def render_registry(registry: Registry) -> str:
    """Full scrape body. HELP/TYPE are emitted once per metric NAME even
    when several label-sets share it (the format forbids repeats)."""
    lines: List[str] = []
    seen_names = set()
    for m in registry.collect():
        first = m.name not in seen_names
        seen_names.add(m.name)
        if m.kind == "histogram":
            lines += histogram_lines(m, help_type=first)
        else:
            if first:
                lines += [f"# HELP {m.name} {m.help}",
                          f"# TYPE {m.name} {m.kind}"]
            lines.append(f"{m.name}{_labels_str(m.labels)} "
                         f"{format_value(m.value)}")
    return "\n".join(lines) + "\n"


class TelemetryServer:
    """Worker-side /metrics + /healthz in a daemon thread.

    Same contract as the operator's MetricsServer: port 0 picks a free
    port (tests), `.port` holds the bound value, close() is idempotent.
    `healthy` is an optional callable polled by /healthz — wire it to the
    training loop's liveness signal; default is always-ok.

    `events_path` additionally serves GET /events: the process's event
    log as JSON `{"now": <server unix time>, "records": [...]}`. The
    `now` stamp is what the controller-side collector anchors per-host
    clock-offset correction on (collector.py) — it is sampled in the
    same request that ships the records, so offset = local_now - now
    holds to within one round trip. read_events tolerates the live
    writer, so a scrape never races a torn record into an error.

    `traces_path` serves GET /traces the same way for the request-trace
    span log (telemetry/trace.py): same envelope, same clock anchor, so
    the collector corrects span wall-times with the offsets it already
    learned from the metrics scrape of the same pod.
    """

    def __init__(self, registry: Registry, port: int = 0, host: str = "",
                 healthy: Optional[Callable[[], bool]] = None,
                 events_path: Optional[str] = None,
                 traces_path: Optional[str] = None):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path == "/metrics":
                    body = render_registry(outer.registry).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                elif self.path == "/events" and outer.events_path:
                    payload = {"now": time.time(),
                               "records": read_events(outer.events_path)}
                    body = (json.dumps(payload) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                elif self.path == "/traces" and outer.traces_path:
                    payload = {"now": time.time(),
                               "records": read_events(outer.traces_path)}
                    body = (json.dumps(payload) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                elif self.path == "/healthz":
                    ok = outer.healthy() if outer.healthy else True
                    body = b"ok\n" if ok else b"unhealthy\n"
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log events
                pass

        self.registry = registry
        self.healthy = healthy
        self.events_path = events_path
        self.traces_path = traces_path
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpu-worker-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


__all__ = ["CONTENT_TYPE", "TelemetryServer", "escape_label_value",
           "format_value", "histogram_lines", "render_registry"]
