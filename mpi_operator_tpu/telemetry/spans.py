"""Host-side span annotations that land in XProf traces.

`jax.profiler.TraceAnnotation` names a host-thread region in the
profiler timeline, so "schedule", "prefill", "decode_step", and
"checkpoint.save" show up NEXT TO the device ops they caused — the view
that makes a host-bound serving loop or a synchronous checkpoint stall
obvious in one screenshot.

Outside an active capture the annotation is close to free (TraceMe's
fast path is a disabled-flag check), so call sites keep their spans
unconditionally. If this jax build lacks the API the helper degrades to
a nullcontext rather than gating every caller.
"""
from __future__ import annotations

from contextlib import nullcontext

# resolved on first span() call, not at import: the telemetry package is
# shared with the CONTROL plane (controller/metrics.py reuses the
# histogram/text-format code), which must stay importable without jax
_TraceAnnotation = None
_resolved = False


def _resolve():
    global _TraceAnnotation, _resolved
    try:
        from jax.profiler import TraceAnnotation
        _TraceAnnotation = TraceAnnotation
    except ImportError:                                # pragma: no cover
        _TraceAnnotation = None
    _resolved = True


def span(name: str):
    """Context manager marking a named host region in XProf traces."""
    if not _resolved:
        _resolve()
    if _TraceAnnotation is None:                       # pragma: no cover
        return nullcontext()
    return _TraceAnnotation(name)


__all__ = ["span"]
