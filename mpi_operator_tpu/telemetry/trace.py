"""Per-request distributed tracing: span trees from front door to
final token.

Every other telemetry signal in this repo is an aggregate — federated
`tpu_job_*` histograms, counters, the merged timeline. When the
`DecodeAutoscaler` sees a TTFT p99 breach, aggregates cannot answer
"which requests were slow, and in which hop". This module adds the
missing per-request layer: a lightweight tracer whose span records
thread through the whole serving path (router queue → admission →
prefill → KV handoff → decode), federate like everything else, and
attach to SLO-breach incidents as exemplars.

Design constraints, in order:

1. **Off-path when sampled out.** `begin_request` on an unsampled
   trace id is ONE integer hash against a precomputed threshold and
   returns None before any allocation — pinned by a unit test. Serving
   hot loops pay nothing for traces they don't keep.
2. **Hop durations sum to end-to-end latency.** A request trace is a
   chain of contiguous "hops": `begin_hop(name, t0)` closes the
   currently-open hop AT `t0` and opens the next, so there are no gaps
   or overlaps by construction and `sum(hop.seconds) == retire - t0`
   exactly on the session clock. The router benchmark gates on this.
3. **One root per request id, across replicas.** The tracer owns the
   registry of open request traces keyed by trace id (= request id);
   `begin_request` returns the existing trace when the id is already
   open, so a failover replay — a fresh `Request` object with the SAME
   id dispatched to a different replica — continues the ONE trace it
   already has. Failovers/sheds land as span events on that root.
4. **Crash-durable sink.** Span records reuse the events.EventLog
   discipline: one fsync'd JSON line per completed span, tolerant
   torn-tail reads, size-based rotation. A mid-kill loses at most the
   last line; everything already retired is attributable post-mortem.

Record schema (one line per COMPLETED span in `traces.jsonl`):

    {"ts": <wall clock at write>, "event": "span",
     "trace": <trace id = request id; negative for engine sessions>,
     "span": <span id, unique per tracer>, "parent": <span id|null>,
     "name": "serve.prefill", "t0": <session-clock start>,
     "seconds": <duration>, "status": "ok|timeout|shed|failover",
     "attrs": {...}, "events": [{"name": "failover", ...}, ...]}

`t0`/`seconds` are session-clock (monotonic, shared by the router and
every replica it drives) so durations and intra-pod ordering are
exact; `ts` is wall clock so the collector's ClockSync correction can
order spans across pods the same way it orders events.

Span taxonomy (the XProf annotations in telemetry/spans.py use the
same names from the same call sites, so host traces and span trees
agree):

    serve.request            root, t0 = arrival, status terminal
      router.queue_wait      arrival → router dispatch decision
      serve.admission        dispatch → scheduler admits (slot bound)
      serve.prefill          admission → last prompt chunk landed
      serve.kv_handoff       disagg only: prefill done → pages moved
                             into the decode pool (attrs: pages,
                             cached_pages)
      serve.decode           first decode-eligible moment → retire
    serve.session            per-engine root (negative trace id)
      serve.decode_step      one dispatched decode batch (attr: batch)
      serve.verify_step      one spec-decode verify batch (attrs:
                             accepted, proposed)
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

from .events import EventLog, read_events

# Event kind for span records: trace sinks ARE event logs, so the
# torn-tail-tolerant reader, rotation, and shell greps all apply.
SPAN = "span"

# Root span names. Request roots are per-request (trace id >= 0);
# session roots are per-engine-session (negative synthetic trace id)
# and parent the batch-level decode/verify spans, which have no single
# owning request.
REQUEST_ROOT = "serve.request"
SESSION_ROOT = "serve.session"

# Histogram buckets for the federated per-hop latency breakdown
# (`tpu_job_trace_hop_seconds{hop=...}`): serving hops span ~100us
# page copies to multi-second decode tails.
TRACE_HOP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_MASK64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a deterministic 64-bit mix of the trace
    id. Used instead of hash() so head-sampling decisions are stable
    across processes/PYTHONHASHSEED — every pod keeps the SAME subset
    of trace ids, which is what makes cross-pod trees reconstructable
    for sampled traces."""
    x &= _MASK64
    x = ((x ^ (x >> 33)) * 0xFF51AFD7ED558CCD) & _MASK64
    x = ((x ^ (x >> 33)) * 0xC4CEB9FE1A85EC53) & _MASK64
    return x ^ (x >> 33)


class RequestTrace:
    """The open span tree of ONE in-flight request.

    A chain of contiguous hops under a single root: `begin_hop` closes
    the open hop at the new hop's t0 (no gaps, no overlaps — durations
    sum to end-to-end), `finish` closes the last hop and the root with
    the terminal status, `abandon` closes the open hop as a failover
    casualty while leaving the root open for the replay. Completed
    hops are emitted to the sink immediately; the root is emitted at
    finish, which is also when the tracer registry forgets the id.
    """

    __slots__ = ("_tracer", "trace", "root_id", "t0", "attrs",
                 "_events", "_hop", "_edge", "done", "status")

    def __init__(self, tracer: "Tracer", trace: int, t0: float,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.trace = trace
        self.root_id = tracer._next_span_id()
        self.t0 = t0
        self.attrs = attrs
        self._events: List[Dict[str, Any]] = []
        # open hop: [name, t0, attrs] or None
        self._hop: Optional[List[Any]] = None
        # the trailing edge of the hop chain: where the last hop closed
        # (= where an implicit next hop begins); starts at arrival
        self._edge = t0
        self.done = False
        self.status: Optional[str] = None

    def event(self, name: str, **attrs) -> None:
        """Attach a point-in-time event (shed/failover/dispatch/...)
        to the root span."""
        self._events.append({"name": name, **attrs})

    def hop_attrs(self, **attrs) -> None:
        """Merge attributes into the currently open hop (e.g. page
        counts onto serve.kv_handoff before the decode hop opens)."""
        if self._hop is not None:
            self._hop[2].update(attrs)

    def _close_hop(self, t1: float, status: str) -> None:
        if self._hop is None:
            self._edge = max(self._edge, t1)
            return
        name, h0, attrs = self._hop
        self._hop = None
        self._edge = max(h0, t1)
        self._tracer._record(self.trace, self._tracer._next_span_id(),
                             self.root_id, name, h0,
                             max(0.0, t1 - h0), status, attrs)

    def begin_hop(self, name: str, t0: Optional[float] = None,
                  **attrs) -> None:
        """Open the next hop at `t0`, closing the open one there.

        t0=None means "wherever the previous hop ended" (or the root
        t0 when this is the first hop) — the contiguity default used
        when the caller has no better clock reading than "immediately
        after the previous stage"."""
        if self.done:
            return
        if t0 is None:
            t0 = self._hop[1] if self._hop is not None else self._edge
        self._close_hop(t0, "ok")
        self._hop = [name, t0, dict(attrs)]

    def abandon(self, now: float, status: str = "failover") -> None:
        """The replica serving this request died (or drained): close
        the open hop with `status`, keep the root open — the router's
        replay continues THIS trace on the surviving replica."""
        self._close_hop(now, status)

    def finish(self, status: str, t1: float) -> None:
        """Terminal: close the open hop and the root with `status`
        (ok / timeout / shed / failover) and emit the root record.
        Idempotent — the first terminal status wins, matching the
        router's collect-once-per-request-id discipline."""
        if self.done:
            return
        self.done = True
        self.status = status
        self._close_hop(t1, status)
        self._tracer._record(self.trace, self.root_id, None,
                             REQUEST_ROOT, self.t0,
                             max(0.0, t1 - self.t0), status, self.attrs,
                             self._events or None)
        self._tracer._requests.pop(self.trace, None)


class SessionSpan:
    """Per-engine-session root for batch-level spans.

    Decode steps and spec-verify batches serve MANY requests at once,
    so they cannot parent under any single request root. Each engine
    session instead opens one synthetic root (negative trace id, so it
    can never collide with a request id) and records each dispatched
    batch as a child at sync time. `end` closes it normally; `abandon`
    closes it as a failover casualty when the router kills the replica
    mid-session — either way the root is always emitted, so batch
    children are never orphaned."""

    __slots__ = ("_tracer", "trace", "span_id", "t0", "attrs", "done")

    def __init__(self, tracer: "Tracer", t0: float,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.trace = -tracer._next_session_id()
        self.span_id = tracer._next_span_id()
        self.t0 = t0
        self.attrs = attrs
        self.done = False

    def child(self, name: str, t0: float, seconds: float,
              **attrs) -> None:
        if not self.done:
            self._tracer._record(self.trace,
                                 self._tracer._next_span_id(),
                                 self.span_id, name, t0,
                                 max(0.0, seconds), "ok", attrs)

    def end(self, t1: float, status: str = "ok") -> None:
        if self.done:
            return
        self.done = True
        self._tracer._record(self.trace, self.span_id, None,
                             SESSION_ROOT, self.t0,
                             max(0.0, t1 - self.t0), status, self.attrs)

    def abandon(self, t1: float) -> None:
        self.end(t1, status="failover")


class Tracer:
    """Head-sampling request tracer with a bounded ring and an
    optional fsync'd JSONL sink.

    `sample` is the head-sampling rate, decided PER TRACE ID by a
    deterministic 64-bit hash against a precomputed threshold: the
    sampled-out path is one integer mix + compare, no allocation, and
    every process keeping rate-p traces keeps the SAME ids.
    `force_sample(id)` overrides the hash for ids a breach handler
    wants kept regardless of rate. `path=None` keeps spans only in the
    in-memory ring (bench percentiles); with a path, every completed
    span is one fsync'd line in `traces.jsonl`."""

    def __init__(self, path: Optional[str] = None, sample: float = 1.0,
                 ring: int = 8192, clock=None):
        self.sample = sample
        # threshold in hash space: sample=1.0 keeps everything without
        # ever consulting the hash; 0.0 keeps only forced ids
        self._threshold = int(min(max(sample, 0.0), 1.0) * (_MASK64 + 1))
        self._forced: set = set()
        self._log: Optional[EventLog] = \
            EventLog(path, **({"clock": clock} if clock else {})) \
            if path else None
        self.ring: Deque[Dict[str, Any]] = collections.deque(maxlen=ring)
        self._lock = threading.Lock()
        self._span_seq = 0
        self._session_seq = 0
        self._requests: Dict[int, RequestTrace] = {}

    # -- sampling ---------------------------------------------------------
    def sampled(self, trace_id: int) -> bool:
        """The off-path check: hash + compare, nothing else."""
        if self._threshold > _MASK64:
            return True
        return (trace_id in self._forced
                or _mix64(trace_id) < self._threshold)

    def force_sample(self, trace_id: int) -> None:
        """Keep this id regardless of the sampling rate — the hook a
        breach handler uses to guarantee its exemplar exists next
        window."""
        self._forced.add(trace_id)

    # -- request traces ---------------------------------------------------
    def begin_request(self, trace_id: int, t0: float,
                      **attrs) -> Optional[RequestTrace]:
        """Open (or join) the trace for `trace_id`.

        Returns the EXISTING open trace when the id is already live —
        the router opened it at intake, or this is a failover replay —
        so root ownership is simply "whoever asked first". Returns
        None without allocating when the id is sampled out."""
        rt = self._requests.get(trace_id)
        if rt is not None:
            return rt
        if not self.sampled(trace_id):
            return None
        rt = RequestTrace(self, trace_id, t0, dict(attrs))
        self._requests[trace_id] = rt
        return rt

    def active(self, trace_id: int) -> Optional[RequestTrace]:
        """The open trace for `trace_id`, or None (finished, sampled
        out, or never begun)."""
        return self._requests.get(trace_id)

    def begin_session(self, t0: float, **attrs) -> SessionSpan:
        """Open a per-engine-session root for batch-level spans."""
        return SessionSpan(self, t0, dict(attrs))

    # -- plumbing ---------------------------------------------------------
    def _next_span_id(self) -> int:
        with self._lock:
            self._span_seq += 1
            return self._span_seq

    def _next_session_id(self) -> int:
        with self._lock:
            self._session_seq += 1
            return self._session_seq

    def _record(self, trace: int, span: int, parent: Optional[int],
                name: str, t0: float, seconds: float, status: str,
                attrs: Dict[str, Any],
                events: Optional[List[Dict[str, Any]]] = None) -> None:
        rec: Dict[str, Any] = {
            "trace": trace, "span": span, "parent": parent,
            "name": name, "t0": round(t0, 6),
            "seconds": round(seconds, 6), "status": status,
        }
        if attrs:
            rec["attrs"] = attrs
        if events:
            rec["events"] = events
        self.ring.append(rec)
        if self._log is not None:
            self._log.emit(SPAN, **rec)

    @property
    def path(self) -> Optional[str]:
        return self._log.path if self._log is not None else None

    def open_requests(self) -> List[int]:
        """Trace ids begun but not yet finished — the completeness
        invariant the chaos leg asserts drains to empty."""
        return list(self._requests)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# A shared do-nothing check for "is tracing even on": call sites guard
# with `if tracer is not None and (rt := tracer.begin_request(...))`.


# -- reading + analysis ---------------------------------------------------

def read_trace_spans(path: str) -> List[Dict[str, Any]]:
    """All span records from a traces.jsonl chain (rotated generations
    included), torn tails skipped — the same tolerant read discipline
    as the event log, because it IS an event log."""
    return read_events(path, kind=SPAN)


def build_trees(spans: Iterable[Dict[str, Any]]
                ) -> Dict[int, Dict[str, Any]]:
    """Group spans into {trace_id: {"root": span|None, "spans": [...]}}.

    Duplicate (trace, span) records — a file re-read, a federated
    re-ingest — keep the first occurrence only, which is also the
    failover-dedup guarantee: one root record per request id no matter
    how many replicas touched it."""
    trees: Dict[int, Dict[str, Any]] = {}
    seen: set = set()
    for s in spans:
        key = (s.get("trace"), s.get("span"))
        if key in seen:
            continue
        seen.add(key)
        t = trees.setdefault(s["trace"], {"root": None, "spans": []})
        t["spans"].append(s)
        if s.get("parent") is None:
            t["root"] = s
    for t in trees.values():
        t["spans"].sort(key=lambda s: (s.get("t0", 0.0), s.get("span", 0)))
    return trees


def hop_spans(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Request hops only: children of request roots (trace >= 0),
    excluding session batch spans and the roots themselves."""
    return [s for s in spans
            if s.get("trace", -1) >= 0 and s.get("parent") is not None]


def hop_name(span: Dict[str, Any]) -> str:
    """Short hop label for metric dimensions: the span name minus its
    component prefix ("router.queue_wait" -> "queue_wait")."""
    return span.get("name", "").rsplit(".", 1)[-1]


def trace_sum_gap(tree: Dict[str, Any]) -> Optional[float]:
    """|sum(hop seconds) - root seconds| for one trace, or None when
    the tree has no root. Contiguous hops make this ~0 (float noise)
    on a single clock; cross-pod it is bounded by the clock-correction
    tolerance."""
    root = tree.get("root")
    if root is None:
        return None
    hops = [s for s in tree["spans"] if s.get("parent") is not None]
    return abs(sum(s.get("seconds", 0.0) for s in hops)
               - root.get("seconds", 0.0))


def orphan_spans(spans: Iterable[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Spans whose trace never recorded a root — the invariant the
    mid-trace replica-kill chaos leg drives to zero."""
    out: List[Dict[str, Any]] = []
    for tree in build_trees(spans).values():
        if tree["root"] is None:
            out.extend(tree["spans"])
    return out


def hop_percentiles(spans: Iterable[Dict[str, Any]],
                    ps: Tuple[int, ...] = (50, 99)
                    ) -> Dict[str, float]:
    """{"<hop>_p50_ms": ..., "<hop>_p99_ms": ...} across all request
    hops — the per-hop breakdown bench.py folds into its serving-leg
    JSONL records."""
    by_hop: Dict[str, List[float]] = {}
    for s in hop_spans(spans):
        by_hop.setdefault(hop_name(s), []).append(s.get("seconds", 0.0))
    out: Dict[str, float] = {}
    for hop, xs in sorted(by_hop.items()):
        xs.sort()
        for p in ps:
            idx = min(len(xs) - 1, max(0, int(round(
                (p / 100.0) * (len(xs) - 1)))))
            out[f"{hop}_p{p}_ms"] = round(xs[idx] * 1e3, 3)
    return out


def render_tree(tree: Dict[str, Any], indent: str = "  ") -> List[str]:
    """One trace as indented hop lines with durations — the postmortem
    "slow traces:" rendering.

        serve.request 812.4ms status=timeout
          router.queue_wait 3.1ms
          serve.admission 0.4ms
          ...
    """
    lines: List[str] = []
    root = tree.get("root")
    spans = tree.get("spans", [])

    def fmt(s: Dict[str, Any]) -> str:
        ms = s.get("seconds", 0.0) * 1e3
        extra = ""
        attrs = s.get("attrs")
        if attrs:
            extra = " " + " ".join(f"{k}={v}"
                                   for k, v in sorted(attrs.items()))
        status = s.get("status", "ok")
        tag = f" status={status}" if status != "ok" else ""
        return f"{s.get('name')} {ms:.1f}ms{tag}{extra}"

    if root is not None:
        lines.append(fmt(root))
        for ev in root.get("events") or []:
            kv = " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                          if k != "name")
            lines.append(f"{indent}@ {ev.get('name')}"
                         + (f" {kv}" if kv else ""))
    for s in spans:
        if s.get("parent") is None:
            continue
        lines.append(indent + fmt(s))
    return lines


__all__ = [
    "REQUEST_ROOT", "SESSION_ROOT", "SPAN", "TRACE_HOP_BUCKETS",
    "RequestTrace", "SessionSpan", "Tracer", "build_trees",
    "hop_name", "hop_percentiles", "hop_spans", "orphan_spans",
    "read_trace_spans", "render_tree", "trace_sum_gap",
]
