"""Per-worker instrument bundles: the named series the data plane exports.

This is the naming contract in one place — trainers and the serving
engine take a bundle and bump instruments; they never invent series
names. Everything is prefixed ``tpu_worker_`` (the operator owns
``tpu_operator_``), so one Prometheus config scrapes both planes without
collisions.

Train series (LMTrainer / Trainer / PipelineLMTrainer benchmark loops):
  step_seconds            histogram — per-step wall time (host-synced)
  tokens_per_sec          gauge     — last-window LM throughput
  examples_per_sec        gauge     — last-window image throughput
  mfu                     gauge     — model FLOPs utilization, 0-1
  goodput                 gauge     — productive / total steps, 0-1
  host_gap_seconds        histogram — host blocked-on-device time per
                                      window fetch (how much of the step
                                      the async dispatch did NOT hide)
  step                    gauge     — last observed global step (the
                                      controller's restart-aware
                                      goodput reads this frontier)
  last_checkpoint_step    gauge     — newest durable checkpoint step
  restore_step            gauge     — step this incarnation restored
                                      from (0 when fresh)
  restore_seconds         gauge     — wall seconds the restore took
                                      (parallel resharded reads included)
  resume_step_seconds     gauge     — restore-done → first post-resume
                                      step (the recompile phase of a
                                      gang resize; collector folds it
                                      into tpu_job_resize_seconds)
  steps_total             counter   — steps executed
  skipped_steps_total     counter   — divergence-guard skipped (lower
                                      bound: streaks are sampled at
                                      window fetches, resets between
                                      fetches are invisible)
  rollback_steps_total    counter   — steps rewound by rollbacks

Serve series (ServingEngine):
  ttft_seconds            histogram — request arrival → first token
  tpot_seconds            histogram — inter-token gap per slot
  prefill_seconds         histogram — prefill chunk dispatch (async: host
                                      wall time, not device time)
  decode_step_seconds     histogram — decode step dispatch → token sync
                                      (async: spans the loop iteration
                                      that hid under it)
  host_gap_seconds        histogram — host blocked on the device token
                                      read per step (≈0 when the decode
                                      fully hides under host scheduling)
  queue_depth             gauge     — requests waiting for a slot
  slot_occupancy          gauge     — slots currently bound
  slots                   gauge     — configured slot count
  step_compiles           gauge     — decode-step compile count
  prefill_compiles        gauge     — prefill compile count
  requests_total          counter   — requests retired
  tokens_total            counter   — new tokens emitted
  kv_pages_total          gauge     — usable KV pages (paged mode;
                                      pool minus the trash page)
  kv_pages_in_use         gauge     — pages referenced by live requests
  kv_pages_cached         gauge     — idle prefix-cache pages retained
                                      for future lookups (evictable)
  prefix_hit_pages_total  counter   — prompt pages served from the
                                      prefix cache at admission
  prefix_miss_pages_total counter   — prompt pages prefilled cold
  kv_handoff_seconds      histogram — disaggregated serving: one
                                      prefill→decode page handoff,
                                      install + copy dispatch (host
                                      wall time, async like prefill)
  kv_handoff_pages_total  counter   — KV pages moved between pools
                                      (decode-side prefix hits move
                                      nothing and are NOT counted)
  spec_proposed_total     counter   — draft tokens sent to a verify
                                      step (speculative decoding)
  spec_accepted_total     counter   — draft tokens that matched the
                                      model's argmax and were emitted
  spec_acceptance_ratio   histogram — accepted/proposed per row per
                                      verify step (0-1)
  spec_tokens_per_step    histogram — tokens emitted per row per verify
                                      step (accepted + the model's own
                                      bonus token; >1 is the speedup)

Disaggregated serving creates one ServeTelemetry per pool with
``labels={"pool": "prefill"|"decode"}`` on a shared registry — the same
bundle-per-label-set pattern as the fused trainer — so every serve
series above federates per pool (tpu_job_queue_depth{pool="decode"}).

Router series (serve/router.py front door, prefixed ``tpu_router_``;
the collector federates these into ``tpu_job_router_*``):
  dispatch_total            counter   — requests dispatched, one series
                                        per replica ({replica="N"})
  shed_total                counter   — requests rejected at the front
                                        door (every replica at its
                                        in-flight cap)
  requests_total            counter   — requests completed through the
                                        router (sheds excluded)
  resubmits_total           counter   — in-flight requests replayed to
                                        survivors after a replica death
  replica_deaths_total      counter   — replicas marked dead from
                                        failed dispatches
  affinity_hit_pages_total  counter   — prompt pages predicted warm on
                                        the chosen replica at dispatch
  affinity_miss_pages_total counter   — prompt pages predicted cold
  queue_wait_seconds        histogram — arrival → dispatch wait at the
                                        front door
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional

from .core import Registry
from .events import EventLog, read_events
from .prometheus import TelemetryServer, render_registry


class TrainTelemetry:
    """Train-loop instruments over a shared registry.

    ``labels`` stamps every instrument in the bundle with the same label
    set, so several bundles can share one registry and render as distinct
    series under the same names — the HFTA fused trainer creates one
    bundle per packed replica (``labels={"replica": "3"}``) and the
    controller packing path one per job (``labels={"job": name}``).
    """

    def __init__(self, registry: Optional[Registry] = None,
                 labels: Optional[Dict[str, str]] = None):
        reg = registry if registry is not None else Registry()
        self.registry = reg
        self.labels = dict(labels) if labels else None
        labels = self.labels
        self.step_seconds = reg.histogram(
            "tpu_worker_step_seconds", "per-step wall time (seconds)",
            labels=labels)
        self.host_gap_seconds = reg.histogram(
            "tpu_worker_host_gap_seconds",
            "host blocked-on-device time at window fetches",
            lo=1e-5, hi=1e3, labels=labels)
        self.tokens_per_sec = reg.gauge(
            "tpu_worker_tokens_per_sec", "last-window LM tokens/sec",
            labels=labels)
        self.examples_per_sec = reg.gauge(
            "tpu_worker_examples_per_sec", "last-window examples/sec",
            labels=labels)
        self.mfu = reg.gauge(
            "tpu_worker_mfu", "model FLOPs utilization (0-1)",
            labels=labels)
        self.goodput = reg.gauge(
            "tpu_worker_goodput", "productive steps / total steps (0-1)",
            labels=labels)
        self.step = reg.gauge(
            "tpu_worker_step", "last observed global step",
            labels=labels)
        self.last_checkpoint_step = reg.gauge(
            "tpu_worker_last_checkpoint_step",
            "newest durable checkpoint's global step",
            labels=labels)
        self.restore_step = reg.gauge(
            "tpu_worker_restore_step",
            "global step this incarnation restored from (0 = fresh)",
            labels=labels)
        self.restore_seconds = reg.gauge(
            "tpu_worker_restore_seconds",
            "wall seconds this incarnation's checkpoint restore took",
            labels=labels)
        self.resume_step_seconds = reg.gauge(
            "tpu_worker_resume_step_seconds",
            "restore-done to first post-resume step wall seconds "
            "(compile included)",
            labels=labels)
        self.steps_total = reg.counter(
            "tpu_worker_steps_total", "train steps executed",
            labels=labels)
        self.skipped_steps_total = reg.counter(
            "tpu_worker_skipped_steps_total",
            "divergence-guard skipped steps (lower bound)",
            labels=labels)
        self.rollback_steps_total = reg.counter(
            "tpu_worker_rollback_steps_total",
            "steps rewound by divergence rollbacks",
            labels=labels)
        self._lock = threading.Lock()
        self._last_streak = 0
        self.goodput.set(1.0)

    def observe_step(self, seconds: float) -> None:
        self.step_seconds.observe(seconds)
        self.steps_total.inc()

    def observe_steps(self, avg_seconds: float, n: int) -> None:
        """Fold a window's worth of steps in as n observations of the
        window-average step time (the only per-step number an async
        dispatch loop can honestly report — see benchmark loops)."""
        self.step_seconds.observe_n(avg_seconds, n)
        self.steps_total.inc(n)

    def update_window(self, tokens_per_sec: Optional[float] = None,
                      examples_per_sec: Optional[float] = None,
                      mfu: Optional[float] = None,
                      step: Optional[int] = None) -> None:
        if tokens_per_sec is not None:
            self.tokens_per_sec.set(tokens_per_sec)
        if examples_per_sec is not None:
            self.examples_per_sec.set(examples_per_sec)
        if mfu is not None:
            self.mfu.set(mfu)
        if step is not None:
            self.step.set(int(step))

    def record_streak(self, streak: int) -> int:
        """Fold a window-fetch `nonfinite_streak` reading into the skipped
        counter. Streaks are only visible at fetches, so this is a lower
        bound: a streak that grew keeps its overlap with the previous
        reading; one that reset and regrew is all new skips."""
        streak = int(streak)
        with self._lock:
            if streak <= 0:
                new = 0
            elif streak > self._last_streak:
                new = streak - self._last_streak
            else:
                new = streak
            self._last_streak = streak
        if new:
            self.skipped_steps_total.inc(new)
            self._update_goodput()
        return new

    def record_rollback(self, steps_rewound: int) -> None:
        with self._lock:
            self._last_streak = 0
        if steps_rewound > 0:
            self.rollback_steps_total.inc(steps_rewound)
        self._update_goodput()

    def _update_goodput(self) -> None:
        total = self.steps_total.value
        if total <= 0:
            return
        lost = (self.skipped_steps_total.value
                + self.rollback_steps_total.value)
        self.goodput.set(max(0.0, 1.0 - lost / total))

    def step_percentiles_ms(self):
        """(p50, p99) step time in milliseconds, Nones when empty — the
        summary bench legs embed in their JSONL records."""
        p50 = self.step_seconds.percentile(50)
        p99 = self.step_seconds.percentile(99)
        to_ms = lambda v: None if v is None else v * 1e3  # noqa: E731
        return to_ms(p50), to_ms(p99)

    def host_gap_percentiles_ms(self):
        """(p50, p99) host blocked-on-device time in milliseconds, Nones
        when empty. One observation per window fetch: the wall time of
        the device read that closes the window — everything else in the
        loop body is async dispatch, so this is the only place the host
        actually waits and the honest measure of how much step time the
        dispatch pipeline failed to hide."""
        p50 = self.host_gap_seconds.percentile(50)
        p99 = self.host_gap_seconds.percentile(99)
        to_ms = lambda v: None if v is None else v * 1e3  # noqa: E731
        return to_ms(p50), to_ms(p99)


class ServeTelemetry:
    """Serving-engine instruments over a shared registry.

    ``labels`` stamps every instrument with the same label set (the
    TrainTelemetry pattern): the disaggregated facade creates one
    bundle per pool (``labels={"pool": "prefill"}`` / ``"decode"``) on
    a shared registry, so per-pool series federate side by side."""

    def __init__(self, registry: Optional[Registry] = None,
                 labels: Optional[Dict[str, str]] = None):
        reg = registry if registry is not None else Registry()
        self.registry = reg
        self.labels = dict(labels) if labels else None
        labels = self.labels
        # serving latencies reach sub-100µs on real accelerators; start
        # the buckets a decade lower than the train histogram
        hist = lambda n, h: reg.histogram(  # noqa: E731
            n, h, lo=1e-5, hi=1e3, labels=labels)
        self.ttft_seconds = hist(
            "tpu_worker_ttft_seconds", "request arrival to first token")
        self.tpot_seconds = hist(
            "tpu_worker_tpot_seconds", "inter-token gap per slot")
        self.prefill_seconds = hist(
            "tpu_worker_prefill_seconds",
            "prefill chunk host dispatch time (async)")
        self.decode_step_seconds = hist(
            "tpu_worker_decode_step_seconds",
            "decode step wall time, dispatch to token sync")
        self.host_gap_seconds = hist(
            "tpu_worker_host_gap_seconds",
            "host blocked on the device token read per step")
        self.kv_handoff_seconds = hist(
            "tpu_worker_kv_handoff_seconds",
            "prefill->decode KV page handoff, install + copy dispatch")
        self.queue_depth = reg.gauge(
            "tpu_worker_queue_depth", "requests waiting for a slot",
            labels=labels)
        self.slot_occupancy = reg.gauge(
            "tpu_worker_slot_occupancy", "slots currently bound",
            labels=labels)
        self.slots = reg.gauge(
            "tpu_worker_slots", "configured decode slots", labels=labels)
        self.step_compiles = reg.gauge(
            "tpu_worker_step_compiles", "decode-step compile count",
            labels=labels)
        self.prefill_compiles = reg.gauge(
            "tpu_worker_prefill_compiles", "prefill compile count",
            labels=labels)
        self.requests_total = reg.counter(
            "tpu_worker_requests_total", "requests retired",
            labels=labels)
        self.tokens_total = reg.counter(
            "tpu_worker_tokens_total", "new tokens emitted",
            labels=labels)
        self.kv_handoff_pages = reg.counter(
            "tpu_worker_kv_handoff_pages_total",
            "KV pages moved prefill->decode (prefix hits excluded)",
            labels=labels)
        self.pages_total = reg.gauge(
            "tpu_worker_kv_pages_total",
            "usable KV pages (paged mode; pool minus the trash page)",
            labels=labels)
        self.pages_in_use = reg.gauge(
            "tpu_worker_kv_pages_in_use",
            "KV pages referenced by live requests", labels=labels)
        self.pages_cached = reg.gauge(
            "tpu_worker_kv_pages_cached",
            "idle prefix-cache pages retained for future lookups",
            labels=labels)
        self.prefix_hit_pages = reg.counter(
            "tpu_worker_prefix_hit_pages_total",
            "prompt pages served from the prefix cache at admission",
            labels=labels)
        self.prefix_miss_pages = reg.counter(
            "tpu_worker_prefix_miss_pages_total",
            "prompt pages prefilled cold", labels=labels)
        self.spec_proposed_total = reg.counter(
            "tpu_worker_spec_proposed_total",
            "draft tokens sent to speculative verify steps",
            labels=labels)
        self.spec_accepted_total = reg.counter(
            "tpu_worker_spec_accepted_total",
            "draft tokens accepted (matched the model's argmax)",
            labels=labels)
        # ratio/count histograms, not latencies: buckets spanning
        # [0.01, 1] and [1, draft_k+1] at the default resolution — the
        # latency bundle's 1e-5 floor would waste 3 decades of edges
        self.spec_acceptance_ratio = reg.histogram(
            "tpu_worker_spec_acceptance_ratio",
            "accepted/proposed drafts per row per verify step",
            lo=1e-2, hi=1.0, labels=labels)
        self.spec_tokens_per_step = reg.histogram(
            "tpu_worker_spec_tokens_per_step",
            "tokens emitted per row per verify step (bonus included)",
            lo=1.0, hi=64.0, labels=labels)


class RouterTelemetry:
    """Serving-router (front door) instruments over a shared registry.

    Per-replica dispatch counters follow the bundle-per-label-set
    pattern lazily: ``dispatch_for(i)`` creates the ``{replica="i"}``
    series on first use, so the bundle needs no up-front fleet size
    (failover can retarget a shrunken fleet without dead series).

    Push-based load reports land here too: ``note_heartbeat(i, ...)``
    stores the newest report per replica (``heartbeat(i)`` reads it
    back — the router's dispatch scoring prefers a fresh report over
    probing engine state) and mirrors it into lazy
    ``tpu_router_replica_{queue_depth,free_slots,free_pages}{replica=}``
    gauges so a scrape sees the same picture the router routes on."""

    def __init__(self, registry: Optional[Registry] = None,
                 labels: Optional[Dict[str, str]] = None):
        reg = registry if registry is not None else Registry()
        self.registry = reg
        self.labels = dict(labels) if labels else None
        labels = self.labels
        self.shed_total = reg.counter(
            "tpu_router_shed_total",
            "requests rejected at the front door (fleet saturated)",
            labels=labels)
        self.requests_total = reg.counter(
            "tpu_router_requests_total",
            "requests completed through the router (sheds excluded)",
            labels=labels)
        self.resubmits_total = reg.counter(
            "tpu_router_resubmits_total",
            "in-flight requests replayed to survivors after a replica "
            "death", labels=labels)
        self.replica_deaths = reg.counter(
            "tpu_router_replica_deaths_total",
            "replicas marked dead from failed dispatches", labels=labels)
        self.affinity_hit_pages = reg.counter(
            "tpu_router_affinity_hit_pages_total",
            "prompt pages predicted warm on the chosen replica at "
            "dispatch", labels=labels)
        self.affinity_miss_pages = reg.counter(
            "tpu_router_affinity_miss_pages_total",
            "prompt pages predicted cold at dispatch", labels=labels)
        self.queue_wait_seconds = reg.histogram(
            "tpu_router_queue_wait_seconds",
            "arrival to dispatch wait at the front door",
            lo=1e-5, hi=1e3, labels=labels)
        self.attach_total = reg.counter(
            "tpu_router_attach_total",
            "replicas joined live (scale-up steps, no gang restart)",
            labels=labels)
        self.detach_total = reg.counter(
            "tpu_router_detach_total",
            "replicas drained and detached live (scale-down steps)",
            labels=labels)
        self._dispatch: Dict[int, object] = {}
        self._heartbeats: Dict[int, Dict[str, float]] = {}
        self._hb_gauges: Dict[int, tuple] = {}

    def dispatch_for(self, replica: int):
        """The ``tpu_router_dispatch_total{replica="N"}`` counter,
        created on first use."""
        c = self._dispatch.get(replica)
        if c is None:
            merged = dict(self.labels or {})
            merged["replica"] = str(replica)
            c = self.registry.counter(
                "tpu_router_dispatch_total",
                "requests dispatched to this replica", labels=merged)
            self._dispatch[replica] = c
        return c

    def note_heartbeat(self, replica: int, now: float, queue_depth: int,
                       free_slots: int, free_pages: int) -> None:
        """Record one replica load report (engine heartbeat). `now` is
        SESSION time — staleness is judged on the same clock the router
        runs on, so wall-clock skew can never mark a fresh report
        stale."""
        self._heartbeats[replica] = {
            "now": float(now), "queue_depth": float(queue_depth),
            "free_slots": float(free_slots),
            "free_pages": float(free_pages)}
        gauges = self._hb_gauges.get(replica)
        if gauges is None:
            merged = dict(self.labels or {})
            merged["replica"] = str(replica)
            gauges = (
                self.registry.gauge(
                    "tpu_router_replica_queue_depth",
                    "queue depth last reported by this replica's "
                    "heartbeat", labels=merged),
                self.registry.gauge(
                    "tpu_router_replica_free_slots",
                    "free slots last reported by this replica's "
                    "heartbeat", labels=merged),
                self.registry.gauge(
                    "tpu_router_replica_free_pages",
                    "free+evictable KV pages last reported by this "
                    "replica's heartbeat", labels=merged))
            self._hb_gauges[replica] = gauges
        gauges[0].set(queue_depth)
        gauges[1].set(free_slots)
        gauges[2].set(free_pages)

    def heartbeat(self, replica: int) -> Optional[Dict[str, float]]:
        """The newest load report for one replica (None before the
        first beat). The caller judges freshness against its own
        staleness threshold."""
        return self._heartbeats.get(replica)


class WorkerTelemetry:
    """One per worker process: shared registry + lazy train/serve bundles
    + optional /metrics server + optional event log. Both hot loops feed
    the SAME registry, so one scrape shows train and serve series side by
    side (a worker can do both — e.g. background eval during serving).

    Two transports, one payload shape. Pull: `serve()` exposes /metrics,
    /events and /traces for the collector to scrape. Push: `push_report()`
    bundles the same three bodies (text-format metrics, event records,
    trace-span records) plus a `now` clock anchor into one JSON dict, and
    `push(url)` POSTs it — call it on the heartbeat cadence from the same
    loop that beats the router, so a NAT'd or sidecar-less worker reports
    without being reachable. JobObservatory.ingest_push accepts the dict
    with scrape-identical bookkeeping: same staleness convention, same
    clock correction, same fault-injection surface."""

    def __init__(self, registry: Optional[Registry] = None,
                 events: Optional[EventLog] = None,
                 traces_path: Optional[str] = None):
        self.registry = registry if registry is not None else Registry()
        self.events = events
        self.traces_path = traces_path
        self._train: Optional[TrainTelemetry] = None
        self._serving: Optional[ServeTelemetry] = None
        self._server: Optional[TelemetryServer] = None

    @property
    def train(self) -> TrainTelemetry:
        if self._train is None:
            self._train = TrainTelemetry(self.registry)
        return self._train

    @property
    def serving(self) -> ServeTelemetry:
        if self._serving is None:
            self._serving = ServeTelemetry(self.registry)
        return self._serving

    def serve(self, port: int = 0, host: str = "",
              healthy=None) -> TelemetryServer:
        if self._server is None:
            # export the event log alongside /metrics: the controller's
            # collector pulls /events with the same scrape and merges
            # the records into the job timeline (clock-offset corrected)
            events_path = self.events.path if self.events else None
            self._server = TelemetryServer(
                self.registry, port=port, host=host, healthy=healthy,
                events_path=events_path, traces_path=self.traces_path)
        return self._server

    def push_report(self) -> Dict[str, object]:
        """One push payload: the exact bodies the three GET endpoints
        would serve, in one dict. `now` is sampled here — the collector
        anchors clock correction on it just as it does for a scrape."""
        report: Dict[str, object] = {
            "now": time.time(),
            "metrics": render_registry(self.registry)}
        if self.events is not None:
            self.events.flush()
            report["events"] = read_events(self.events.path)
        if self.traces_path:
            report["traces"] = read_events(self.traces_path)
        return report

    def push(self, url: str, timeout: float = 5.0) -> bool:
        """POST push_report() to a collector ingest endpoint. Returns
        False (never raises) on transport failure — push is best-effort
        like a missed scrape; the next heartbeat retries with fresher
        data, and the collector's staleness convention covers the gap."""
        body = json.dumps(self.push_report()).encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return 200 <= resp.status < 300
        except (OSError, ValueError, urllib.error.URLError):
            return False

    @property
    def port(self) -> Optional[int]:
        return self._server.port if self._server else None

    def close(self, close_events: bool = True) -> None:
        """Shutdown order matters: the event log is flushed FIRST so the
        final records (e.g. a preemption drain) are durable even if the
        HTTP server teardown hangs or the process is about to exit(215).
        close_events=False flushes but leaves a BORROWED event log open
        (the caller that opened it closes it)."""
        if self.events is not None:
            self.events.flush()
        if self._server is not None:
            self._server.close()
            self._server = None
        if self.events is not None and close_events:
            self.events.close()


__all__ = ["RouterTelemetry", "ServeTelemetry", "TrainTelemetry",
           "WorkerTelemetry"]
