from .trainer import (  # noqa: F401
    TrainState, Trainer, TrainerConfig, cross_entropy_loss, make_sgd,
)
from .checkpoint import latest_checkpoint, restore_checkpoint, save_checkpoint  # noqa: F401,E402
from .lm_trainer import (  # noqa: F401,E402
    LMTrainer, LMTrainerConfig, LMTrainState, lm_loss, make_adamw,
)
from .pp_trainer import PipelineLMTrainer, PPTrainState  # noqa: F401,E402
from .resilience import (  # noqa: F401,E402
    DivergenceError, FaultInjector, Preempted, PreemptionListener,
    ResilienceConfig, ResilienceContext, Watchdog,
    FAULT_DIE_EXIT, PREEMPTED_EXIT, WATCHDOG_STALL_EXIT, is_retryable_exit,
)
