from .trainer import (  # noqa: F401
    TrainState, Trainer, TrainerConfig, cross_entropy_loss, make_sgd,
)
