"""Checkpoint / resume (orbax-backed).

The reference operator has NO checkpointing — it delegates to the workload
(the example merely mounts --train_dir on an emptyDir, reference
examples/tensorflow-benchmarks-imagenet.yaml:32-45; SURVEY §5). We keep the
same boundary: the operator never touches checkpoints, the workload
(train side) owns them — but unlike the reference image's TF checkpoint,
this is orbax, sharding-aware: on restore, arrays land back on the mesh
with their recorded shardings.
"""
from __future__ import annotations

import os
import shutil
from typing import Any, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..telemetry import span
from .trainer import TrainState


def _state_payload(state):
    """Only the array pytree is persisted; tx/apply_fn are static config
    reconstructed by the caller. Works for both TrainState (has
    batch_stats) and LMTrainState (doesn't)."""
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if hasattr(state, "batch_stats"):
        payload["batch_stats"] = state.batch_stats
    return payload


# One async checkpointer per process: saves return once the on-device
# arrays are snapshotted and the serialize/write continues on background
# threads — training overlaps the IO instead of stalling on it. A second
# save (or wait_for_checkpoints) joins the previous write first, so at
# most one write is in flight and step_N directories appear atomically
# (orbax commit semantics).
_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None

# Per-directory last step THIS process saved. Every rank executes the
# same periodic hooks in the same order, so the value is identical across
# processes by construction — the safe way to decide whether to enter a
# COLLECTIVE save (gating one on local os.listdir diverges on per-host
# filesystems and deadlocks the ranks that enter against the ones that
# skip). A dict (not a single slot) so interleaved saves to different
# directories can't evict each other's record and trigger a needless
# force-rewrite of a committed checkpoint. Deliberately UNBOUNDED: one
# (str, int) pair per distinct checkpoint directory is negligible, while
# evicting an entry would reintroduce the force-rewrite hazard for that
# directory (maybe_save would re-save with force=True, deleting the
# committed copy before rewriting — a crash mid-rewrite destroys the
# newest checkpoint).
_LAST_SAVED: dict = {}


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_checkpoints() -> None:
    """Join any in-flight async checkpoint write (no-op when none)."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(directory: str, state, step: Optional[int] = None,
                    block: bool = True) -> str:
    """Write a checkpoint under `directory/step_<n>`; returns the path.
    block=False returns as soon as the device arrays are snapshotted and
    lets the write complete in the background (call wait_for_checkpoints
    — or any later save — to join it)."""
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = _async_checkpointer()
    # the span covers the device-array snapshot (and, when block=True, the
    # full write) so checkpoint stalls show up next to device ops in XProf
    with span("checkpoint.save"):
        ckptr.save(path, args=ocp.args.StandardSave(_state_payload(state)),
                   force=True)
        _LAST_SAVED[os.path.abspath(directory)] = step
        if block:
            ckptr.wait_until_finished()
    return path


def checkpoint_steps(directory: str) -> List[int]:
    """Ascending step numbers of the step_N entries under `directory`
    (committed names only — an in-flight orbax write lives under a tmp
    name until its atomic rename)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(int(name[5:]) for name in os.listdir(directory)
                  if name.startswith("step_") and name[5:].isdigit())


def verify_checkpoint(path: str) -> bool:
    """Cheap integrity check on a step_N candidate: the orbax commit
    marker (tmp-named dirs are uncommitted writes; is_checkpoint_finalized
    covers the commit_success variant on object stores) plus the
    StandardSave metadata files a restore cannot start without. Content
    corruption inside the array files is caught by the restore itself —
    restore_with_fallback treats a raising restore the same way."""
    base = os.path.basename(path)
    if not (base.startswith("step_") and base[5:].isdigit()):
        return False
    if not os.path.isdir(path):
        return False
    try:
        if ocp.utils.is_tmp_checkpoint(path):
            return False
        if not ocp.utils.is_checkpoint_finalized(path):
            return False
    except Exception:  # noqa: BLE001 — marker helpers vary across versions
        pass
    entries = set(os.listdir(path))
    return "_METADATA" in entries


def latest_checkpoint(directory: str, verify: bool = True) -> Optional[str]:
    """Newest INTACT step_N path (or None). verify=True (default) skips
    candidates that fail the commit-marker/metadata check, falling back
    to the previous step — a crash mid-write or a half-deleted directory
    must not take resume down with it."""
    # join any in-flight async write FIRST: an uncommitted step_N still
    # lives under its orbax tmp name and would be invisible to listdir,
    # silently resolving "latest" to an older checkpoint
    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    for step in reversed(checkpoint_steps(directory)):
        path = os.path.join(directory, f"step_{step}")
        if not verify or verify_checkpoint(path):
            return path
    return None


def restore_checkpoint(directory_or_path: str, state):
    """Restore into the structure (and shardings) of `state` — sharded
    arrays land back on the mesh in their recorded layout. Accepts either a
    checkpoint path or a directory of step_N checkpoints (takes latest)."""
    wait_for_checkpoints()      # never read behind an in-flight write
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path!r}")
        path = latest
    ckptr = ocp.StandardCheckpointer()
    target = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_payload(state))
    restored = ckptr.restore(path, target)
    fields = {k: restored[k] for k in ("step", "params", "opt_state")}
    if hasattr(state, "batch_stats"):
        fields["batch_stats"] = restored["batch_stats"]
    return state.replace(**fields)


__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint",
           "checkpoint_steps", "verify_checkpoint", "restore_with_fallback",
           "gc_checkpoints", "reset_saved_state",
           "wait_for_checkpoints", "periodic_saver"]


def restore_with_fallback(train_dir, state, log=print
                          ) -> Tuple[Any, Optional[str]]:
    """Newest-first restore with per-candidate fallback: a candidate that
    fails the integrity check OR raises during the actual restore (bytes
    scribbled inside a committed directory) logs a warning and falls back
    to the previous step_N. Returns (state, restored_path) —
    restored_path is None when nothing restorable exists (state returned
    unchanged)."""
    wait_for_checkpoints()
    directory = os.path.abspath(train_dir)
    for step in reversed(checkpoint_steps(directory)):
        path = os.path.join(directory, f"step_{step}")
        if not verify_checkpoint(path):
            log(f"WARNING: checkpoint {path} failed the integrity check "
                f"(uncommitted or torn write); falling back to the "
                f"previous step")
            continue
        try:
            return restore_checkpoint(path, state), path
        except Exception as exc:  # noqa: BLE001 — corruption shapes vary
            log(f"WARNING: checkpoint {path} is corrupt ({exc!r}); "
                f"falling back to the previous step")
    return state, None


def maybe_resume(train_dir, state, log=print):
    """Restore the newest INTACT checkpoint under train_dir into `state`
    (no-op when train_dir is falsy or empty). A corrupted newest step_N
    falls back to the previous one with a logged warning instead of
    killing the restart (restore_with_fallback). The single resume path
    every benchmark entrypoint shares.

    Multi-host: train_dir MUST be a filesystem every host shares (PVC/
    NFS/GCS — the shipped manifests mount a PVC). Restore is a collective;
    per-pod paths make the has-a-checkpoint decision diverge across ranks
    and deadlock the ranks that enter against the ones that skip."""
    if not train_dir:
        return state
    state, path = restore_with_fallback(train_dir, state, log)
    if path is not None:
        log(f"resumed from {path} (step {int(state.step)})")
    return state


def maybe_save(train_dir, state, log=print, block: bool = True):
    """Write a checkpoint when train_dir is set (collective across all
    processes — see examples/benchmark.py for why every rank must call).
    Skips the write when THIS process already saved this step (the
    periodic hook fired on the final step) — rewriting with force=True
    would delete the committed copy first, so a crash mid-rewrite would
    destroy the newest checkpoint for nothing. The skip decision uses the
    in-process _LAST_SAVED pair, replicated across ranks by construction
    (same hook sequence everywhere) — NEVER the local filesystem, which
    diverges on per-host paths and would deadlock the collective.

    block=True (the default) returns with the write committed — what the
    emergency path needs (resilience.emergency_save runs under a SIGTERM
    grace window; returning before commit would let the pod die with a
    torn tmp directory). Benchmark exits pass block=False to overlap the
    final write with teardown and join once via wait_for_checkpoints()."""
    if not train_dir:
        return
    step = int(state.step)
    if _LAST_SAVED.get(os.path.abspath(train_dir)) == step:
        if block:
            wait_for_checkpoints()            # join the in-flight write
        log(f"checkpoint for step {step} already written")
        return
    path = save_checkpoint(train_dir, state, block=block)
    log(f"checkpoint written to {path}")


def gc_checkpoints(train_dir, keep_last: int, log=print) -> List[int]:
    """Delete all but the newest `keep_last` committed step_N directories
    (long runs checkpointing every N steps would otherwise fill the PVC).
    Only process 0 deletes — deletion is NOT a collective, and concurrent
    rmtree of the same shared-filesystem path from every rank races.
    Returns the deleted step numbers (empty when disabled/nothing due).
    The in-flight async write is invisible here (tmp-named until commit)
    and the newest committed steps are by construction never deleted."""
    if not train_dir or keep_last <= 0:
        return []
    if jax.process_index() != 0:
        return []
    directory = os.path.abspath(train_dir)
    doomed = checkpoint_steps(directory)[:-keep_last]
    for step in doomed:
        shutil.rmtree(os.path.join(directory, f"step_{step}"),
                      ignore_errors=True)
    if doomed:
        log(f"checkpoint gc: removed steps {doomed} "
            f"(keep-last {keep_last})")
    return doomed


def reset_saved_state() -> None:
    """Forget the per-directory last-saved records (and join any in-flight
    write first, so a forgotten record can't race a background commit).
    For test fixtures and back-to-back in-process runs against a REUSED
    train_dir: without the reset, a second run reaching the same step
    number would skip its legitimately-needed final save."""
    wait_for_checkpoints()
    _LAST_SAVED.clear()


def periodic_saver(train_dir, every: int, log=print, keep_last: int = 0,
                   resilience=None):
    """A `hook(state, step)` for training loops: every `every` steps it
    fires a NON-blocking async checkpoint (training overlaps the write —
    this is what makes mid-run gang restarts resumable instead of losing
    the whole run). keep_last > 0 additionally garbage-collects older
    step_N directories after each save (gc_checkpoints). None when
    disabled; pair with wait_for_checkpoints() (or the final maybe_save,
    which joins implicitly) before exit.

    `resilience` (a ResilienceContext) gets record_checkpoint(step) on
    the NEXT hook firing, after wait_for_checkpoints has joined the
    write — the `checkpoint_saved` event must describe a committed
    checkpoint, not an in-flight one."""
    if not train_dir or every <= 0:
        return None
    pending = []        # steps dispatched but not yet reported committed

    def hook(state, step: int) -> None:
        if step % every == 0:
            # join the PREVIOUS write before gc'ing or dispatching the
            # next one: near-free (it had `every` steps to finish), and
            # it guarantees the newest committed checkpoint exists before
            # gc deletes older ones — gc must never race an in-flight
            # write it cannot see (tmp-named until commit)
            wait_for_checkpoints()
            if resilience is not None:
                while pending:
                    resilience.record_checkpoint(pending.pop(0))
            if keep_last > 0:
                gc_checkpoints(train_dir, keep_last, log)
            # explicit step: save_checkpoint(step=None) would host-read
            # state.step, a device sync the training loop must not pay
            path = save_checkpoint(train_dir, state, step=step, block=False)
            if resilience is not None:
                pending.append(step)
            log(f"async checkpoint -> {path}")
    return hook
