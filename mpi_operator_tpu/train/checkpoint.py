"""Checkpoint / resume (orbax-backed).

The reference operator has NO checkpointing — it delegates to the workload
(the example merely mounts --train_dir on an emptyDir, reference
examples/tensorflow-benchmarks-imagenet.yaml:32-45; SURVEY §5). We keep the
same boundary: the operator never touches checkpoints, the workload
(train side) owns them — but unlike the reference image's TF checkpoint,
this is orbax, sharding-aware: on restore, arrays land back on the mesh
with their recorded shardings.
"""
from __future__ import annotations

import os
import shutil
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..telemetry import span
from .trainer import TrainState


def _state_payload(state):
    """Only the array pytree is persisted; tx/apply_fn are static config
    reconstructed by the caller. Works for both TrainState (has
    batch_stats) and LMTrainState (doesn't)."""
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if hasattr(state, "batch_stats"):
        payload["batch_stats"] = state.batch_stats
    return payload


# One async checkpointer per process: saves return once the on-device
# arrays are snapshotted and the serialize/write continues on background
# threads — training overlaps the IO instead of stalling on it. A second
# save (or wait_for_checkpoints) joins the previous write first, so at
# most one write is in flight and step_N directories appear atomically
# (orbax commit semantics).
_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None

# Per-directory last step THIS process saved. Every rank executes the
# same periodic hooks in the same order, so the value is identical across
# processes by construction — the safe way to decide whether to enter a
# COLLECTIVE save (gating one on local os.listdir diverges on per-host
# filesystems and deadlocks the ranks that enter against the ones that
# skip). A dict (not a single slot) so interleaved saves to different
# directories can't evict each other's record and trigger a needless
# force-rewrite of a committed checkpoint. Deliberately UNBOUNDED: one
# (str, int) pair per distinct checkpoint directory is negligible, while
# evicting an entry would reintroduce the force-rewrite hazard for that
# directory (maybe_save would re-save with force=True, deleting the
# committed copy before rewriting — a crash mid-rewrite destroys the
# newest checkpoint).
_LAST_SAVED: dict = {}


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_checkpoints() -> None:
    """Join any in-flight async checkpoint write (no-op when none)."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(directory: str, state, step: Optional[int] = None,
                    block: bool = True) -> str:
    """Write a checkpoint under `directory/step_<n>`; returns the path.
    block=False returns as soon as the device arrays are snapshotted and
    lets the write complete in the background (call wait_for_checkpoints
    — or any later save — to join it)."""
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = _async_checkpointer()
    # the span covers the device-array snapshot (and, when block=True, the
    # full write) so checkpoint stalls show up next to device ops in XProf
    with span("checkpoint.save"):
        ckptr.save(path, args=ocp.args.StandardSave(_state_payload(state)),
                   force=True)
        _LAST_SAVED[os.path.abspath(directory)] = step
        if block:
            ckptr.wait_until_finished()
    return path


def checkpoint_steps(directory: str) -> List[int]:
    """Ascending step numbers of the step_N entries under `directory`
    (committed names only — an in-flight orbax write lives under a tmp
    name until its atomic rename)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(int(name[5:]) for name in os.listdir(directory)
                  if name.startswith("step_") and name[5:].isdigit())


def verify_checkpoint(path: str) -> bool:
    """Cheap integrity check on a step_N candidate: the orbax commit
    marker (tmp-named dirs are uncommitted writes; is_checkpoint_finalized
    covers the commit_success variant on object stores) plus the
    StandardSave metadata files a restore cannot start without. Content
    corruption inside the array files is caught by the restore itself —
    restore_with_fallback treats a raising restore the same way."""
    base = os.path.basename(path)
    if not (base.startswith("step_") and base[5:].isdigit()):
        return False
    if not os.path.isdir(path):
        return False
    try:
        if ocp.utils.is_tmp_checkpoint(path):
            return False
        if not ocp.utils.is_checkpoint_finalized(path):
            return False
    except Exception:  # noqa: BLE001 — marker helpers vary across versions
        pass
    entries = set(os.listdir(path))
    return "_METADATA" in entries


def latest_checkpoint(directory: str, verify: bool = True) -> Optional[str]:
    """Newest INTACT step_N path (or None). verify=True (default) skips
    candidates that fail the commit-marker/metadata check, falling back
    to the previous step — a crash mid-write or a half-deleted directory
    must not take resume down with it."""
    # join any in-flight async write FIRST: an uncommitted step_N still
    # lives under its orbax tmp name and would be invisible to listdir,
    # silently resolving "latest" to an older checkpoint
    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    for step in reversed(checkpoint_steps(directory)):
        path = os.path.join(directory, f"step_{step}")
        if not verify or verify_checkpoint(path):
            return path
    return None


def restore_checkpoint(directory_or_path: str, state):
    """Restore into the structure (and shardings) of `state` — sharded
    arrays land back on the mesh in their recorded layout. Accepts either a
    checkpoint path or a directory of step_N checkpoints (takes latest)."""
    wait_for_checkpoints()      # never read behind an in-flight write
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path!r}")
        path = latest
    ckptr = ocp.StandardCheckpointer()
    target = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_payload(state))
    restored = ckptr.restore(path, target)
    fields = {k: restored[k] for k in ("step", "params", "opt_state")}
    if hasattr(state, "batch_stats"):
        fields["batch_stats"] = restored["batch_stats"]
    return state.replace(**fields)


# ---------------------------------------------------------------------------
# Resharding restore — load a checkpoint saved on mesh (dp=N) into mesh
# (dp=M) by resharding on READ
# ---------------------------------------------------------------------------
# orbax's StandardSave writes an OCDBT kvstore in which every pytree leaf
# is its own zarr array, keyed by the dot-joined tree path
# ("params.blocks_0.attn.kernel") and chunked exactly along the
# SAVE-time shard boundaries. restore_resharded exploits that layout
# directly through tensorstore: each host opens only the leaves it needs,
# reads only the index domains of its NEW shards (tensorstore touches
# just the chunks — byte ranges — that overlap), and assembles the
# jax.Array from per-device buffers. No host ever materializes a full
# replica of a sharded leaf, and a thread pool overlaps the per-leaf
# reads — the fast-resume path a gang resize (4 -> 2 -> 4) rides.

#: opt-in env knob for maybe_resume: "1"/"true" routes the shared resume
#: path through restore_resharded (with a per-candidate orbax fallback)
ENV_RESHARD_RESTORE = "TPU_RESHARD_RESTORE"
#: thread-pool width for the per-leaf parallel reads (0/unset = auto)
ENV_RESTORE_THREADS = "TPU_RESTORE_THREADS"


@dataclass
class ReadStats:
    """Instrumentation for one restore_resharded call.
    `peak_in_flight_bytes` is the memory contract a test can pin: the
    high-water mark of shard bytes materialized on THIS host at any
    instant, which must stay well under `total_bytes` (the full
    unsharded tree) whenever the target is actually sharded."""
    leaves: int = 0
    reads: int = 0
    bytes_read: int = 0
    total_bytes: int = 0
    in_flight_bytes: int = 0
    peak_in_flight_bytes: int = 0
    seconds: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def begin_read(self, nbytes: int) -> None:
        with self._lock:
            self.reads += 1
            self.bytes_read += nbytes
            self.in_flight_bytes += nbytes
            self.peak_in_flight_bytes = max(self.peak_in_flight_bytes,
                                            self.in_flight_bytes)

    def end_read(self, nbytes: int) -> None:
        with self._lock:
            self.in_flight_bytes -= nbytes


#: last restore's stats/info, for telemetry plumbing (the benchmark
#: reports restore seconds + leaf count without threading a handle
#: through every call site). Overwritten per restore; read via
#: last_restore_info().
_LAST_RESTORE_INFO: Dict[str, Any] = {}


def last_restore_info() -> Dict[str, Any]:
    """{"path", "seconds", "leaves", "resharded", ...} of the most recent
    successful restore in this process (empty dict when none)."""
    return dict(_LAST_RESTORE_INFO)


def _path_components(key_path) -> Tuple[str, ...]:
    """jax key path -> checkpoint tree path components, matching orbax's
    OCDBT naming: dict keys by name, sequence entries by index,
    namedtuple fields by field name."""
    out = []
    for entry in key_path:
        if hasattr(entry, "key"):          # DictKey / FlattenedIndexKey
            out.append(str(entry.key))
        elif hasattr(entry, "idx"):        # SequenceKey
            out.append(str(entry.idx))
        elif hasattr(entry, "name"):       # GetAttrKey (namedtuple field)
            out.append(str(entry.name))
        else:
            out.append(str(entry))
    return tuple(out)


def _restore_threads(n_leaves: int) -> int:
    raw = os.environ.get(ENV_RESTORE_THREADS, "")
    if raw.isdigit() and int(raw) > 0:
        return int(raw)
    return max(1, min(16, n_leaves, os.cpu_count() or 4))


def _read_leaf_resharded(path: str, key: str, target, sharding, stats):
    """Read ONE leaf from the checkpoint's OCDBT store into a jax.Array
    with `sharding`: per addressable shard, read only that shard's index
    domain (deduped — replicated devices share one read) and device_put
    the buffer. Runs on a pool thread; tensorstore reads release the GIL
    so leaves genuinely overlap."""
    import tensorstore as ts

    spec = {"driver": "zarr",
            "kvstore": {"driver": "ocdbt", "base": f"file://{path}",
                        "path": key + "/"}}
    arr = ts.open(spec, open=True).result()
    shape = tuple(target.shape)
    if tuple(arr.shape) != shape:
        raise ValueError(
            f"checkpoint leaf {key!r} has shape {tuple(arr.shape)}, "
            f"target expects {shape}")
    if arr.dtype.numpy_dtype != np.dtype(target.dtype):
        raise ValueError(
            f"checkpoint leaf {key!r} has dtype {arr.dtype.numpy_dtype}, "
            f"target expects {np.dtype(target.dtype)}")
    itemsize = np.dtype(target.dtype).itemsize
    index_map = sharding.addressable_devices_indices_map(shape)
    buffers: Dict[Tuple, Any] = {}      # normalized index -> np shard
    device_buffers = []
    held = 0        # host bytes this leaf keeps alive until assembly
    try:
        for device, idx in index_map.items():
            idx = idx if idx is not None else ()
            norm = tuple((s.start, s.stop, s.step) for s in idx)
            if norm not in buffers:
                view = arr[idx] if idx else arr
                nbytes = int(np.prod([max(0, d) for d in view.shape],
                                     initial=1)) * itemsize
                stats.begin_read(nbytes)
                held += nbytes
                buffers[norm] = np.asarray(view.read().result())
            # replicated devices share one host buffer; each device_put
            # copies onto its device
            device_buffers.append(jax.device_put(buffers[norm], device))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, device_buffers)
    finally:
        # the host-side shard buffers stay accounted until the device
        # copies exist — that whole window is what the memory pin bounds
        stats.end_read(held)


def restore_resharded(directory_or_path: str, state, rules=None,
                      max_workers: Optional[int] = None,
                      log: Callable[[str], None] = print,
                      stats: Optional[ReadStats] = None):
    """Restore a checkpoint into `state` with RESHARD-ON-READ semantics:
    every leaf lands in the sharding `state` carries on its CURRENT mesh
    (typically a different world size than the save), overridable per
    leaf by regex restore rules (parallel/sharding.path_match — patterns
    windowed over the checkpoint tree path, first hit wins):

        rules = [(("params", ".*kernel"), P("fsdp", "tp")),
                 ((r"opt_state", ".*", "mu", ".*"), None)]   # replicate

    Accepts a step_N path or a directory (takes newest). Each host reads
    only the byte ranges its new shards cover (OCDBT chunks equal the
    save-time shards, so tensorstore never pulls more than the chunks
    overlapping a shard), across a thread pool of per-leaf reads.
    `stats` (a ReadStats) is filled in for callers that pin the memory
    contract. Raises on missing leaves, shape or dtype mismatch — the
    caller's fallback chain (restore_with_fallback) treats that like any
    corrupt candidate."""
    from ..parallel.sharding import sharding_for_path

    wait_for_checkpoints()
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path!r}")
        path = latest
    path = os.path.abspath(path)
    stats = stats if stats is not None else ReadStats()
    t0 = time.monotonic()

    payload = _state_payload(state)
    flat, treedef = jax.tree_util.tree_flatten_with_path(payload)
    stats.leaves = len(flat)
    stats.total_bytes = sum(
        int(np.prod(leaf.shape, initial=1))
        * np.dtype(leaf.dtype).itemsize for _, leaf in flat)

    jobs = []
    for key_path, leaf in flat:
        components = _path_components(key_path)
        default = getattr(leaf, "sharding", None)
        sharding = default
        if rules:
            mesh = getattr(default, "mesh", None)
            if mesh is not None:
                sharding = sharding_for_path(mesh, components, rules,
                                             tuple(leaf.shape),
                                             default=default)
        if sharding is None:
            raise ValueError(
                f"leaf {'.'.join(components)!r} has no sharding and no "
                f"restore rule matched — restore_resharded needs a "
                f"target layout for every leaf")
        jobs.append((".".join(components), leaf, sharding))

    with ThreadPoolExecutor(
            max_workers=max_workers or _restore_threads(len(jobs)),
            thread_name_prefix="reshard-restore") as pool:
        futures = [pool.submit(_read_leaf_resharded, path, key, leaf,
                               sharding, stats)
                   for key, leaf, sharding in jobs]
        leaves = [f.result() for f in futures]

    restored = jax.tree_util.tree_unflatten(treedef, leaves)
    stats.seconds = time.monotonic() - t0
    _LAST_RESTORE_INFO.update(path=path, seconds=round(stats.seconds, 3),
                              leaves=stats.leaves, resharded=True,
                              bytes_read=stats.bytes_read,
                              peak_in_flight_bytes=stats.peak_in_flight_bytes)
    fields = {k: restored[k] for k in ("step", "params", "opt_state")}
    if hasattr(state, "batch_stats"):
        fields["batch_stats"] = restored["batch_stats"]
    return state.replace(**fields)


def _resharded_with_orbax_fallback(log: Callable[[str], None]):
    """Per-candidate restore fn for restore_with_fallback: try the
    parallel resharding reader first; a mechanism failure (non-OCDBT
    layout, tensorstore missing) falls back to the orbax restore for the
    SAME candidate before the outer loop declares it corrupt."""
    def _restore(path: str, state):
        try:
            return restore_resharded(path, state, log=log)
        except (ValueError, FileNotFoundError):
            raise               # genuine mismatch/corruption: next step_N
        except Exception as exc:  # noqa: BLE001 — layout/driver surprises
            log(f"WARNING: resharded restore of {path} failed ({exc!r}); "
                f"retrying via orbax")
            return restore_checkpoint(path, state)
    return _restore


__all__ = ["save_checkpoint", "restore_checkpoint", "restore_resharded",
           "latest_checkpoint",
           "checkpoint_steps", "verify_checkpoint", "restore_with_fallback",
           "gc_checkpoints", "reset_saved_state", "last_restore_info",
           "ReadStats", "ENV_RESHARD_RESTORE",
           "wait_for_checkpoints", "periodic_saver"]


def restore_with_fallback(train_dir, state, log=print, restore=None
                          ) -> Tuple[Any, Optional[str]]:
    """Newest-first restore with per-candidate fallback: a candidate that
    fails the integrity check OR raises during the actual restore (bytes
    scribbled inside a committed directory) logs a warning and falls back
    to the previous step_N. Returns (state, restored_path) —
    restored_path is None when nothing restorable exists (state returned
    unchanged). `restore` swaps the per-candidate restore fn
    ((path, state) -> state; default restore_checkpoint) — this is how
    restore_resharded composes with the fallback chain."""
    wait_for_checkpoints()
    restore_fn = restore if restore is not None else restore_checkpoint
    directory = os.path.abspath(train_dir)
    for step in reversed(checkpoint_steps(directory)):
        path = os.path.join(directory, f"step_{step}")
        if not verify_checkpoint(path):
            log(f"WARNING: checkpoint {path} failed the integrity check "
                f"(uncommitted or torn write); falling back to the "
                f"previous step")
            continue
        try:
            _LAST_RESTORE_INFO.pop("resharded", None)
            t0 = time.monotonic()
            restored = restore_fn(path, state)
            seconds = time.monotonic() - t0
            leaves = len(jax.tree.leaves(_state_payload(restored)))
            # a slow restore must be visible outside the histogram: one
            # INFO line with wall time + leaf count per restore
            log(f"INFO: restored {path} in {seconds:.2f}s "
                f"({leaves} leaves)")
            _LAST_RESTORE_INFO.update(
                path=path, seconds=round(seconds, 3), leaves=leaves,
                resharded=_LAST_RESTORE_INFO.get("resharded", False))
            return restored, path
        except Exception as exc:  # noqa: BLE001 — corruption shapes vary
            log(f"WARNING: checkpoint {path} is corrupt ({exc!r}); "
                f"falling back to the previous step")
    return state, None


def maybe_resume(train_dir, state, log=print, reshard: Optional[bool] = None):
    """Restore the newest INTACT checkpoint under train_dir into `state`
    (no-op when train_dir is falsy or empty). A corrupted newest step_N
    falls back to the previous one with a logged warning instead of
    killing the restart (restore_with_fallback). The single resume path
    every benchmark entrypoint shares.

    `reshard` routes the restore through restore_resharded (parallel
    per-leaf shard reads, reshard-on-read onto the CURRENT mesh — what a
    gang resized 4 -> 2 needs, since the recorded shardings reference a
    world that no longer exists). Default: the TPU_RESHARD_RESTORE env
    knob ("1"/"true"), off otherwise.

    Multi-host: train_dir MUST be a filesystem every host shares (PVC/
    NFS/GCS — the shipped manifests mount a PVC). Restore is a collective;
    per-pod paths make the has-a-checkpoint decision diverge across ranks
    and deadlock the ranks that enter against the ones that skip."""
    if not train_dir:
        return state
    if reshard is None:
        reshard = os.environ.get(ENV_RESHARD_RESTORE, "").lower() \
            in ("1", "true", "yes")
    restore = _resharded_with_orbax_fallback(log) if reshard else None
    state, path = restore_with_fallback(train_dir, state, log,
                                        restore=restore)
    if path is not None:
        log(f"resumed from {path} (step {int(state.step)})")
    return state


def maybe_save(train_dir, state, log=print, block: bool = True):
    """Write a checkpoint when train_dir is set (collective across all
    processes — see examples/benchmark.py for why every rank must call).
    Skips the write when THIS process already saved this step (the
    periodic hook fired on the final step) — rewriting with force=True
    would delete the committed copy first, so a crash mid-rewrite would
    destroy the newest checkpoint for nothing. The skip decision uses the
    in-process _LAST_SAVED pair, replicated across ranks by construction
    (same hook sequence everywhere) — NEVER the local filesystem, which
    diverges on per-host paths and would deadlock the collective.

    block=True (the default) returns with the write committed — what the
    emergency path needs (resilience.emergency_save runs under a SIGTERM
    grace window; returning before commit would let the pod die with a
    torn tmp directory). Benchmark exits pass block=False to overlap the
    final write with teardown and join once via wait_for_checkpoints()."""
    if not train_dir:
        return
    step = int(state.step)
    if _LAST_SAVED.get(os.path.abspath(train_dir)) == step:
        if block:
            wait_for_checkpoints()            # join the in-flight write
        log(f"checkpoint for step {step} already written")
        return
    path = save_checkpoint(train_dir, state, block=block)
    log(f"checkpoint written to {path}")


def gc_checkpoints(train_dir, keep_last: int, log=print) -> List[int]:
    """Delete all but the newest `keep_last` committed step_N directories
    (long runs checkpointing every N steps would otherwise fill the PVC).
    Only process 0 deletes — deletion is NOT a collective, and concurrent
    rmtree of the same shared-filesystem path from every rank races.
    Returns the deleted step numbers (empty when disabled/nothing due).
    The in-flight async write is invisible here (tmp-named until commit)
    and the newest committed steps are by construction never deleted."""
    if not train_dir or keep_last <= 0:
        return []
    if jax.process_index() != 0:
        return []
    directory = os.path.abspath(train_dir)
    doomed = checkpoint_steps(directory)[:-keep_last]
    for step in doomed:
        shutil.rmtree(os.path.join(directory, f"step_{step}"),
                      ignore_errors=True)
    if doomed:
        log(f"checkpoint gc: removed steps {doomed} "
            f"(keep-last {keep_last})")
    return doomed


def reset_saved_state() -> None:
    """Forget the per-directory last-saved records (and join any in-flight
    write first, so a forgotten record can't race a background commit).
    For test fixtures and back-to-back in-process runs against a REUSED
    train_dir: without the reset, a second run reaching the same step
    number would skip its legitimately-needed final save."""
    wait_for_checkpoints()
    _LAST_SAVED.clear()


def periodic_saver(train_dir, every: int, log=print, keep_last: int = 0,
                   resilience=None):
    """A `hook(state, step)` for training loops: every `every` steps it
    fires a NON-blocking async checkpoint (training overlaps the write —
    this is what makes mid-run gang restarts resumable instead of losing
    the whole run). keep_last > 0 additionally garbage-collects older
    step_N directories after each save (gc_checkpoints). None when
    disabled; pair with wait_for_checkpoints() (or the final maybe_save,
    which joins implicitly) before exit.

    `resilience` (a ResilienceContext) gets record_checkpoint(step) on
    the NEXT hook firing, after wait_for_checkpoints has joined the
    write — the `checkpoint_saved` event must describe a committed
    checkpoint, not an in-flight one."""
    if not train_dir or every <= 0:
        return None
    pending = []        # steps dispatched but not yet reported committed

    def hook(state, step: int) -> None:
        if step % every == 0:
            # join the PREVIOUS write before gc'ing or dispatching the
            # next one: near-free (it had `every` steps to finish), and
            # it guarantees the newest committed checkpoint exists before
            # gc deletes older ones — gc must never race an in-flight
            # write it cannot see (tmp-named until commit)
            wait_for_checkpoints()
            if resilience is not None:
                while pending:
                    resilience.record_checkpoint(pending.pop(0))
            if keep_last > 0:
                gc_checkpoints(train_dir, keep_last, log)
            # explicit step: save_checkpoint(step=None) would host-read
            # state.step, a device sync the training loop must not pay
            path = save_checkpoint(train_dir, state, step=step, block=False)
            if resilience is not None:
                pending.append(step)
            log(f"async checkpoint -> {path}")
    return hook
