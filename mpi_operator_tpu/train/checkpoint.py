"""Checkpoint / resume (orbax-backed).

The reference operator has NO checkpointing — it delegates to the workload
(the example merely mounts --train_dir on an emptyDir, reference
examples/tensorflow-benchmarks-imagenet.yaml:32-45; SURVEY §5). We keep the
same boundary: the operator never touches checkpoints, the workload
(train side) owns them — but unlike the reference image's TF checkpoint,
this is orbax, sharding-aware: on restore, arrays land back on the mesh
with their recorded shardings.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from .trainer import TrainState


def _state_payload(state):
    """Only the array pytree is persisted; tx/apply_fn are static config
    reconstructed by the caller. Works for both TrainState (has
    batch_stats) and LMTrainState (doesn't)."""
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if hasattr(state, "batch_stats"):
        payload["batch_stats"] = state.batch_stats
    return payload


def save_checkpoint(directory: str, state, step: Optional[int] = None) -> str:
    """Write a checkpoint under `directory/step_<n>`; returns the path."""
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _state_payload(state), force=True)
    ckptr.wait_until_finished()
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    if not steps:
        return None
    return os.path.join(directory, f"step_{max(steps)}")


def restore_checkpoint(directory_or_path: str, state):
    """Restore into the structure (and shardings) of `state` — sharded
    arrays land back on the mesh in their recorded layout. Accepts either a
    checkpoint path or a directory of step_N checkpoints (takes latest)."""
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path!r}")
        path = latest
    ckptr = ocp.StandardCheckpointer()
    target = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_payload(state))
    restored = ckptr.restore(path, target)
    fields = {k: restored[k] for k in ("step", "params", "opt_state")}
    if hasattr(state, "batch_stats"):
        fields["batch_stats"] = restored["batch_stats"]
    return state.replace(**fields)


__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint"]


def maybe_resume(train_dir, state, log=print):
    """Restore the latest checkpoint under train_dir into `state` (no-op
    when train_dir is falsy or empty). The single resume path every
    benchmark entrypoint shares."""
    if not train_dir:
        return state
    latest = latest_checkpoint(train_dir)
    if latest is None:
        return state
    state = restore_checkpoint(latest, state)
    log(f"resumed from {latest} (step {int(state.step)})")
    return state


def maybe_save(train_dir, state, log=print):
    """Write a checkpoint when train_dir is set (collective across all
    processes — see examples/benchmark.py for why every rank must call)."""
    if not train_dir:
        return
    path = save_checkpoint(train_dir, state)
    log(f"checkpoint written to {path}")
