"""Checkpoint / resume (orbax-backed).

The reference operator has NO checkpointing — it delegates to the workload
(the example merely mounts --train_dir on an emptyDir, reference
examples/tensorflow-benchmarks-imagenet.yaml:32-45; SURVEY §5). We keep the
same boundary: the operator never touches checkpoints, the workload
(train side) owns them — but unlike the reference image's TF checkpoint,
this is orbax, sharding-aware: on restore, arrays land back on the mesh
with their recorded shardings.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from .trainer import TrainState


def _state_payload(state):
    """Only the array pytree is persisted; tx/apply_fn are static config
    reconstructed by the caller. Works for both TrainState (has
    batch_stats) and LMTrainState (doesn't)."""
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if hasattr(state, "batch_stats"):
        payload["batch_stats"] = state.batch_stats
    return payload


# One async checkpointer per process: saves return once the on-device
# arrays are snapshotted and the serialize/write continues on background
# threads — training overlaps the IO instead of stalling on it. A second
# save (or wait_for_checkpoints) joins the previous write first, so at
# most one write is in flight and step_N directories appear atomically
# (orbax commit semantics).
_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None

# Per-directory last step THIS process saved. Every rank executes the
# same periodic hooks in the same order, so the value is identical across
# processes by construction — the safe way to decide whether to enter a
# COLLECTIVE save (gating one on local os.listdir diverges on per-host
# filesystems and deadlocks the ranks that enter against the ones that
# skip). A dict (not a single slot) so interleaved saves to different
# directories can't evict each other's record and trigger a needless
# force-rewrite of a committed checkpoint. Deliberately UNBOUNDED: one
# (str, int) pair per distinct checkpoint directory is negligible, while
# evicting an entry would reintroduce the force-rewrite hazard for that
# directory (maybe_save would re-save with force=True, deleting the
# committed copy before rewriting — a crash mid-rewrite destroys the
# newest checkpoint).
_LAST_SAVED: dict = {}


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_checkpoints() -> None:
    """Join any in-flight async checkpoint write (no-op when none)."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(directory: str, state, step: Optional[int] = None,
                    block: bool = True) -> str:
    """Write a checkpoint under `directory/step_<n>`; returns the path.
    block=False returns as soon as the device arrays are snapshotted and
    lets the write complete in the background (call wait_for_checkpoints
    — or any later save — to join it)."""
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = _async_checkpointer()
    ckptr.save(path, args=ocp.args.StandardSave(_state_payload(state)),
               force=True)
    _LAST_SAVED[os.path.abspath(directory)] = step
    if block:
        ckptr.wait_until_finished()
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    # join any in-flight async write FIRST: an uncommitted step_N still
    # lives under its orbax tmp name and would be invisible to listdir,
    # silently resolving "latest" to an older checkpoint
    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    if not steps:
        return None
    return os.path.join(directory, f"step_{max(steps)}")


def restore_checkpoint(directory_or_path: str, state):
    """Restore into the structure (and shardings) of `state` — sharded
    arrays land back on the mesh in their recorded layout. Accepts either a
    checkpoint path or a directory of step_N checkpoints (takes latest)."""
    wait_for_checkpoints()      # never read behind an in-flight write
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path!r}")
        path = latest
    ckptr = ocp.StandardCheckpointer()
    target = jax.tree.map(ocp.utils.to_shape_dtype_struct, _state_payload(state))
    restored = ckptr.restore(path, target)
    fields = {k: restored[k] for k in ("step", "params", "opt_state")}
    if hasattr(state, "batch_stats"):
        fields["batch_stats"] = restored["batch_stats"]
    return state.replace(**fields)


__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint",
           "wait_for_checkpoints", "periodic_saver"]


def maybe_resume(train_dir, state, log=print):
    """Restore the latest checkpoint under train_dir into `state` (no-op
    when train_dir is falsy or empty). The single resume path every
    benchmark entrypoint shares.

    Multi-host: train_dir MUST be a filesystem every host shares (PVC/
    NFS/GCS — the shipped manifests mount a PVC). Restore is a collective;
    per-pod paths make the has-a-checkpoint decision diverge across ranks
    and deadlock the ranks that enter against the ones that skip."""
    if not train_dir:
        return state
    latest = latest_checkpoint(train_dir)
    if latest is None:
        return state
    state = restore_checkpoint(latest, state)
    log(f"resumed from {latest} (step {int(state.step)})")
    return state


def maybe_save(train_dir, state, log=print):
    """Write a checkpoint when train_dir is set (collective across all
    processes — see examples/benchmark.py for why every rank must call).
    Skips the write when THIS process already saved this step (the
    periodic hook fired on the final step) — rewriting with force=True
    would delete the committed copy first, so a crash mid-rewrite would
    destroy the newest checkpoint for nothing. The skip decision uses the
    in-process _LAST_SAVED pair, replicated across ranks by construction
    (same hook sequence everywhere) — NEVER the local filesystem, which
    diverges on per-host paths and would deadlock the collective."""
    if not train_dir:
        return
    step = int(state.step)
    if _LAST_SAVED.get(os.path.abspath(train_dir)) == step:
        wait_for_checkpoints()                # join the in-flight write
        log(f"checkpoint for step {step} already written")
        return
    path = save_checkpoint(train_dir, state)
    log(f"checkpoint written to {path}")


def periodic_saver(train_dir, every: int, log=print):
    """A `hook(state, step)` for training loops: every `every` steps it
    fires a NON-blocking async checkpoint (training overlaps the write —
    this is what makes mid-run gang restarts resumable instead of losing
    the whole run). None when disabled; pair with wait_for_checkpoints()
    (or the final maybe_save, which joins implicitly) before exit."""
    if not train_dir or every <= 0:
        return None

    def hook(state, step: int) -> None:
        if step % every == 0:
            # explicit step: save_checkpoint(step=None) would host-read
            # state.step, a device sync the training loop must not pay
            path = save_checkpoint(train_dir, state, step=step, block=False)
            log(f"async checkpoint -> {path}")
    return hook
