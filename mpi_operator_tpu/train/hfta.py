"""Horizontally fused training arrays (HFTA) — K sweep replicas, ONE
jitted step.

Small jobs waste most of a big accelerator. Instead of running K
same-architecture sweep members as K sequential (or K gang-scheduled)
programs, this trainer stacks them along a leading ``[K, ...]`` axis —
params, optimizer state, and the per-step batch all carry the replica
dimension — and vmaps ONE train step over it. XLA then fuses the K
copies into batched matmuls, recovering the utilization a single small
model leaves on the floor (HFTA, PAPERS.md). The controller-side
counterpart (controller/packing.py) packs the *jobs* onto one slice;
this module packs the *arrays*.

Per-replica hyperparameters (learning rate, weight decay, warmup, init
seed) ride along as ``[K]`` vectors, so a fused run IS a hyperparameter
sweep. Replica k's update math is kept bitwise-identical to a plain
``LMTrainer`` with the same scalars:

  - init: each replica is initialized UNVMAPPED with its own seed via the
    exact ``shard_init`` call LMTrainer makes, then stacked — so replica
    k's params at step 0 equal the solo run's bit for bit.
  - loss/grad: the fused step vmaps ``LMTrainer._loss_fn`` itself — the
    same loss code, not a re-implementation.
  - optimizer: the replica-INVARIANT prefix of ``make_adamw`` (global-norm
    clip + scale_by_adam) runs as a shared transformation under vmap; the
    replica-VARYING tail (weight decay, lr schedule, sign flip) is applied
    with the per-replica ``[K]`` scalars using the same formulas optax
    evaluates, in the same order (clip -> adam -> +wd*p -> -lr_t * u).
  - guard: the divergence guard is per-replica — a replica whose
    loss/grad-norm goes non-finite has THAT update dropped (params and
    optimizer state roll back leaf-wise along axis k) while its K-1
    siblings apply theirs untouched. ``freeze_after`` consecutive bad
    steps freeze the replica for the rest of the run: a frozen replica
    stops consuming updates but never stalls the fused program (there is
    no host-side rollback to serialize on).

Checkpoints persist the stacked pytree through the ordinary
train/checkpoint.py path (the payload contract only needs
step/params/opt_state). ``extract_replica`` slices one member back out
as a plain ``LMTrainState`` — including an ``optax.adamw``-shaped
optimizer state rebuilt from the fused inner state — so a finished sweep
member exports a normal single-model checkpoint.

Scope (enforced in __init__): causal-LM loss, no masked-LM, no gradient
accumulation, no tp-overlap. The fused step runs WITHOUT
activation_rules_scope — logical sharding constraints are no-ops under
vmap's extra axis; fused replicas target single-slice packing where the
batch axes carry the parallelism.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, replace as dc_replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh

from ..parallel.mesh import BATCH_AXES
from ..telemetry import TrainTelemetry
from ..telemetry import events as tev
from ..telemetry.core import Registry
from ..utils import flops
from .lm_trainer import LMTrainer, LMTrainerConfig, LMTrainState, make_adamw
from .resilience import FaultInjector


class HFTATrainState(struct.PyTreeNode):
    """Stacked train state: ``step`` is a lockstep scalar; every other
    leaf carries a leading ``[K]`` replica axis."""
    step: jax.Array
    params: Any
    opt_state: Any
    nonfinite_streak: Any   # [K] int32 — consecutive dropped steps
    frozen: Any             # [K] bool  — permanently parked replicas

    @property
    def k(self) -> int:
        return int(self.frozen.shape[0])


@dataclass(frozen=True)
class HFTAHyperparams:
    """Per-replica sweep axes. All tuples have the same length K; scalars
    not swept are broadcast from the base LMTrainerConfig."""
    learning_rates: Tuple[float, ...]
    seeds: Tuple[int, ...]
    weight_decays: Tuple[float, ...]
    warmup_steps: Tuple[int, ...]

    @property
    def k(self) -> int:
        return len(self.learning_rates)

    @classmethod
    def sweep(cls, k: int, config: LMTrainerConfig,
              learning_rates: Optional[Sequence[float]] = None,
              seeds: Optional[Sequence[int]] = None,
              weight_decays: Optional[Sequence[float]] = None,
              warmup_steps: Optional[Sequence[int]] = None
              ) -> "HFTAHyperparams":
        def axis(given, default):
            if given is None:
                return (default,) * k
            if len(given) != k:
                raise ValueError(f"sweep axis has {len(given)} values, "
                                 f"expected K={k}")
            return tuple(given)
        hp = cls(
            learning_rates=axis(learning_rates, config.learning_rate),
            seeds=axis(seeds, 0) if seeds is None
            else axis(seeds, None),
            weight_decays=axis(weight_decays, config.weight_decay),
            warmup_steps=axis(warmup_steps, config.warmup_steps),
        )
        return hp

    def replica_config(self, base: LMTrainerConfig,
                       k: int) -> LMTrainerConfig:
        """The solo LMTrainerConfig replica k is equivalent to."""
        return dc_replace(base,
                          learning_rate=self.learning_rates[k],
                          weight_decay=self.weight_decays[k],
                          warmup_steps=self.warmup_steps[k])

    def as_arrays(self) -> Dict[str, jax.Array]:
        return {
            "lr": jnp.asarray(self.learning_rates, jnp.float32),
            "wd": jnp.asarray(self.weight_decays, jnp.float32),
            "warmup": jnp.asarray(self.warmup_steps, jnp.int32),
        }


def _select_replicas(ok, new_tree, old_tree):
    """Leaf-wise where along the leading [K] axis: keep `new` where ok."""
    def sel(n, o):
        mask = ok.reshape(ok.shape + (1,) * (n.ndim - 1))
        return jnp.where(mask, n, o)
    return jax.tree.map(sel, new_tree, old_tree)


def poison_replica(state: HFTATrainState, k: int) -> HFTATrainState:
    """Multiply replica k's params by NaN (fault injection: the
    nan-replica:K@N drill). Siblings are multiplied by 1.0 — bitwise
    unchanged — so the drill can assert true isolation."""
    kk = state.k
    bad = jnp.where(jnp.arange(kk) == k, jnp.nan, 1.0)

    def poison(p):
        return p * bad.reshape((kk,) + (1,) * (p.ndim - 1)).astype(p.dtype)
    return state.replace(params=jax.tree.map(poison, state.params))


class HFTATrainer:
    """K-replica horizontally fused LM trainer (see module docstring)."""

    def __init__(self, model, mesh: Mesh,
                 config: Optional[LMTrainerConfig] = None,
                 hparams: Optional[HFTAHyperparams] = None,
                 k: int = 2, freeze_after: int = 3):
        self.config = config or LMTrainerConfig()
        self.hparams = hparams or HFTAHyperparams.sweep(k, self.config)
        self.model = model
        self.mesh = mesh
        self.freeze_after = int(freeze_after)
        cfg = self.config
        if cfg.masked_lm:
            raise ValueError("HFTA fusion supports causal LM only "
                             "(masked_lm=False)")
        if cfg.accum_steps != 1:
            raise ValueError("HFTA fusion does not compose with gradient "
                             "accumulation (accum_steps must be 1)")
        if getattr(model.config, "tp_overlap", False):
            raise ValueError("HFTA fusion does not compose with tp_overlap")
        # The solo trainer we mirror: its _loss_fn is THE loss (vmapped
        # verbatim below) and its config carries the shared scalars.
        self._lm = LMTrainer(model, mesh, config=self.config)
        # Replica-invariant optimizer prefix of make_adamw: optax.adamw is
        # chain(scale_by_adam, add_decayed_weights, scale_by_learning_rate)
        # — the first link shares b1/b2/eps across replicas, so it runs as
        # one transformation under vmap; the wd/lr tail varies per replica
        # and is applied manually with the [K] hyperparameter vectors.
        self._inner_tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.scale_by_adam(b1=cfg.b1, b2=cfg.b2, eps=1e-8),
        )
        self._hp_arrays = self.hparams.as_arrays()
        # Slice sharing vs batch sharding. Replicas are INDEPENDENT (the
        # only cross-replica op is metric stacking), so when K divides
        # the mesh batch-axis extent the [K] axis itself shards over the
        # devices: whole replicas land on disjoint device groups, the
        # step runs with ZERO cross-device collectives, and the optimizer
        # touches each replica's state exactly once (replicated [K,...]
        # params would re-run all K adam updates on every device). When
        # K doesn't divide, fall back to sharding the per-replica batch
        # dim (dim 1 of [K, B, S]) with params replicated — still no
        # redundant forward/backward, at the cost of a grad all-reduce.
        # Both are placement-only at nb==1, which keeps the K=1
        # single-device bitwise pin intact.
        P = jax.sharding.PartitionSpec
        nb = math.prod(mesh.shape[a] for a in BATCH_AXES)
        self._replica_sharding = None
        self._batch_sharding = None
        if nb > 1 and self.k % nb == 0:
            self._replica_sharding = jax.sharding.NamedSharding(
                mesh, P(BATCH_AXES))
            self._batch_sharding = self._replica_sharding   # dim 0 = K
        elif nb > 1 and cfg.global_batch_size % nb == 0:
            self._batch_sharding = jax.sharding.NamedSharding(
                mesh, P(None, BATCH_AXES))
        self._step = jax.jit(self._fused_step_fn, donate_argnums=(0,))

    @property
    def k(self) -> int:
        return self.hparams.k

    # -- init ---------------------------------------------------------------

    def init_state(self) -> HFTATrainState:
        """Per-replica init stacked along axis 0. Each replica runs the
        EXACT solo init (same shard_init call, its own seed-derived key),
        so replica k starts bit-identical to a plain LMTrainer seeded the
        same way; the stack happens after the fact."""
        per_replica = [
            self._lm.init_state(jax.random.PRNGKey(seed))
            for seed in self.hparams.seeds
        ]
        params = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[s.params for s in per_replica])
        opt_state = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[self._inner_tx.init(s.params) for s in per_replica])
        kk = self.k
        state = HFTATrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            nonfinite_streak=jnp.zeros((kk,), jnp.int32),
            frozen=jnp.zeros((kk,), bool),
        )
        # Commit EVERY leaf onto the mesh. The stacked params inherit the
        # solo init's mesh placement but optax scalars (adam count) and
        # the step counter are born on the default device, and
        # restore_checkpoint reuses this state's layout as the template —
        # a mixed device set poisons the fused jit after restore. Under
        # slice sharing the [K,...] leaves shard along K; everything else
        # (the step counter) is replicated.
        rep = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec())
        by_k = self._replica_sharding

        def _place(x):
            if by_k is not None and getattr(x, "ndim", 0) >= 1 \
                    and x.shape[0] == kk:
                return jax.device_put(x, by_k)
            return jax.device_put(x, rep)

        return jax.tree.map(_place, state)

    # -- the fused step -----------------------------------------------------

    def _lr_at(self, count, lr, warmup):
        """The schedule value optax's make_lr_schedule(cfg_k) yields at
        `count`, with lr/warmup as traced per-replica scalars. Formulas
        replicate optax.linear_schedule / warmup_cosine_decay_schedule
        term for term so the linear path is bitwise-pinned by the K=1
        exactness test."""
        cfg = self.config
        w = jnp.maximum(1, warmup)
        c = jnp.clip(count, 0, w)
        frac = 1 - c / w
        warm = (0.0 - lr) * (frac ** 1) + lr        # polynomial, power=1
        if cfg.lr_schedule == "linear":
            return warm
        if cfg.lr_schedule != "cosine":
            raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")
        alpha = cfg.end_lr_fraction                  # end/peak, shared
        total = jnp.maximum(cfg.decay_steps, w + 1)
        ds = total - w
        c2 = jnp.clip(count - w, 0, ds)
        cosine = 0.5 * (1 + jnp.cos(jnp.pi * (c2 / ds)))
        decayed = (1 - alpha) * (cosine ** 1.0) + alpha
        return jnp.where(count < w, warm, lr * decayed)

    def _map_replicas(self, fn):
        """vmap over the leading [K] axis — except K=1, which squeezes
        and re-expands instead. The batched K=1 program is numerically
        identical to the solo one for every op EXCEPT a ~1e-10
        reduction-order wobble in LayerNorm bias grads (XLA fuses the
        batched backward sum differently); squeezing preserves the solo
        program bit for bit, which is what pins the K=1 exactness test,
        and skips a pointless unit batch dim."""
        if self.k > 1:
            return jax.vmap(fn)

        def mapped(*xs):
            out = fn(*[jax.tree.map(lambda a: a[0], x) for x in xs])
            return jax.tree.map(lambda a: a[None], out)
        return mapped

    def _fused_step_fn(self, state, hp, tokens, targets, mask):
        def forward(params, t, y, m):
            (loss, logits), grads = jax.value_and_grad(
                self._lm._loss_fn, has_aux=True)(params, t, y, m)
            if logits is None:                       # fused-xent path
                acc = jnp.full((), jnp.nan, jnp.float32)
            else:
                acc = (jnp.sum((jnp.argmax(logits, -1) == y) * m)
                       / jnp.maximum(m.sum(), 1))
            return loss, acc, grads

        loss, acc, grads = self._map_replicas(forward)(
            state.params, tokens, targets, mask)

        def update(params, inner, g, lr, wd, warmup):
            # pre-update count: scale_by_adam and scale_by_schedule march
            # in lockstep in the solo chain, so adam's count doubles as
            # the schedule step
            count = inner[1].count
            u, new_inner = self._inner_tx.update(g, inner, params)
            u = jax.tree.map(lambda ui, pi: ui + wd * pi, u, params)
            step_size = -self._lr_at(count, lr, warmup)
            u = jax.tree.map(
                lambda ui: jnp.array(step_size, dtype=ui.dtype) * ui, u)
            return optax.apply_updates(params, u), new_inner

        new_params, new_opt = self._map_replicas(update)(
            state.params, state.opt_state, grads,
            hp["lr"], hp["wd"], hp["warmup"])

        # per-replica divergence guard (vector form of
        # resilience.guard_nonfinite_update) + freeze
        gnorm = self._map_replicas(optax.global_norm)(grads)
        finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
        ok = finite & ~state.frozen
        params = _select_replicas(ok, new_params, state.params)
        opt_state = _select_replicas(ok, new_opt, state.opt_state)
        streak = jnp.where(
            ok, 0,
            jnp.where(state.frozen, state.nonfinite_streak,
                      state.nonfinite_streak + 1)).astype(jnp.int32)
        frozen = state.frozen | (streak >= self.freeze_after)
        new_state = HFTATrainState(
            step=state.step + 1, params=params, opt_state=opt_state,
            nonfinite_streak=streak, frozen=frozen)
        metrics = {"loss": loss, "accuracy": acc,
                   "nonfinite_streak": streak, "frozen": frozen}
        return new_state, metrics

    def train_step(self, state: HFTATrainState, tokens, targets, mask=None):
        """One fused step over a [K, B, S] batch; metrics come back as
        [K] vectors. Deliberately NOT under activation_rules_scope (see
        module docstring)."""
        if mask is None:
            mask = jnp.ones(targets.shape, jnp.float32)
        if self._batch_sharding is not None:
            tokens = jax.device_put(tokens, self._batch_sharding)
            targets = jax.device_put(targets, self._batch_sharding)
            mask = jax.device_put(mask, self._batch_sharding)
        return self._step(state, self._hp_arrays, tokens, targets, mask)

    # -- per-replica extraction / checkpoints --------------------------------

    def extract_replica(self, state: HFTATrainState, k: int) -> LMTrainState:
        """Slice replica k back out as a plain LMTrainState whose
        opt_state has the exact make_adamw(cfg_k) chain shape, so it
        checkpoints/restores like any solo run."""
        take = lambda x: x[k]
        params = jax.tree.map(take, state.params)
        inner = jax.tree.map(take, state.opt_state)
        adam = inner[1]                              # ScaleByAdamState
        cfg_k = self.hparams.replica_config(self.config, k)
        tx = make_adamw(cfg_k)
        full = tx.init(params)
        # chain(clip, adamw) state:
        #   (EmptyState, (ScaleByAdamState, EmptyState, ScaleByScheduleState))
        opt_state = (full[0], (
            full[1][0]._replace(count=adam.count, mu=adam.mu, nu=adam.nu),
            full[1][1],
            full[1][2]._replace(count=adam.count),
        ))
        return LMTrainState(
            step=state.step, params=params, opt_state=opt_state,
            tx=tx, apply_fn=self.model.apply,
            nonfinite_streak=jax.tree.map(take, state.nonfinite_streak))

    def export_replica_checkpoint(self, directory: str,
                                  state: HFTATrainState, k: int,
                                  block: bool = True) -> str:
        """Write replica k as a NORMAL single-model checkpoint a plain
        LMTrainer can restore (the finished-sweep-member export path)."""
        from .checkpoint import save_checkpoint
        return save_checkpoint(directory, self.extract_replica(state, k),
                               block=block)

    # -- benchmark loop ------------------------------------------------------

    def _replica_flops_per_step(self, state) -> float:
        cfg, mcfg = self.config, self.model.config
        n_params = flops.param_count(state.params) // self.k
        per_token = flops.transformer_train_flops_per_token(
            n_params, mcfg.num_layers, mcfg.embed_dim, cfg.seq_len,
            causal=getattr(mcfg, "causal", True))
        return per_token * cfg.global_batch_size * cfg.seq_len

    def benchmark(self, state: HFTATrainState, dataset,
                  num_steps: int = 50, warmup_steps: int = 5,
                  log: Callable[[str], None] = print,
                  registry: Optional[Registry] = None,
                  faults: Optional[FaultInjector] = None,
                  step_hook: Optional[Callable] = None,
                  events=None
                  ) -> Tuple[HFTATrainState, Dict[str, Any]]:
        """Timed fused loop. `dataset` yields ([K,B,S] tokens, [K,B,S]
        targets). Per-replica throughput/MFU/goodput land as LABELED
        tpu_worker_* series (labels={"replica": k}) on one shared
        registry — the per-job view the packing controller scrapes.
        `events` (an EventLog) gets the same treatment: all K replicas
        share the file, so each replica's records are emitted through a
        bound view stamping the matching ``replica`` label."""
        cfg = self.config
        kk = self.k
        reg = registry if registry is not None else Registry()
        tels = [TrainTelemetry(reg, labels={"replica": str(k)})
                for k in range(kk)]
        evs = ([events.bind(replica=str(k)) for k in range(kk)]
               if events is not None else None)
        if faults is None:
            faults = FaultInjector.from_env()
        if faults is not None and faults.events is None:
            faults.events = events

        it = iter(dataset)
        tokens, targets = next(it)
        replica_flops = self._replica_flops_per_step(state)
        replica_tokens_per_step = cfg.global_batch_size * cfg.seq_len
        n_devices = self.mesh.size

        state, metrics = self.train_step(state, tokens, targets)  # compile
        for _ in range(max(0, warmup_steps - 1)):
            tokens, targets = next(it)
            state, metrics = self.train_step(state, tokens, targets)
        np.asarray(metrics["loss"])                  # sync before timing

        base_step = int(state.step)
        log_every = max(1, min(cfg.log_every, num_steps))
        prev_frozen = np.asarray(state.frozen).astype(bool).copy()
        windows: List[Dict[str, Any]] = []
        t0 = g0 = time.perf_counter()
        start = t0
        for i in range(1, num_steps + 1):
            if faults is not None:
                k_poison = faults.check_nan_replica(base_step + i - 1)
                if k_poison is not None:
                    log(f"fault-inject: NaN into replica {k_poison} "
                        f"at step {base_step + i - 1}")
                    state = poison_replica(state, k_poison)
            tokens, targets = next(it)
            state, metrics = self.train_step(state, tokens, targets)
            if step_hook is not None:
                step_hook(state, base_step + i)
            if i % log_every == 0:
                loss = np.asarray(metrics["loss"])   # host sync
                t1 = time.perf_counter()
                dt = max(t1 - t0, 1e-9)
                streaks = np.asarray(metrics["nonfinite_streak"])
                frozen = np.asarray(metrics["frozen"])
                tps_replica = replica_tokens_per_step * log_every / dt
                mfu_stats = flops.throughput_stats(
                    replica_flops, log_every / dt, 1)
                for k in range(kk):
                    tels[k].host_gap_seconds.observe(max(t1 - g0, 0.0))
                    tels[k].observe_steps(dt / log_every, log_every)
                    tels[k].update_window(tokens_per_sec=tps_replica,
                                          mfu=mfu_stats.get("mfu"),
                                          step=base_step + i)
                    tels[k].record_streak(int(streaks[k]))
                    # a replica freezing is a discrete, precious fact —
                    # one labeled record per transition, not per window
                    if evs is not None and frozen[k] and not prev_frozen[k]:
                        evs[k].emit(tev.REPLICA_FROZEN,
                                    step=base_step + i,
                                    streak=int(streaks[k]))
                prev_frozen = frozen.astype(bool).copy()
                windows.append({
                    "steps": log_every, "seconds": dt,
                    "loss": loss.tolist(), "frozen": frozen.tolist(),
                })
                log(f"hfta step {base_step + i} "
                    f"loss[K]={np.round(loss, 4).tolist()} "
                    f"agg_tokens/s={tps_replica * kk:,.0f} "
                    f"frozen={int(frozen.sum())}/{kk}")
                t0 = time.perf_counter()
                g0 = t0
        wall = time.perf_counter() - start

        steady = windows[1:] if len(windows) > 1 else windows
        steady_steps = sum(w["steps"] for w in steady)
        steady_secs = max(sum(w["seconds"] for w in steady), 1e-9)
        steps_per_sec = steady_steps / steady_secs
        agg_tokens_per_sec = replica_tokens_per_step * kk * steps_per_sec
        agg_stats = flops.throughput_stats(
            replica_flops * kk, steps_per_sec, n_devices)
        final_loss = windows[-1]["loss"] if windows else [float("nan")] * kk
        frozen_now = np.asarray(state.frozen)
        per_replica = {
            "tokens_per_sec": [replica_tokens_per_step * steps_per_sec] * kk,
            "mfu": [flops.throughput_stats(replica_flops, steps_per_sec,
                                           1).get("mfu")] * kk,
            "goodput": [float(t.goodput.value) for t in tels],
            "loss": [float(x) for x in final_loss],
            "frozen": frozen_now.tolist(),
            "nonfinite_streak": np.asarray(state.nonfinite_streak).tolist(),
        }
        result = {
            "k": kk,
            "tokens_per_sec": agg_tokens_per_sec,
            "tokens_per_sec_per_device": agg_tokens_per_sec / n_devices,
            "wall_seconds": wall,
            "final_loss": per_replica["loss"],
            "frozen_replicas": int(frozen_now.sum()),
            "per_replica": per_replica,
        }
        result.update(agg_stats)
        return state, result


__all__ = ["HFTAHyperparams", "HFTATrainState", "HFTATrainer",
           "poison_replica"]
