"""Language-model trainer — sharded-parameter training for the transformer
ladder (GPT-2, BERT; BASELINE.json configs[2-3]).

Where train.trainer.Trainer replicates parameters (the reference's
Horovod-style DP, SURVEY.md §2.3), this trainer is the TPU-native
generalization: parameters live in the layout given by the logical sharding
rules (parallel/sharding.py) — fsdp-sharded storage, tp-sharded Megatron
matmuls — and the batch is sharded over the data axes. The gradient
collectives (allreduce over dp, reduce-scatter/all-gather over fsdp, the tp
pair inside each layer) are all inserted by XLA from the sharding
annotations; no hand-written communication.

Remat: cfg.remat wraps each block in jax.checkpoint inside the model
(models/transformer.py), trading FLOPs for HBM as SURVEY directs.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, batch_spec
from ..parallel.sharding import activation_rules_scope, shard_init
from ..telemetry import TrainTelemetry, span
from ..utils import flops
from ..utils.profiling import WindowProfiler


class LMTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Callable = struct.field(pytree_node=False)
    # consecutive non-finite (skipped) steps, maintained ON DEVICE by the
    # divergence guard (resilience.guard_nonfinite_update); not persisted
    # in checkpoints (a restore starts a fresh streak)
    nonfinite_streak: Any = 0

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(step=self.step + 1,
                            params=optax.apply_updates(self.params, updates),
                            opt_state=new_opt)


@dataclass
class LMTrainerConfig:
    global_batch_size: int = 32
    seq_len: int = 1024
    learning_rate: float = 2.5e-4
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    # "linear": warmup then constant (the benchmark default — throughput
    # runs never reach decay territory). "cosine": warmup then cosine
    # decay over decay_steps down to end_lr_fraction of the peak (the
    # standard pretraining schedule, GPT-2/BERT style).
    lr_schedule: str = "linear"
    decay_steps: int = 10_000
    end_lr_fraction: float = 0.1
    moe_aux_weight: float = 0.01
    masked_lm: bool = False        # BERT-style objective over masked slots
    # chunked tied-head xent (fused_lm_loss): the full [B*S, vocab] logits
    # never hit HBM; causal models only (BERT's MLM head has extra layers)
    fused_xent: bool = False
    # gradient accumulation: split each global batch into `accum_steps`
    # microbatches, lax.scan the fwd+bwd over them, apply ONE optimizer
    # update on the summed gradient — numerically identical to the
    # unaccumulated step because every microbatch objective is normalized
    # by the FULL batch's mask count (masked objectives included; see
    # _loss_fn), with activation memory divided by accum_steps
    accum_steps: int = 1
    log_every: int = 10
    # divergence guard: a step with non-finite loss/grad-norm applies NO
    # update (resilience.guard_nonfinite_update); numerically a no-op on
    # finite steps, the selects fuse into the optimizer update
    guard_nonfinite: bool = True


def make_lr_schedule(cfg: LMTrainerConfig) -> optax.Schedule:
    """The LR curve make_adamw drives: warmup-linear (constant after
    warmup) or warmup-cosine decaying to end_lr_fraction of the peak."""
    if cfg.lr_schedule == "linear":
        return optax.linear_schedule(0.0, cfg.learning_rate,
                                     max(1, cfg.warmup_steps))
    if cfg.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=cfg.learning_rate,
            warmup_steps=max(1, cfg.warmup_steps),
            decay_steps=max(cfg.decay_steps, cfg.warmup_steps + 1),
            end_value=cfg.learning_rate * cfg.end_lr_fraction)
    raise ValueError(f"lr_schedule={cfg.lr_schedule!r}; expected "
                     f"'linear' or 'cosine'")


def make_adamw(cfg: LMTrainerConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(make_lr_schedule(cfg), b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def lm_loss(logits, targets, mask=None, denom=None):
    """Token-level softmax cross-entropy; mask selects scored positions
    (next-token LM passes all-ones, MLM passes the masked slots). `denom`
    overrides the normalizer (gradient accumulation passes the FULL-batch
    mask count so microbatch grads sum to exactly the full-batch grad)."""
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is None and denom is None:
        return losses.mean()
    if mask is None:
        mask = jnp.ones(losses.shape, jnp.float32)
    d = denom if denom is not None else jnp.maximum(mask.sum(), 1)
    return (losses * mask).sum() / d


def fused_lm_loss(h, table, targets, mask=None, num_chunks: int = 8,
                  denom=None):
    """Tied-head projection + softmax-xent, chunked over tokens so the full
    [B·S, vocab] logits NEVER materialize in HBM.

    The un-fused path writes the f32 logits (e.g. 1.65 GB for gpt2-medium
    at batch 16 × seq 512), reads them through softmax, and — under the
    dots remat policy — holds them as a forward→backward residual. Here a
    `lax.scan` over token chunks computes each chunk's loss from a
    transient [C, vocab] logits tile, and `jax.checkpoint` on the chunk
    body makes the backward recompute that tile instead of saving it —
    HBM traffic and the residual both shrink by num_chunks×.

    h: [B, S, E] backbone output (CausalLM __call__ with_head=False);
    table: the [V, E] tied embedding (params['wte']['embedding']).
    Numerically equals lm_loss(tied_logits(h, wte), targets, mask).

    Chunking is along the SEQUENCE axis only — the batch axis stays intact
    so a dp/fsdp-sharded batch keeps its sharding through the scan (a
    [B·S]-flattened chunking would force GSPMD to all-gather the whole
    activation on every device). num_chunks degrades to gcd(num_chunks, S)
    when S is not divisible (power-of-two seq lens keep all 8)."""
    from ..models.transformer import _head_matmul

    B, S, E = h.shape
    num_chunks = math.gcd(num_chunks, S)
    C = S // num_chunks
    h_r = jnp.moveaxis(h.reshape(B, num_chunks, C, E), 1, 0)
    t_r = jnp.moveaxis(targets.reshape(B, num_chunks, C), 1, 0)
    m = (jnp.ones((B, S), jnp.float32) if mask is None
         else mask.astype(jnp.float32))
    m_r = jnp.moveaxis(m.reshape(B, num_chunks, C), 1, 0)
    table = table.astype(h.dtype)

    def chunk(carry, xs):
        h_c, t_c, m_c = xs                             # [B, C, ...]
        logits = _head_matmul(h_c, table)              # [B, C, V] transient
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, t_c)
        return carry + (losses * m_c).sum(), None

    total, _ = lax.scan(jax.checkpoint(chunk), jnp.zeros((), jnp.float32),
                        (h_r, t_r, m_r))
    d = denom if denom is not None else jnp.maximum(m_r.sum(), 1)
    return total / d


def tp_overlap_lm_loss(h, table, targets, mask, mesh, num_chunks: int = 8,
                       denom=None, ring: str = "uni"):
    """fused_lm_loss with the logits matmul VOCAB-PARALLEL and overlapped:
    one manual region over the whole chunk scan where h enters seq-over-tp
    sharded and each chunk's logits tile is a ring
    `allgather_matmul(h_chunk, tableᵀ_local)` — the tp all-gather of the
    hidden rows hides behind the per-shard vocab matmuls
    (parallel/collectives.py), and the backward's dh comes out as the
    mirrored overlapped reduce-scatter via the custom_vjp.

    Each rank only ever holds a [B, C, V/tp] logits tile (the chunking
    memory win times the vocab-parallel win); the softmax normalizer and
    the target logit are completed across vocab shards with psums — the
    Megatron vocab-parallel cross-entropy, in autodiff form. Numerically
    equals fused_lm_loss / lm_loss to accumulation-order tolerance.

    Vocab/seq not divisible by the tp degree are zero-padded up to the
    next multiple (pad seq rows carry mask 0, pad vocab columns are forced
    to -inf logits before the normalizer) — the loss is exactly the
    unpadded one; trainers gate on TransformerConfig.tp_overlap.
    `ring` selects the collective-matmul schedule ('uni'/'bidir' — see
    parallel/collectives.py); both are numerically identical."""
    from ..parallel.collectives import allgather_matmul
    from ..parallel.sharding import (tp_manual_spec,
                                     tp_overlap_activation_spec)
    from ..utils.compat import shard_map

    B, S, E = h.shape
    V = table.shape[0]
    tp = dict(mesh.shape).get("tp", 1)
    if mask is None:
        mask = jnp.ones((B, S), jnp.float32)
    mask = mask.astype(jnp.float32)
    pad_s = (-S) % tp
    if pad_s:
        # pad rows: zero hidden, target 0 (any valid id), mask 0 — they
        # contribute nothing to the loss or the denominator
        h = jnp.pad(h, ((0, 0), (0, pad_s), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad_s)))
        mask = jnp.pad(mask, ((0, 0), (0, pad_s)))
        S += pad_s
    pad_v = (-V) % tp
    if pad_v:
        # pad vocab rows are zeros; their logit columns are masked to -inf
        # inside the chunk so they never enter the softmax normalizer
        table = jnp.pad(table, ((0, pad_v), (0, 0)))
    Sl = S // tp
    nc = math.gcd(num_chunks, Sl)
    Cl = Sl // nc
    have_denom = denom is not None

    def body(h_l, t_l, m_l, table_l, *d):
        Bl = h_l.shape[0]
        idx = lax.axis_index("tp")
        Vl = table_l.shape[0]
        offset = idx * Vl
        wt = table_l.astype(h_l.dtype).T                 # [E, Vl]
        h_r = jnp.moveaxis(h_l.reshape(Bl, nc, Cl, E), 1, 0)
        t_r = jnp.moveaxis(t_l.reshape(Bl, nc, Cl), 1, 0)
        m_r = jnp.moveaxis(m_l.reshape(Bl, nc, Cl), 1, 0)

        def chunk(carry, xs):
            h_c, t_c, m_c = xs                           # [Bl, Cl, ...]
            # [Bl, tp·Cl, Vl]: every rank's chunk rows × my vocab columns;
            # row placement (src·Cl) matches the tiled all_gather below
            logits = allgather_matmul(h_c, wt, "tp", ring=ring)
            if pad_v:
                cols = offset + jnp.arange(Vl)
                logits = jnp.where(cols < V, logits, -1e30)
            t_g = lax.all_gather(t_c, "tp", axis=1, tiled=True)
            # vocab-parallel softmax-xent: max/normalizer/target-pick each
            # completed across the vocab shards with one collective
            # (max via a tiny [tp, Bl, tp·Cl] all_gather — lax.pmax has no
            # autodiff rule on legacy jax, and all_gather does even though
            # the max's cotangent is stopped anyway)
            lmax = lax.stop_gradient(
                lax.all_gather(logits.max(-1), "tp").max(0))
            ex = jnp.exp(logits.astype(jnp.float32) - lmax[..., None])
            sumexp = lax.psum(ex.sum(-1), "tp")
            t_loc = t_g - offset
            valid = (t_loc >= 0) & (t_loc < Vl)
            picked = jnp.take_along_axis(
                logits, jnp.clip(t_loc, 0, Vl - 1)[..., None], axis=-1)[..., 0]
            tgt = lax.psum(
                jnp.where(valid, picked.astype(jnp.float32), 0.0), "tp")
            losses = jnp.log(sumexp) + lmax - tgt        # [Bl, tp·Cl]
            mine = lax.dynamic_slice_in_dim(losses, idx * Cl, Cl, axis=1)
            return carry + (mine * m_c).sum()[None], None

        # rank-1 carry: differentiating a scan with a RANK-0 carry inside
        # legacy shard_map leaves a scalar residual the partial-eval can't
        # name ({0: axes} on a shapeless aval -> _SpecError)
        total, _ = lax.scan(jax.checkpoint(chunk),
                            jnp.zeros((1,), jnp.float32), (h_r, t_r, m_r))
        # sum the per-rank row contributions; NOT over pp/ep (batch and seq
        # are replicated there — the value is already complete)
        total = lax.psum(total, BATCH_AXES + ("tp",))
        if have_denom:
            dd = d[0].reshape(1)
        else:
            dd = jnp.maximum(lax.psum(m_l.sum(), BATCH_AXES + ("tp",)),
                             1)[None]
        # total stays rank-1 throughout: legacy shard_map also can't stitch
        # rank-0 OUTPUTS under check_rep=False (the value IS mesh-constant
        # after the psum; the caller drops the singleton)
        return total / dd

    seq_spec = tp_overlap_activation_spec(3)
    row_spec = tp_overlap_activation_spec(2)
    in_specs = (seq_spec, row_spec, row_spec,
                tp_manual_spec(("vocab", "embed")))
    args = [h, targets, mask, table]
    if have_denom:
        in_specs = in_specs + (P(),)
        args.append(jnp.asarray(denom, jnp.float32))
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P(),
                   check_vma=False)
    return fn(*args)[0]


class LMTrainer:
    """Sharded trainer over a Mesh. Params are created directly in their
    ruled layout (shard_init), the optimizer state inherits it, and the jit
    carries explicit in/out shardings so the step never re-lays-out state.
    """

    def __init__(self, model, mesh: Mesh,
                 config: Optional[LMTrainerConfig] = None,
                 tx: Optional[optax.GradientTransformation] = None):
        self.model = model
        self.mesh = mesh
        self.config = config or LMTrainerConfig()
        self.tx = tx or make_adamw(self.config)
        # [B, S] batches: batch over the data axes, seq over sp (context
        # parallelism — attention="ring" rings the K/V shards; everything
        # else in the model is position-wise so GSPMD shards it over seq
        # for free). sp=1 meshes get the same spec, trivially.
        sp = dict(mesh.shape).get("sp", 1)
        if self.config.seq_len % max(sp, 1):
            raise ValueError(
                f"seq_len={self.config.seq_len} not divisible by the mesh's "
                f"sp={sp}; context parallelism shards the sequence axis")
        self.batch_sharding = NamedSharding(mesh, batch_spec(("sp",)))
        A = self.config.accum_steps
        nb = math.prod(mesh.shape[a] for a in BATCH_AXES)
        if A < 1:
            raise ValueError(f"accum_steps={A} must be >= 1")
        if A > 1 and self.config.global_batch_size % (A * nb):
            raise ValueError(
                f"global_batch_size={self.config.global_batch_size} must "
                f"split into accum_steps={A} microbatches of whole "
                f"per-device shards (data-parallel degree {nb})")
        self.replicated = NamedSharding(mesh, P())
        self._step = None
        self._eval = None
        self._state_shardings = None

    def init_state(self, rng: jax.Array) -> LMTrainState:
        cfg = self.config
        # batch dim sized to the data-axes product: the nested ring
        # shard_map (attention="ring") needs every global dim divisible by
        # its mapped mesh axes, init included
        nb = math.prod(self.mesh.shape[a] for a in BATCH_AXES)
        dummy = jnp.zeros((max(2, nb), cfg.seq_len), jnp.int32)
        # under the scope so attention="ring" can resolve the ambient mesh
        # while tracing init (same context the step runs in)
        with activation_rules_scope(self.mesh):
            variables, shardings = shard_init(self.model, self.mesh, rng,
                                              dummy)
        params = variables["params"]
        param_sh = shardings["params"]

        def init_opt(p):
            return self.tx.init(p)
        # optimizer state shardings mirror the params they track
        opt_abstract = jax.eval_shape(init_opt, params)
        opt_sh = _opt_shardings(opt_abstract, params, param_sh,
                                self.replicated)
        opt_state = jax.jit(init_opt, out_shardings=opt_sh)(params)
        state = LMTrainState(step=jnp.zeros((), jnp.int32), params=params,
                             opt_state=opt_state, tx=self.tx,
                             apply_fn=self.model.apply,
                             nonfinite_streak=jnp.zeros((), jnp.int32))
        self._state_shardings = LMTrainState(
            step=self.replicated, params=param_sh, opt_state=opt_sh,
            tx=self.tx, apply_fn=self.model.apply,
            nonfinite_streak=self.replicated)
        return state

    def _use_fused(self):
        mcfg = getattr(self.model, "config", None)
        return (self.config.fused_xent and mcfg is not None and mcfg.causal
                and not self.config.masked_lm)

    def _use_overlap_loss(self):
        """Ring-overlapped vocab-parallel loss: only meaningful when the
        mesh actually has a tp ring to rotate around and the model opted in
        (TransformerConfig.tp_overlap). Falls back to fused_lm_loss (the
        oracle path) otherwise — same loss value either way."""
        mcfg = getattr(self.model, "config", None)
        return (mcfg is not None and getattr(mcfg, "tp_overlap", False)
                and dict(self.mesh.shape).get("tp", 1) > 1)

    def _loss_fn(self, params, tokens, targets, mask, denom=None,
                 aux_scale=1.0, include_aux=True):
        """`denom`/`aux_scale` support exact gradient accumulation: with
        denom = the FULL-batch mask count and aux_scale = 1/accum_steps,
        the SUM of microbatch gradients equals the full-batch gradient by
        linearity — masked objectives included (each microbatch's own
        mask.sum() would weight tokens unevenly)."""
        if self._use_fused():
            h, interm = self.model.apply(
                {"params": params}, tokens, with_head=False,
                mutable=["intermediates"])
            if self._use_overlap_loss():
                ring = getattr(self.model.config, "tp_ring", "uni")
                loss = tp_overlap_lm_loss(h, params["wte"]["embedding"],
                                          targets, mask, self.mesh,
                                          denom=denom, ring=ring)
            else:
                loss = fused_lm_loss(h, params["wte"]["embedding"], targets,
                                     mask, denom=denom)
            logits = None
        else:
            logits, interm = self.model.apply(
                {"params": params}, tokens, mutable=["intermediates"])
            loss = lm_loss(logits, targets, mask, denom=denom)
        aux = jax.tree.leaves(interm.get("intermediates", {}))
        if aux and include_aux:
            loss = loss + aux_scale * self.config.moe_aux_weight * sum(
                jnp.asarray(a).mean() for a in aux)
        return loss, logits

    def _step_fn(self, state: LMTrainState, tokens, targets, mask):
        A = self.config.accum_steps
        if A > 1:
            B = tokens.shape[0]
            # Each microbatch objective is normalized by the FULL batch's
            # mask count (and aux scaled by 1/A), so summing microbatch
            # grads reproduces the full-batch grad EXACTLY — masked
            # objectives included. Batch stays the leading microbatch dim
            # so the dp/fsdp sharding survives the reshape.
            total = jnp.maximum(mask.sum(), 1.0)

            def micro(carry, xs):
                loss_sum, grad_sum = carry
                t, g, m = xs
                (loss, _), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(
                        state.params, t, g, m, denom=total,
                        aux_scale=1.0 / A)
                return (loss_sum + loss,
                        jax.tree.map(jnp.add, grad_sum, grads)), None
            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (loss_sum, grad_sum), _ = lax.scan(
                micro, (jnp.zeros(()), zeros),
                (tokens.reshape(A, B // A, *tokens.shape[1:]),
                 targets.reshape(A, B // A, *targets.shape[1:]),
                 mask.reshape(A, B // A, *mask.shape[1:])))
            state = self._guarded(state, state.apply_gradients(grad_sum),
                                  loss_sum, grad_sum)
            # accuracy would need the per-microbatch logits kept alive —
            # defeats the memory point of accumulating
            return state, {"loss": loss_sum,
                           "accuracy": jnp.full((), jnp.nan),
                           "nonfinite_streak": state.nonfinite_streak}
        (loss, logits), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(state.params, tokens, targets, mask)
        state = self._guarded(state, state.apply_gradients(grads), loss,
                              grads)
        if logits is None:
            # fused path never materializes logits; accuracy is a
            # diagnostic, not worth a second vocab projection
            acc = jnp.full((), jnp.nan)
        else:
            acc = jnp.sum((jnp.argmax(logits, -1) == targets) * mask) \
                / jnp.maximum(mask.sum(), 1)
        return state, {"loss": loss, "accuracy": acc,
                       "nonfinite_streak": state.nonfinite_streak}

    def _guarded(self, old_state, new_state, loss, grads):
        if not self.config.guard_nonfinite:
            return new_state
        from .resilience import guard_nonfinite_update
        return guard_nonfinite_update(old_state, new_state, loss, grads)

    def compile_step(self):
        if self._step is None:
            assert self._state_shardings is not None, "call init_state first"
            self._step = jax.jit(
                self._step_fn,
                in_shardings=(self._state_shardings, self.batch_sharding,
                              self.batch_sharding, self.batch_sharding),
                out_shardings=(self._state_shardings, self.replicated),
                donate_argnums=(0,),
            )
        return self._step

    def _eval_fn(self, params, tokens, targets, mask):
        # no aux term: the MoE load-balancing loss exists only to shape
        # gradients — including it would inflate exp(val_loss) past true
        # perplexity for MoE models
        loss, _ = self._loss_fn(params, tokens, targets, mask,
                                include_aux=False)
        return loss

    def compile_eval(self):
        if self._eval is None:
            assert self._state_shardings is not None, "call init_state first"
            self._eval = jax.jit(
                self._eval_fn,
                in_shardings=(self._state_shardings.params,
                              self.batch_sharding, self.batch_sharding,
                              self.batch_sharding),
                out_shardings=self.replicated,
            )
        return self._eval

    def eval_step(self, state, tokens, targets, mask=None):
        """Loss-only forward at the current params (no grads, no update)."""
        if mask is None:
            mask = jnp.ones_like(targets, jnp.float32)
        with activation_rules_scope(self.mesh):
            return self.compile_eval()(state.params, tokens, targets,
                                       mask.astype(jnp.float32))

    def evaluate(self, state, dataset, num_batches: int = 10
                 ) -> Dict[str, float]:
        """Mean held-out loss + perplexity over `num_batches` batches of
        `dataset` (same batch contract as the training stream)."""
        total = 0.0
        it = iter(dataset)
        for _ in range(num_batches):
            total += float(self.eval_step(state, *next(it)))
        mean = total / max(1, num_batches)
        return {"val_loss": mean, "perplexity": math.exp(min(mean, 30.0))}

    def train_step(self, state, tokens, targets, mask=None):
        if mask is None:
            mask = jnp.ones_like(targets, jnp.float32)
        mask = mask.astype(jnp.float32)
        # activation_rules_scope makes the model's residual-stream
        # constraints live during tracing (first call compiles); they pin
        # activations to batch-sharded/embed-replicated so GSPMD never pays
        # an involuntary full remat reconciling inferred layouts
        with activation_rules_scope(self.mesh):
            return self.compile_step()(state, tokens, targets, mask)

    def _step_flops(self, state, probe) -> Optional[float]:
        """GLOBAL model FLOPs for one train step. Analytic 6N+attention is
        primary (the conventional MFU numerator; XLA's cost model scores
        Pallas custom calls as 0 FLOPs, so it blind-spots the flash
        attention share); per-device cost model × mesh size is the
        fallback for models without a config."""
        mcfg = getattr(self.model, "config", None)
        if mcfg is not None:
            per_token = flops.transformer_train_flops_per_token(
                flops.param_count(state.params), mcfg.num_layers,
                mcfg.embed_dim, self.config.seq_len, causal=mcfg.causal)
            return (per_token * self.config.global_batch_size
                    * self.config.seq_len)
        batch = tuple(probe)
        if len(batch) == 2:
            batch = (*batch, jnp.ones_like(batch[1], jnp.float32))
        else:
            batch = (*batch[:2], batch[2].astype(jnp.float32))
        try:
            with activation_rules_scope(self.mesh):
                compiled = self.compile_step().lower(state, *batch).compile()
            counted = flops.compiled_flops(compiled)
        except Exception:  # noqa: BLE001 — cost model is best-effort
            counted = None
        # cost analysis sees the post-SPMD-partition (per-device) module
        return counted * self.mesh.size if counted is not None else None

    def benchmark(self, state, dataset, num_steps: int = 50,
                  warmup_steps: int = 5, log: Callable[[str], None] = print,
                  profile_dir: Optional[str] = None,
                  step_hook: Optional[Callable] = None,
                  resilience=None, telemetry: Optional[TrainTelemetry] = None,
                  ) -> Tuple[LMTrainState, Dict[str, float]]:
        """tokens/sec measurement, same windowed protocol as
        train.trainer.Trainer.benchmark (ref README.md:113-131 format).
        step_hook(state, step) fires after every step (periodic async
        checkpointing — train/checkpoint.periodic_saver).

        resilience: an entered train.resilience.ResilienceContext —
        per-step stop-bit check (emergency checkpoint + Preempted on a
        gang drain) and divergence rollback at window fetches; see
        Trainer.benchmark.

        telemetry: a telemetry.TrainTelemetry to feed (pass one backed by
        a served registry to expose a live /metrics); when None a private
        recorder still runs so step_time_p50/p99_ms and goodput always
        land in the returned metrics dict. Instruments are only touched at
        window fetches — the loop body dispatches async, so per-iteration
        host time is not a step time; the window average is."""
        cfg = self.config
        tel = telemetry if telemetry is not None else TrainTelemetry()
        if resilience is not None and resilience.telemetry is None:
            resilience.telemetry = tel    # rollback accounting → goodput
        it = iter(dataset)
        probe = next(it)
        state, metrics = self.train_step(state, *probe)   # compiles
        flops_per_step = self._step_flops(state, probe)
        for _ in range(max(0, warmup_steps - 1)):
            batch = next(it)
            state, metrics = self.train_step(state, *batch)
        float(metrics["loss"])
        base_step = int(state.step)       # one host read, OUTSIDE the loop
        tokens_per_step = cfg.global_batch_size * cfg.seq_len
        n = self.mesh.size
        log_every = max(1, min(cfg.log_every, num_steps))
        windows = []
        profiler = WindowProfiler(profile_dir, log)
        profiler.start()
        t0 = time.perf_counter()
        wall0 = t0
        try:
            for i in range(1, num_steps + 1):
                batch = next(it)
                with span("train.step"):
                    state, metrics = self.train_step(state, *batch)
                if step_hook is not None:
                    step_hook(state, base_step + i)
                if resilience is not None \
                        and resilience.on_step(base_step + i):
                    from .resilience import Preempted
                    log(f"preemption drain: stopping the gang at step "
                        f"{base_step + i}")
                    resilience.emergency_save(state)
                    raise Preempted(base_step + i)
                if i % log_every == 0:
                    g0 = time.perf_counter()
                    loss = float(metrics["loss"])  # the window's one sync
                    t1 = time.perf_counter()       # BEFORE the trace write
                    tel.host_gap_seconds.observe(t1 - g0)
                    profiler.stop_if_active()
                    tps = tokens_per_step * log_every / (t1 - t0)
                    windows.append(tps)
                    tel.observe_steps((t1 - t0) / log_every, log_every)
                    tel.update_window(
                        tokens_per_sec=tps,
                        mfu=flops.throughput_stats(
                            flops_per_step, tps / tokens_per_step, n)["mfu"],
                        step=base_step + i)
                    streak = int(metrics.get("nonfinite_streak", 0))
                    if streak:
                        tel.record_streak(streak)
                    log(f"{i}\ttokens/sec: {tps:.0f}\tloss: {loss:.3f}")
                    if resilience is not None \
                            and streak >= resilience.config.divergence_k:
                        state = resilience.rollback(state)
                        base_step = int(state.step) - i
                    t0 = time.perf_counter()
        finally:
            profiler.stop_if_active()
        steady = windows[1:] if len(windows) > 1 else windows
        tps = sum(steady) / len(steady)
        stats = flops.throughput_stats(flops_per_step,
                                       tps / tokens_per_step, n)
        p50_ms, p99_ms = tel.step_percentiles_ms()
        gap50_ms, gap99_ms = tel.host_gap_percentiles_ms()
        log("-" * 40)
        log(f"total tokens/sec: {tps:.0f}")
        if p50_ms is not None:
            log(f"step time: p50 {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms, "
                f"goodput {tel.goodput.value:.1%}")
        if stats["mfu"] is not None:
            log(f"per-device: {stats['tflops_per_sec_per_device']:.1f} "
                f"TFLOP/s, MFU {stats['mfu']:.1%}")
        log("-" * 40)
        return state, {
            "tokens_per_sec": tps,
            "tokens_per_sec_per_device": tps / n,
            "wall_seconds": time.perf_counter() - wall0,
            "final_loss": float(metrics["loss"]),
            "step_time_p50_ms": p50_ms,
            "step_time_p99_ms": p99_ms,
            "host_gap_p50_ms": gap50_ms,
            "host_gap_p99_ms": gap99_ms,
            "goodput": tel.goodput.value,
            **stats,
        }


def _opt_shardings(opt_abstract, params, param_sh, replicated):
    """Shard optimizer-state leaves that mirror a param (same shape) like
    that param; everything else (counts, scalars) replicates."""
    shape_to_sh = {}
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_sh = jax.tree.leaves(param_sh)
    for (path, leaf), sh in zip(flat_p, flat_sh):
        shape_to_sh.setdefault(
            tuple(path), (leaf.shape, sh))

    def pick(path, leaf):
        # match by trailing path (params appear nested inside opt state)
        for ppath, (shape, sh) in shape_to_sh.items():
            if len(path) >= len(ppath) and tuple(path[-len(ppath):]) == ppath \
                    and leaf.shape == shape:
                return sh
        return replicated

    flat_o = jax.tree_util.tree_flatten_with_path(opt_abstract)[0]
    leaves = [pick(p, l) for p, l in flat_o]
    return jax.tree.unflatten(jax.tree.structure(opt_abstract), leaves)


__all__ = ["LMTrainer", "LMTrainerConfig", "LMTrainState", "make_adamw",
           "make_lr_schedule", "lm_loss", "fused_lm_loss",
           "tp_overlap_lm_loss"]
