"""Pipeline-parallel LM trainer — end-to-end training over the pp axis.

Builds on parallel/pipeline.pipeline_lm_loss (the stage-sliced CausalLM):
this module adds the optimizer half so pp is a usable training strategy,
not just a loss function. Parameters live in the pipeline layout
(stack_lm_params: blocks stacked [L, ...] and SHARDED over pp on the layer
dim; embeddings/ln_f replicated), the AdamW state mirrors that layout leaf
for leaf, and the jitted step carries explicit shardings so XLA keeps
every tensor where it belongs — each stage's optimizer update touches only
its own L/P layer slice (the pp memory win extends to the optimizer).

Composes with data axes: the microbatch dim of the token stream is sharded
over (dcn, dp, fsdp) while the M dim is sharded over pp (the trainer's
mb % data_degree validation guarantees pipeline_lm_loss takes its
dp-sharded path), so pp×dp runs without replicating either stream and the
loss/grad psums span both axis groups.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import CausalLM, MaskedLM, TransformerConfig
from ..parallel.pipeline import (bubble_fraction, pipeline_lm_loss,
                                 pipeline_mlm_loss, stack_lm_params,
                                 stack_mlm_params)
from ..telemetry import TrainTelemetry, span
from ..utils import flops
from .lm_trainer import LMTrainerConfig, _opt_shardings, make_adamw


class PPTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any                       # stack_lm_params layout
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


class PipelineLMTrainer:
    """GPipe training over mesh axes pp × tp × (dcn, dp, fsdp).

    tp composes via GSPMD: the block params are PLACED with Megatron
    shardings (lm_stage_tp_specs) and pipeline_lm_loss runs tp as an auto
    axis, so each stage tick partitions its matmuls over tp with XLA
    inserting the collective pair — no manual tp code in the schedule.

    num_microbatches M must divide over pp; pick M >= 4 × pp to keep the
    bubble (P-1)/(M+P-1) small (parallel/pipeline.bubble_fraction)."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh,
                 config: Optional[LMTrainerConfig] = None,
                 num_microbatches: Optional[int] = None,
                 tx: Optional[optax.GradientTransformation] = None,
                 schedule: str = "gpipe", interleave: int = 1):
        self.cfg = cfg
        self.mesh = mesh
        self.config = config or LMTrainerConfig()
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule={schedule!r}; expected gpipe|1f1b")
        if interleave < 1:
            raise ValueError(f"interleave={interleave} must be >= 1")
        if interleave > 1 and schedule != "1f1b":
            raise ValueError("interleave>1 requires schedule='1f1b' "
                             "(virtual stages are a 1F1B concept)")
        self.schedule = schedule
        self.interleave = interleave
        # masked LM (BERT family): both schedules — GPipe relays the mask
        # stream (pipeline_mlm_loss); 1F1B consumes it at the last
        # virtual stage with the dynamic mask-count divisor
        # (pipeline_lm_1f1b_grads mask=)
        self.masked = bool(self.config.masked_lm)
        if self.masked and cfg.causal:
            raise ValueError("masked_lm needs a causal=False (MaskedLM) "
                             "config")
        # chunked tied-head xent on the LAST stage (lm_stage_head_loss
        # fused=True): causal models only, like the unpiped path
        self.fused_xent = bool(self.config.fused_xent)
        if self.fused_xent and self.masked:
            raise ValueError("fused_xent supports the causal LM only "
                             "(BERT's MLM head has extra layers before "
                             "the tied decoder)")
        if not self.masked and not cfg.causal:
            # next-token xent over a bidirectional model would leak every
            # future token — loss collapses while learning a degenerate
            # copy objective; refuse the mispairing loudly
            raise ValueError("a causal=False (bert) config needs "
                             "LMTrainerConfig(masked_lm=True)")
        if cfg.pos_embedding != "learned":
            raise ValueError(
                f"the pipeline trainer supports learned-position models "
                f"only (the stage embed reads the wpe table); got "
                f"pos_embedding={cfg.pos_embedding!r}")
        self.pp = mesh.shape["pp"]
        self.num_microbatches = num_microbatches or max(4 * self.pp, self.pp)
        if self.num_microbatches % self.pp:
            raise ValueError(f"num_microbatches={self.num_microbatches} "
                             f"must divide over pp={self.pp}")
        if cfg.num_layers % (self.pp * self.interleave):
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide over "
                f"pp×interleave={self.pp}×{self.interleave}")
        # pp×MoE (GPipe): stages scan (dense-run, MoE-block) periods, so
        # each stage's contiguous layer range must hold whole periods
        self.moe = cfg.num_experts > 0
        if self.moe:
            if schedule != "gpipe":
                raise ValueError("MoE composes with schedule='gpipe' only "
                                 "(the 1F1B in-schedule vjp applies dense "
                                 "stage bodies)")
            if cfg.moe_every < 2:
                raise ValueError(
                    f"pp needs moe_every >= 2 (got {cfg.moe_every}); an "
                    f"all-MoE stack has no dense blocks to period over")
            if cfg.num_layers % (cfg.moe_every * self.pp):
                raise ValueError(
                    f"num_layers={cfg.num_layers} must divide over "
                    f"moe_every×pp = {cfg.moe_every}×{self.pp} so every "
                    f"stage owns whole dense+MoE periods")
        if self.config.global_batch_size % self.num_microbatches:
            raise ValueError(
                f"global_batch_size={self.config.global_batch_size} must "
                f"divide into {self.num_microbatches} microbatches")
        data_deg = (mesh.shape["dcn"] * mesh.shape["dp"]
                    * mesh.shape["fsdp"])
        mb = self.config.global_batch_size // self.num_microbatches
        if mb % data_deg:
            raise ValueError(
                f"microbatch size {mb} (global {self.config.global_batch_size}"
                f" / M={self.num_microbatches}) must divide over the data "
                f"axes (dcn×dp×fsdp = {data_deg})")
        # pp×sp: the sequence dim of the stream shards over sp; each stage
        # tick rings its attention over the sp neighbors
        # (parallel/pipeline._lm_pipeline_local seq_sharded path)
        self.sp = dict(mesh.shape).get("sp", 1)
        if self.sp > 1:
            if cfg.attention != "ring":
                raise ValueError(
                    'pp×sp needs attention="ring" (build the model with '
                    "create_lm(..., attention=\"ring\") so stage bodies "
                    "ring their K/V shards)")
            if self.config.seq_len % self.sp:
                raise ValueError(f"seq_len={self.config.seq_len} must "
                                 f"divide over sp={self.sp}")
        self.tx = tx or make_adamw(self.config)
        # token stream [M, mb, S]: M over pp, microbatch over data axes,
        # seq over sp when context-parallel
        self.batch_sharding = NamedSharding(
            mesh, P("pp", ("dcn", "dp", "fsdp"),
                    "sp" if self.sp > 1 else None))
        self.replicated = NamedSharding(mesh, P())
        self._step = None
        self._eval_step = None
        self._state_shardings = None

    @property
    def bubble(self) -> float:
        if self.schedule == "1f1b":
            from ..parallel.pipeline_1f1b import simulate_1f1b
            return simulate_1f1b(self.pp, self.num_microbatches,
                                 self.interleave).bubble_fraction
        return bubble_fraction(self.pp, self.num_microbatches)

    # -- initialization -----------------------------------------------------

    def _param_shardings(self, params):
        from ..parallel.pipeline import lm_stage_tp_specs
        from ..parallel.sharding import _divisible_spec

        # blocks: layer dim over pp, plus Megatron tp on the mlp/attn dims
        # when tp > 1 (pipeline_lm_loss leaves tp to GSPMD, so placement IS
        # the activation of tensor parallelism) — and for the MoE stack
        # the expert dim over ep, which is what makes GSPMD lower the
        # stage's dispatch einsums to the expert all-to-all.
        # _divisible_spec replicates any dim tp/ep doesn't divide (tiny
        # test configs).
        def place(tree):
            return jax.tree.map(
                lambda leaf, spec: NamedSharding(
                    self.mesh, _divisible_spec(self.mesh, spec, leaf.shape)),
                tree, lm_stage_tp_specs(tree))

        stacked = ("blocks", "moe")
        # everything outside the stacked blocks replicates (embeddings,
        # norms, the MLM head leaves when masked)
        out = {k: jax.tree.map(lambda _: self.replicated, v)
               for k, v in params.items() if k not in stacked}
        for k in stacked:
            if k in params:
                out[k] = place(params[k])
        return out

    def init_state(self, rng: jax.Array) -> PPTrainState:
        import dataclasses

        cfg = self.cfg
        # init on the dense twin: the attention impl owns no params, and
        # "ring" (the pp×sp stage body) refuses to trace outside a live
        # sp axis — which init legitimately is
        family = MaskedLM if self.masked else CausalLM
        stack = stack_mlm_params if self.masked else stack_lm_params
        model = family(dataclasses.replace(cfg, attention="dense"))
        dummy = jnp.zeros((2, self.config.seq_len), jnp.int32)

        def init_all(rng):
            variables = meta.unbox(model.init(rng, dummy))
            params = stack(variables["params"], cfg.num_layers,
                           num_experts=cfg.num_experts,
                           moe_every=cfg.moe_every)
            if self.schedule == "1f1b" and self.interleave > 1:
                # 1F1B virtual stages: device-major chunk layout so a
                # plain pp sharding hands each device its chunk stack
                # (parallel/pipeline_1f1b.interleave_blocks); grads and
                # optimizer state live in the same layout
                from ..parallel.pipeline_1f1b import interleave_blocks
                params = dict(params)
                params["blocks"] = interleave_blocks(
                    params["blocks"], self.pp, self.interleave)
            return params, self.tx.init(params)

        abstract_p, _ = jax.eval_shape(init_all, rng)
        param_sh = self._param_shardings(abstract_p)
        opt_abstract = jax.eval_shape(self.tx.init, abstract_p)
        # AdamW moments mirror the params leaf-for-leaf: shard them
        # identically (blocks' mu/nu live pp-sharded with their layers)
        opt_sh = _opt_shardings(opt_abstract, abstract_p, param_sh,
                                self.replicated)
        # Init is jitted WITHOUT out_shardings and the result device_put
        # into the target layout afterwards. Jitting init_all with a
        # partially-sharded out_shardings miscompiles on this XLA:
        # jnp.stack/concatenate of per-layer jax.random draws (what
        # stack_lm_params builds) under an out_sharding that leaves some
        # axes replicated emits an unreduced partial-sum — every stacked
        # kernel comes out inflated by EXACTLY the replication degree
        # (total_devices / sharded_axis_size; e.g. 4x on an 8-device
        # pp=2 mesh). A with_sharding_constraint inside doesn't avoid it;
        # plain jit + device_put matches the eager oracle bit-for-bit and
        # costs one staging copy at init only.
        params, opt_state = jax.jit(init_all)(rng)
        params = jax.device_put(params, param_sh)
        opt_state = jax.device_put(opt_state, opt_sh)
        self._state_shardings = PPTrainState(
            step=self.replicated, params=param_sh, opt_state=opt_sh,
            tx=self.tx)
        return PPTrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), self.replicated),
            params=params, opt_state=opt_state, tx=self.tx)

    # -- the jitted step ----------------------------------------------------

    # -- checkpoint layout --------------------------------------------------
    # Checkpoints are ALWAYS written in canonical layer order so a run can
    # switch pp schedule / interleave across restarts without silently
    # loading permuted weights; the 1F1B device-major layout exists only
    # inside the live training state.

    def _permute_state(self, state: PPTrainState,
                       to_canonical: bool) -> PPTrainState:
        if self.schedule != "1f1b" or self.interleave <= 1:
            return state
        from ..parallel.pipeline_1f1b import (deinterleave_blocks,
                                              interleave_blocks)
        fn = deinterleave_blocks if to_canonical else interleave_blocks
        L = self.cfg.num_layers

        def fix(tree):
            # any leaf under a "blocks" path with the stacked layer dim
            # (params AND the AdamW moments mirroring them)
            def f(path, leaf):
                if ("blocks" in jax.tree_util.keystr(path)
                        and hasattr(leaf, "ndim") and leaf.ndim >= 1
                        and leaf.shape[0] == L):
                    return fn(leaf, self.pp, self.interleave)
                return leaf
            return jax.tree_util.tree_map_with_path(f, tree)

        return state.replace(params=fix(state.params),
                             opt_state=fix(state.opt_state))

    def canonical_state(self, state: PPTrainState) -> PPTrainState:
        """The checkpoint view (canonical layer order)."""
        return self._permute_state(state, to_canonical=True)

    def from_canonical_state(self, state: PPTrainState) -> PPTrainState:
        """Back to this trainer's live layout after a restore."""
        return self._permute_state(state, to_canonical=False)

    def _step_fn(self, state: PPTrainState, tokens, targets, mask=None):
        w = self.config.moe_aux_weight
        moe_metrics = {}
        if self.schedule == "1f1b":
            # 1F1B computes grads IN-SCHEDULE (backward ticks interleave
            # with forwards), so no outer jax.grad; mask= selects the
            # masked-LM head + dynamic divisor
            from ..parallel.pipeline_1f1b import pipeline_lm_1f1b_grads
            loss, grads = pipeline_lm_1f1b_grads(
                self.cfg, state.params, tokens, targets, self.mesh,
                self.num_microbatches, interleave=self.interleave,
                mask=mask if self.masked else None,
                fused_xent=self.fused_xent)
        elif self.masked:
            def loss_fn(params):
                return pipeline_mlm_loss(self.cfg, params, tokens, targets,
                                         mask, self.mesh,
                                         self.num_microbatches,
                                         moe_aux_weight=w,
                                         with_moe_metrics=True)
            (loss, moe_metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        else:
            def loss_fn(params):
                return pipeline_lm_loss(self.cfg, params, tokens, targets,
                                        self.mesh, self.num_microbatches,
                                        moe_aux_weight=w,
                                        with_moe_metrics=True,
                                        fused_xent=self.fused_xent)
            (loss, moe_metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           state.params)
        return state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=new_opt), {"loss": loss, **moe_metrics}

    def compile_step(self):
        if self._step is None:
            assert self._state_shardings is not None, "call init_state first"
            n_streams = 3 if self.masked else 2
            self._step = jax.jit(
                self._step_fn,
                in_shardings=(self._state_shardings,)
                + (self.batch_sharding,) * n_streams,
                out_shardings=(self._state_shardings, self.replicated),
                donate_argnums=(0,),
            )
        return self._step

    def train_step(self, state, tokens, targets, mask=None):
        """tokens/targets (+ float mask when masked): [M, microbatch, S]."""
        if self.masked:
            if mask is None:
                raise ValueError("masked_lm train_step needs the mask "
                                 "stream")
            return self.compile_step()(state, tokens, targets, mask)
        return self.compile_step()(state, tokens, targets)

    def microbatch(self, tokens, targets, mask=None):
        """Reshape a flat [B, S] batch into the [M, B/M, S] stream. For
        host arrays (synthetic streams) the jitted step's in_shardings do
        the placement. Device-committed flat batches should NOT come
        through here — no flat PartitionSpec matches the [M, mb] split's
        two-level element distribution, so re-placement would be a real
        per-step all-to-all; real-data streams instead yield the 3-D
        stream pre-placed (benchmark() accepts it directly)."""
        M = self.num_microbatches
        B, S = tokens.shape
        out = (tokens.reshape(M, B // M, S),
               targets.reshape(M, B // M, S))
        if mask is not None:
            out = out + (mask.reshape(M, B // M, S),)
        return out

    # -- evaluation ---------------------------------------------------------

    def compile_eval_step(self):
        """Loss-only pipeline pass (no grads, no optimizer, state NOT
        donated) — the pp analogue of LMTrainer.eval_step."""
        if self._eval_step is None:
            assert self._state_shardings is not None, "call init_state first"

            def eval_fn(params, tokens, targets, mask=None):
                # moe_aux_weight=0: the load-balance aux shapes gradients
                # only — folding it into val_loss would inflate reported
                # perplexity (same stance as LMTrainer._eval_fn).
                # 1F1B×interleave stores blocks in the device-major chunk
                # layout; the GPipe eval pass needs canonical layer order
                # or stages apply layers out of sequence.
                if self.schedule == "1f1b" and self.interleave > 1:
                    from ..parallel.pipeline_1f1b import deinterleave_blocks
                    params = dict(params)
                    params["blocks"] = deinterleave_blocks(
                        params["blocks"], self.pp, self.interleave)
                if self.masked:
                    return pipeline_mlm_loss(
                        self.cfg, params, tokens, targets, mask,
                        self.mesh, self.num_microbatches,
                        moe_aux_weight=0.0)
                return pipeline_lm_loss(
                    self.cfg, params, tokens, targets, self.mesh,
                    self.num_microbatches, moe_aux_weight=0.0,
                    fused_xent=self.fused_xent)

            n_streams = 3 if self.masked else 2
            # params only (LMTrainer.compile_eval symmetry): the loss
            # never reads the optimizer state, so don't plumb it through
            self._eval_step = jax.jit(
                eval_fn,
                in_shardings=(self._state_shardings.params,)
                + (self.batch_sharding,) * n_streams,
                out_shardings=self.replicated,
            )
        return self._eval_step

    def evaluate(self, state, dataset, num_batches: int = 10
                 ) -> Dict[str, float]:
        """Mean held-out loss + perplexity over `num_batches` batches —
        same contract as LMTrainer.evaluate, same stream shapes as the
        training loop (flat [B, S] pairs are microbatched here)."""
        import math

        step = self.compile_eval_step()
        total = 0.0
        it = iter(dataset)
        for _ in range(num_batches):
            batch = next(it)
            if batch[0].ndim == 2:
                batch = self.microbatch(*batch)
            total += float(step(state.params, *batch))
        mean = total / max(1, num_batches)
        return {"val_loss": mean, "perplexity": math.exp(min(mean, 30.0))}

    # -- benchmark loop -----------------------------------------------------

    def benchmark(self, state, dataset, num_steps: int = 50,
                  warmup_steps: int = 5, log: Callable[[str], None] = print,
                  step_hook: Optional[Callable] = None,
                  resilience=None, telemetry: Optional[TrainTelemetry] = None,
                  ) -> Tuple[PPTrainState, Dict[str, float]]:
        """The stream may yield flat [B, S] pairs (microbatched and placed
        here) or pre-placed [M, mb, S] streams (real-data pipelines).
        step_hook(state, step) fires after every timed step (periodic
        async checkpointing, train/checkpoint.periodic_saver).

        resilience: preemption stop-bit + a COARSE divergence backstop.
        The emergency checkpoint is written in CANONICAL layer order
        (canonical_state, same as every pp checkpoint) so the restarted
        gang may pick a different schedule/interleave. The in-step
        divergence guard is a flat-trainer feature (1F1B computes grads
        in-schedule; there is no single post-step select point) — here
        the loss is instead read back on the host every divergence_k
        steps, so a non-finite loss runs at most divergence_k steps
        before routing into the SAME rollback path (restore the newest
        intact checkpoint, bounded by max_rollbacks, DivergenceError
        when the budget is spent). One host read per window keeps the
        schedule device-bound between checks.

        telemetry: a telemetry.TrainTelemetry to feed. The pp loop is a
        single timed block (no window fetches), so the whole run folds in
        as num_steps observations of the average step time."""
        cfg = self.config
        tel = telemetry if telemetry is not None else TrainTelemetry()

        def prepare(batch):
            if batch[0].ndim == 2:
                return self.microbatch(*batch)
            return batch

        it = iter(dataset)
        step = self.compile_step()
        for _ in range(max(1, warmup_steps)):
            state, metrics = step(state, *prepare(next(it)))
        float(metrics["loss"])
        base_step = int(state.step)      # one host read, OUTSIDE the loop
        tokens_per_step = cfg.global_batch_size * cfg.seq_len
        # divergence backstop cadence: the same k that bounds the flat
        # trainers' on-device streak bounds how many pp steps a
        # non-finite loss can run unnoticed (0 = no resilience, no check)
        loss_check_every = (resilience.config.divergence_k
                            if resilience is not None else 0)
        t0 = time.perf_counter()
        for i in range(1, num_steps + 1):
            with span("train.pp_step"):
                state, metrics = step(state, *prepare(next(it)))
            if step_hook is not None:
                step_hook(state, base_step + i)
            if loss_check_every and i % loss_check_every == 0 \
                    and not math.isfinite(float(metrics["loss"])):
                log(f"non-finite loss at step {base_step + i}: "
                    f"rolling back")
                state = resilience.rollback(state)
            if resilience is not None \
                    and resilience.on_step(base_step + i):
                from .resilience import Preempted
                log(f"preemption drain: stopping the gang at step "
                    f"{base_step + i}")
                resilience.emergency_save(self.canonical_state(state))
                raise Preempted(base_step + i)
        g0 = time.perf_counter()
        final_loss = float(metrics["loss"])         # host read barrier
        dt = time.perf_counter() - t0
        tel.host_gap_seconds.observe(time.perf_counter() - g0)
        tps = tokens_per_step * num_steps / dt
        n = self.mesh.size
        num_params = flops.param_count(state.params)
        per_token = flops.transformer_train_flops_per_token(
            num_params, self.cfg.num_layers, self.cfg.embed_dim,
            cfg.seq_len, causal=self.cfg.causal)
        stats = flops.throughput_stats(
            per_token * tokens_per_step, tps / tokens_per_step, n)
        tel.observe_steps(dt / num_steps, num_steps)
        tel.update_window(tokens_per_sec=tps, mfu=stats["mfu"],
                          step=base_step + num_steps)
        p50_ms, p99_ms = tel.step_percentiles_ms()
        gap50_ms, gap99_ms = tel.host_gap_percentiles_ms()
        log(f"pp={self.pp} M={self.num_microbatches} "
            f"schedule={self.schedule}"
            + (f"×{self.interleave}" if self.interleave > 1 else "")
            + f" bubble={self.bubble:.1%}: {tps:.0f} tokens/sec")
        extra = {}
        if "moe_drop_rate" in metrics:
            # observable router imbalance in the pp path (pipeline_lm_loss
            # threads it out of the schedule; parallel/moe.py sows it)
            extra["moe_drop_rate"] = float(metrics["moe_drop_rate"])
        return state, {"tokens_per_sec": tps,
                       "tokens_per_sec_per_device": tps / n,
                       "final_loss": final_loss,
                       "bubble_fraction": self.bubble,
                       "step_time_p50_ms": p50_ms,
                       "step_time_p99_ms": p99_ms,
                       "host_gap_p50_ms": gap50_ms,
                       "host_gap_p99_ms": gap99_ms,
                       **stats, **extra}


__all__ = ["PipelineLMTrainer", "PPTrainState"]
