"""Preemption-tolerant training runtime.

The control plane already speaks the reference operator's failure
language: exit codes in the 128-255 band are retryable and trigger a gang
restart (controller.py `_should_restart`, ExitCode policy, ref
common_types.go:150-155; bootstrap.LAUNCHER_LOST_EXIT rides the same
band). This module gives the DATA plane something worth restarting:

  * PreemptionListener — SIGTERM/SIGUSR1 set a local flag (TPU
    preemptions deliver SIGTERM with ~30s notice; SIGUSR1 is the manual
    drain channel). The flag is only a local fact.
  * gang_should_stop — folds the local flags into one replicated stop
    bit via an all-gather, so every rank exits at the SAME step boundary
    and the final checkpoint is a clean collective instead of a torn
    race between ranks that saw the signal and ranks that didn't.
  * guard_nonfinite_update — in-step divergence defense: a step whose
    loss or global grad-norm is non-finite contributes NO update
    (params/opt state/BN stats revert to their pre-step values) and an
    on-device skip streak increments; K consecutive skips escalate to a
    host-side rollback-from-last-checkpoint (ResilienceContext.rollback)
    instead of silently training on NaNs.
  * Watchdog — a per-step deadline thread: a hung ICI collective dumps
    every thread's stack and aborts with WATCHDOG_STALL_EXIT instead of
    idling until activeDeadlineSeconds kills the job with no diagnosis.
  * FaultInjector — TPU_FAULT_INJECT=... test knobs (die-at-step,
    sigterm-at-step, corrupt-latest-checkpoint, delay-coordinator) so
    tests/test_resilience.py can prove the kill→restart→resume story on
    a CPU mesh without real preemptions.

ResilienceContext bundles all of it behind the single `on_step` call the
benchmark loops make per step.
"""
from __future__ import annotations

import faulthandler
import os
import re
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

#: env var holding the fault-injection spec (see FaultInjector)
ENV_FAULT_INJECT = "TPU_FAULT_INJECT"
#: env var default for ResilienceConfig.step_deadline (seconds)
ENV_STEP_DEADLINE = "TPU_STEP_DEADLINE"
#: stop-bit cadence: an integer, or "auto" to derive it from the last
#: run's measured drain latency in <train-dir>/events.jsonl
ENV_STOP_CHECK_EVERY = "TPU_STOP_CHECK_EVERY"
#: drain-latency budget (seconds) the auto cadence targets
ENV_DRAIN_TARGET = "TPU_DRAIN_TARGET_SECONDS"
#: default drain budget: well inside the ~30s TPU preemption notice,
#: leaving the emergency checkpoint write the rest of the grace window
DRAIN_TARGET_SECONDS = 5.0

# Exit codes in the reference's 128-255 "retryable" band (ref
# common_types.go:150-155) — the controller's ExitCode restart policy
# (controller._should_restart) relaunches the gang on any of these.
# bootstrap.LAUNCHER_LOST_EXIT (213) is the neighbor.
PREEMPTED_EXIT = 215        # gang drained after SIGTERM/SIGUSR1
WATCHDOG_STALL_EXIT = 216   # a step blew its deadline (hung collective)
FAULT_DIE_EXIT = 217        # injected hard death (die-at-step:N)


def is_retryable_exit(code: Optional[int]) -> bool:
    """The controller's ExitCode-policy predicate, importable by tools:
    None (signal-killed pod) and 128-255 retry; 1-127 is a workload bug."""
    return code is None or code >= 128


class Preempted(RuntimeError):
    """The gang agreed to stop; the emergency checkpoint is written.
    Entrypoints catch this and exit with `exit_code` (retryable band)."""

    def __init__(self, step: int, exit_code: int = PREEMPTED_EXIT):
        super().__init__(f"preempted at step {step}")
        self.step = step
        self.exit_code = exit_code


class DivergenceError(RuntimeError):
    """K consecutive non-finite steps and no checkpoint to roll back to
    (or the rollback budget is spent) — a workload failure, NOT retryable:
    restarting would replay the same divergence."""


# ---------------------------------------------------------------------------
# Preemption listener + the gang stop bit
# ---------------------------------------------------------------------------

class PreemptionListener:
    """Installs SIGTERM/SIGUSR1 handlers that set a flag; `requested`
    reads it. Previous handlers are chained (called after ours) and
    restored on uninstall, so harnesses with their own SIGTERM
    bookkeeping (bench.py's summary flush) keep working. Signal handlers
    only install from the main thread — construct this there."""

    SIGNALS = (signal.SIGTERM, signal.SIGUSR1)

    def __init__(self, log: Callable[[str], None] = print):
        self._requested = False
        self._log = log
        self._prev: dict = {}

    @property
    def requested(self) -> bool:
        return self._requested

    def _handler(self, signum, frame):
        if not self._requested:
            self._log(f"preemption notice ({signal.Signals(signum).name}): "
                      f"draining at the next step boundary")
        self._requested = True
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self) -> "PreemptionListener":
        for sig in self.SIGNALS:
            self._prev[sig] = signal.getsignal(sig)
            signal.signal(sig, self._handler)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / weird prev
                pass
        self._prev.clear()


def suggest_stop_check_every(drain_seconds: float, cadence: int,
                             target: Optional[float] = None,
                             lo: int = 1, hi: int = 256) -> Optional[int]:
    """The cadence that would have landed a measured drain inside the
    target budget, assuming drain latency scales roughly linearly with
    the cadence (the drain waits for the next stop-check boundary, so
    expected latency ~ cadence/2 steps + checkpoint write). Returns None
    when the inputs can't support a suggestion."""
    if target is None:
        raw = os.environ.get(ENV_DRAIN_TARGET, "")
        try:
            target = float(raw) if raw else DRAIN_TARGET_SECONDS
        except ValueError:
            target = DRAIN_TARGET_SECONDS
    if drain_seconds <= 0 or cadence <= 0 or target <= 0:
        return None
    return max(lo, min(hi, int(round(cadence * target / drain_seconds))
                       or lo))


def drain_latency_from_events(events_path: str
                              ) -> Tuple[Optional[float], Optional[int]]:
    """(worst drain latency, its recorded cadence) from an events.jsonl:
    each preemption_drain pairs with the next emergency_checkpoint, and
    the drain record carries the stop_check_every it ran under (emitted
    by emergency_save). (None, None) when no complete drain exists."""
    from ..telemetry import events as ev

    worst: Optional[float] = None
    cadence: Optional[int] = None
    open_ts: Optional[float] = None
    open_cadence: Optional[int] = None
    try:
        records = ev.read_events(events_path)
    except OSError:
        return None, None
    for rec in records:
        kind = rec.get("event")
        if kind == ev.PREEMPTION_DRAIN:
            open_ts = rec.get("ts")
            open_cadence = rec.get("stop_check_every")
        elif kind == ev.EMERGENCY_CHECKPOINT and open_ts is not None:
            latency = float(rec.get("ts", open_ts)) - float(open_ts)
            if worst is None or latency > worst:
                worst, cadence = latency, open_cadence
            open_ts = None
    return worst, (int(cadence) if cadence else None)


def auto_stop_check_every(train_dir: Optional[str],
                          default: int = 8,
                          log: Callable[[str], None] = print) -> int:
    """TPU_STOP_CHECK_EVERY=auto: derive the cadence from the LAST run's
    drain latency in <train_dir>/events.jsonl (the file the next
    incarnation of a preempted/resized gang inherits on the shared
    train_dir). Falls back to `default` when no drain has been measured
    yet — the first run of a fresh job has nothing to learn from."""
    if not train_dir:
        return default
    path = os.path.join(os.path.abspath(train_dir), "events.jsonl")
    if not os.path.exists(path):
        return default
    worst, cadence = drain_latency_from_events(path)
    if worst is None:
        return default
    suggested = suggest_stop_check_every(worst, cadence or default)
    if suggested is None:
        return default
    log(f"stop-check cadence auto-tuned to {suggested} (last drain "
        f"{worst:.2f}s at cadence {cadence or default})")
    return suggested


def gang_should_stop(local: bool) -> bool:
    """Replicated stop decision: True iff ANY rank requested a stop.

    Multi-process this is a collective (every rank MUST call it at the
    same step — ResilienceContext.on_step guarantees that by checking on
    a fixed step cadence regardless of the local flag). Single-process
    runs short-circuit to the local flag: no device work on the hot path.
    """
    if jax.process_count() == 1:
        return bool(local)
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        jnp.asarray([1 if local else 0], jnp.int32))
    return bool(int(jnp.max(flags)))


# ---------------------------------------------------------------------------
# Divergence guard (jitted-step side)
# ---------------------------------------------------------------------------

def guard_nonfinite_update(old_state, new_state, loss, grads):
    """Select old vs new state inside the jitted step: when `loss` or the
    global grad-norm is non-finite, every pytree leaf reverts to its
    pre-update value (params, optimizer moments, BN stats) and the
    on-device `nonfinite_streak` increments; a finite step resets it.
    The step counter always advances so checkpoint naming, LR schedules
    keyed on opt-state counts notwithstanding, stays monotonic — a
    skipped step is a no-op update, not a rewind."""
    import optax

    ok = jnp.isfinite(loss) & jnp.isfinite(optax.global_norm(grads))
    # select leaf-wise against new_state's treedef, not tree.map over both
    # trees: the two states can disagree on EMPTY container types (a
    # BN-free model carries batch_stats=FrozenDict({}) on one side and a
    # rebuilt plain {} on the other) and strict two-tree matching rejects
    # that even though there is no leaf underneath
    new_leaves, treedef = jax.tree.flatten(new_state)
    old_leaves = jax.tree.leaves(old_state)
    guarded = treedef.unflatten(
        [jnp.where(ok, n, o) for n, o in zip(new_leaves, old_leaves)])
    streak = jnp.where(
        ok, 0, jnp.asarray(old_state.nonfinite_streak, jnp.int32) + 1)
    return guarded.replace(step=new_state.step,
                           nonfinite_streak=streak.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Per-step deadline: `pet()` after every step; a daemon thread that
    sees `deadline` seconds without a pet dumps EVERY thread's stack
    (faulthandler — C-safe, works mid-collective) and aborts the process
    with WATCHDOG_STALL_EXIT. The point is turning "the job hung until
    activeDeadlineSeconds" into "rank N stalled in <this collective>,
    restart me" — the abort code sits in the retryable band so the
    controller relaunches the gang. `abort` is injectable for tests."""

    def __init__(self, deadline: float,
                 exit_code: int = WATCHDOG_STALL_EXIT,
                 log: Callable[[str], None] = print,
                 abort: Optional[Callable[[int], None]] = None,
                 poll: Optional[float] = None):
        self.deadline = float(deadline)
        self.exit_code = exit_code
        self._log = log
        self._abort = abort if abort is not None else self._default_abort
        self._poll = poll if poll is not None else min(
            max(self.deadline / 4.0, 0.05), 5.0)
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_abort(code: int) -> None:
        # os._exit, not sys.exit: the main thread is stuck in a
        # collective and will never run exception handlers
        os._exit(code)

    def pet(self) -> None:
        self._last = time.monotonic()

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._last = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name="tpu-step-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            stalled = time.monotonic() - self._last
            if stalled > self.deadline:
                self._log(f"watchdog: step exceeded {self.deadline:.1f}s "
                          f"deadline ({stalled:.1f}s since last step); "
                          f"dumping stacks, aborting with exit code "
                          f"{self.exit_code}")
                try:
                    faulthandler.dump_traceback(file=sys.stderr,
                                                all_threads=True)
                except Exception:  # noqa: BLE001 — diagnosis best-effort
                    pass
                self._abort(self.exit_code)
                return


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

def corrupt_latest_checkpoint(directory: str) -> Optional[str]:
    """Scribble garbage over every file of the NEWEST committed step_N —
    the directory still looks committed (the commit-marker check passes)
    but restore raises, exercising the read-side fallback to the previous
    step. Returns the corrupted path, or None when nothing to corrupt."""
    from .checkpoint import wait_for_checkpoints

    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = [int(n[5:]) for n in os.listdir(directory)
             if n.startswith("step_") and n[5:].isdigit()]
    if not steps:
        return None
    path = os.path.join(directory, f"step_{max(steps)}")
    for root, _dirs, files in os.walk(path):
        for name in files:
            with open(os.path.join(root, name), "wb") as fh:
                fh.write(b"\x00corrupted-by-fault-injection\x00")
    return path


class FaultInjector:
    """Parsed TPU_FAULT_INJECT spec — ';'/',' separated directives:

      die-at-step:N             os._exit(FAULT_DIE_EXIT) after step N
                                (hard death: no emergency checkpoint)
      sigterm-at-step:N         SIGTERM to self after step N (the
                                graceful preemption drill)
      corrupt-latest-checkpoint scribble the newest step_N before resume
      delay-coordinator:K       first K jax.distributed.initialize
                                attempts fail (exercises init retry)
      nan-replica:K@N           poison fused-trainer replica K's params
                                with NaN at step N (HFTA divergence-
                                isolation drill; '@' because ':' starts
                                the arg and ';'/',' separate directives)

    Unknown directives raise at parse time — a typo'd fault spec that
    silently injects nothing would green a test that proved nothing."""

    def __init__(self, spec: str = ""):
        #: telemetry.EventLog — when set (ResilienceContext wires its
        #: own), step faults leave a durable `fault_injected` record
        #: BEFORE the kill. A hard death writes no emergency checkpoint,
        #: so this record is the only evidence of how far the run got —
        #: the controller's goodput ledger charges restart-lost steps
        #: against exactly this frontier.
        self.events = None
        self.die_at_step: Optional[int] = None
        self.sigterm_at_step: Optional[int] = None
        self.corrupt_latest = False
        self.delay_coordinator = 0
        self.nan_replica: Optional[int] = None
        self.nan_replica_step: Optional[int] = None
        self._injected_init_failures = 0
        for raw in re.split(r"[;,]", spec or ""):
            part = raw.strip()
            if not part:
                continue
            name, _, arg = part.partition(":")
            if name == "die-at-step":
                self.die_at_step = int(arg)
            elif name == "sigterm-at-step":
                self.sigterm_at_step = int(arg)
            elif name == "corrupt-latest-checkpoint":
                self.corrupt_latest = True
            elif name == "delay-coordinator":
                self.delay_coordinator = int(arg)
            elif name == "nan-replica":
                replica, _, at = arg.partition("@")
                self.nan_replica = int(replica)
                self.nan_replica_step = int(at)
            else:
                raise ValueError(
                    f"unknown {ENV_FAULT_INJECT} directive {part!r}; known: "
                    f"die-at-step:N, sigterm-at-step:N, "
                    f"corrupt-latest-checkpoint, delay-coordinator:K, "
                    f"nan-replica:K@N")

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultInjector"]:
        env = os.environ if env is None else env
        spec = env.get(ENV_FAULT_INJECT, "")
        return cls(spec) if spec else None

    def check_step(self, step: int) -> bool:
        """Fire any step-indexed fault; returns True when a graceful stop
        was injected THIS call (the caller treats it like a delivered
        preemption signal — the return value makes the drill
        deterministic instead of racing CPython's signal delivery)."""
        if self.die_at_step is not None and step >= self.die_at_step:
            self._emit_fault("die", step)
            os._exit(FAULT_DIE_EXIT)
        if self.sigterm_at_step is not None and step >= self.sigterm_at_step:
            self.sigterm_at_step = None        # one shot
            self._emit_fault("sigterm", step)
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        return False

    def _emit_fault(self, fault: str, step: int) -> None:
        """The drill leaves evidence: one fsync'd record before the kill."""
        if self.events is not None:
            from ..telemetry import events as ev
            self.events.emit(ev.FAULT_INJECTED, fault=fault, step=int(step))

    def check_nan_replica(self, step: int) -> Optional[int]:
        """One-shot nan-replica:K@N probe — returns the replica index to
        poison when `step` has reached the trigger, else None. The HFTA
        benchmark loop consults this before dispatching each step."""
        if (self.nan_replica_step is not None
                and step >= self.nan_replica_step):
            self.nan_replica_step = None       # one shot
            return self.nan_replica
        return None

    def maybe_corrupt_checkpoint(self, train_dir: Optional[str],
                                 log: Callable[[str], None] = print
                                 ) -> Optional[str]:
        if not (self.corrupt_latest and train_dir):
            return None
        self.corrupt_latest = False            # one shot
        path = corrupt_latest_checkpoint(train_dir)
        if path:
            log(f"fault-inject: corrupted {path}")
        return path

    def fail_init_attempt(self) -> bool:
        """delay-coordinator budget: consume and report one injected
        distributed-init failure (bootstrap's retry loop consults this
        before every real attempt)."""
        if self._injected_init_failures < self.delay_coordinator:
            self._injected_init_failures += 1
            return True
        return False


# ---------------------------------------------------------------------------
# The per-loop bundle
# ---------------------------------------------------------------------------

@dataclass
class ResilienceConfig:
    train_dir: Optional[str] = None
    #: consecutive non-finite steps before rollback-from-checkpoint
    divergence_k: int = 3
    #: rollbacks allowed before giving up as a genuine divergence
    max_rollbacks: int = 2
    #: seconds a single step may take; 0 disables the watchdog
    step_deadline: float = 0.0
    #: gang stop-bit cadence (multi-process allgather every N steps;
    #: single-process checks the local flag every step regardless).
    #: Default 8: a preemption drain can afford up to 8 steps of latency
    #: (the grace window is tens of seconds), while an every-step
    #: allgather serializes a host round-trip into each step — measured
    #: pure overhead at steady state.
    stop_check_every: int = 8

    @classmethod
    def from_env(cls, env=None, **overrides) -> "ResilienceConfig":
        env = os.environ if env is None else env
        # a None override means "caller didn't specify" (optional CLI
        # flags pass straight through): drop it so env/default applies
        overrides = {k: v for k, v in overrides.items() if v is not None}
        if "step_deadline" not in overrides and env.get(ENV_STEP_DEADLINE):
            overrides["step_deadline"] = float(env[ENV_STEP_DEADLINE])
        if ("stop_check_every" not in overrides
                and env.get(ENV_STOP_CHECK_EVERY)):
            raw = str(env[ENV_STOP_CHECK_EVERY]).strip()
            if raw.lower() == "auto":
                overrides["stop_check_every"] = auto_stop_check_every(
                    overrides.get("train_dir"))
            else:
                overrides["stop_check_every"] = int(raw)
        return cls(**overrides)


class ResilienceContext:
    """One per training run; use as a context manager around the loop.

    Per step the loop calls `on_step(step)` — fault hooks fire, the
    watchdog is petted, and the gang stop bit is evaluated; True means
    "drain now": the loop writes the emergency checkpoint
    (`emergency_save`) and raises Preempted. At window boundaries the
    loop reads the on-device skip streak from metrics and calls
    `rollback` when it reaches divergence_k.
    """

    def __init__(self, config: Optional[ResilienceConfig] = None,
                 log: Callable[[str], None] = print,
                 listener: Optional[PreemptionListener] = None,
                 faults: Optional[FaultInjector] = None,
                 watchdog: Optional[Watchdog] = None,
                 events=None, telemetry=None):
        self.config = config or ResilienceConfig()
        self.log = log
        self.listener = (listener if listener is not None
                         else PreemptionListener(log))
        self.faults = faults if faults is not None else FaultInjector.from_env()
        if watchdog is None and self.config.step_deadline > 0:
            watchdog = Watchdog(self.config.step_deadline, log=log)
        self.watchdog = watchdog
        #: telemetry.EventLog — resilience transitions become durable JSONL
        #: records; every emit is fsync'd before it returns, which is what
        #: lets emergency_save promise the drain is on disk before exit(215)
        self.events = events
        #: telemetry.TrainTelemetry — rollback accounting feeds goodput
        self.telemetry = telemetry
        if self.faults is not None and self.faults.events is None:
            self.faults.events = events
        self._pending_stop = False
        self._rollbacks = 0
        # resume-phase bookkeeping: record_restore arms these, the next
        # on_step emits FIRST_RESUME_STEP (restore-done -> first step,
        # compile included — the recompile phase of a gang resize)
        self._resume_ts: Optional[float] = None
        self._resume_step = 0

    def __enter__(self) -> "ResilienceContext":
        self.listener.install()
        # the watchdog arms on the FIRST on_step call, not here: the step
        # deadline budgets a steady-state step, and compilation (minutes,
        # before any on_step) must not trip it
        if self.faults is not None:
            self.faults.maybe_corrupt_checkpoint(self.config.train_dir,
                                                 self.log)
        return self

    def __exit__(self, *exc) -> None:
        # flush the event log BEFORE any teardown that could hang or kill
        # the process: when __exit__ runs on the Preempted unwind path the
        # very next thing the entrypoint does is exit(215), and the
        # preemption record must already be durable by then
        if self.events is not None:
            self.events.flush()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.listener.uninstall()

    # -- the hot-path call ---------------------------------------------------

    def on_step(self, step: int) -> bool:
        if self._resume_ts is not None and step > self._resume_step:
            # first completed step of this incarnation: the dispatch of
            # the step above blocked on its compile, so wall time since
            # the restore IS the recompile phase
            seconds = round(time.time() - self._resume_ts, 3)
            self._resume_ts = None
            if self.events is not None:
                from ..telemetry import events as ev
                self.events.emit(ev.FIRST_RESUME_STEP, step=int(step),
                                 seconds=seconds)
            if self.telemetry is not None \
                    and hasattr(self.telemetry, "resume_step_seconds"):
                self.telemetry.resume_step_seconds.set(seconds)
        local = False
        if self.faults is not None:
            local = self.faults.check_step(step)
        if self.watchdog is not None:
            self.watchdog.start()       # idempotent; arms on first step
            self.watchdog.pet()
        local = local or self.listener.requested
        if jax.process_count() == 1:
            return local
        # multi-process: the allgather is a collective, so it must run at
        # the SAME steps on every rank — fixed cadence, local flag carried
        # to the next boundary
        self._pending_stop = self._pending_stop or local
        if step % max(1, self.config.stop_check_every) != 0:
            return False
        stop = gang_should_stop(self._pending_stop)
        self._pending_stop = False
        return stop

    # -- drain / rollback ----------------------------------------------------

    def emergency_save(self, state) -> None:
        """The final SYNCHRONOUS checkpoint before a preemption exit —
        blocks until committed (an async write racing SIGKILL is how you
        lose the run). Collective: every rank calls it at the same step
        (on_step's replicated stop bit guarantees that).

        Event ordering is deliberate: `preemption_drain` is fsync'd to the
        event log BEFORE the save starts, so a checkpoint write that dies
        mid-flight still leaves durable evidence of WHY the process
        exited; `emergency_checkpoint` lands after the commit."""
        from .checkpoint import maybe_save

        step = int(state.step)
        if self.events is not None:
            from ..telemetry import events as ev
            # the cadence rides the drain record so the NEXT incarnation
            # (TPU_STOP_CHECK_EVERY=auto) and the postmortem can relate
            # the measured latency to the setting that produced it
            self.events.emit(ev.PREEMPTION_DRAIN, step=step,
                             stop_check_every=self.config.stop_check_every)
        maybe_save(self.config.train_dir, state, self.log)
        if self.events is not None:
            self.events.emit(ev.EMERGENCY_CHECKPOINT, step=step,
                             train_dir=self.config.train_dir)
        if self.telemetry is not None:
            self.telemetry.last_checkpoint_step.set(step)
            self.telemetry.step.set(step)

    # -- restart-aware goodput bookkeeping -----------------------------------

    def record_restore(self, step: int, path: Optional[str] = None,
                       seconds: Optional[float] = None,
                       leaves: Optional[int] = None,
                       resharded: Optional[bool] = None) -> None:
        """Report the step this incarnation restored from. The controller
        charges (last observed step − restore step) to the lost column of
        the job goodput ledger, so the restore step MUST be durable in the
        event log and visible on /metrics — call this right after
        maybe_resume, with step 0 meaning a fresh start (no event).
        `seconds`/`leaves`/`resharded` (checkpoint.last_restore_info)
        describe the restore itself — the restore phase of the
        resize_seconds split."""
        step = int(step)
        if step > 0 and self.events is not None:
            from ..telemetry import events as ev
            fields = {"step": step}
            if path:
                fields["path"] = path
            if seconds is not None:
                fields["seconds"] = round(float(seconds), 3)
            if leaves is not None:
                fields["leaves"] = int(leaves)
            if resharded is not None:
                fields["resharded"] = bool(resharded)
            self.events.emit(ev.CHECKPOINT_RESTORE, **fields)
        if step > 0:
            # arm the recompile-phase probe: the next completed step
            # closes the restore -> first-step window (on_step)
            self._resume_ts = time.time()
            self._resume_step = step
        if self.telemetry is not None:
            self.telemetry.restore_step.set(step)
            if seconds is not None \
                    and hasattr(self.telemetry, "restore_seconds"):
                self.telemetry.restore_seconds.set(round(float(seconds), 3))
            if step > 0:
                self.telemetry.last_checkpoint_step.set(step)
                self.telemetry.step.set(step)

    def record_checkpoint(self, step: int) -> None:
        """Report a durable periodic checkpoint (periodic_saver hook)."""
        step = int(step)
        if self.events is not None:
            from ..telemetry import events as ev
            self.events.emit(ev.CHECKPOINT_SAVED, step=step,
                             train_dir=self.config.train_dir)
        if self.telemetry is not None:
            self.telemetry.last_checkpoint_step.set(step)

    def rollback(self, state):
        """Restore the newest intact checkpoint after divergence_k
        consecutive non-finite steps; resets the on-device streak. Raises
        DivergenceError when nothing restorable remains or the rollback
        budget is spent — that's a workload bug (exit code 1, NOT
        retryable: a restart would replay the same divergence)."""
        from .checkpoint import restore_with_fallback

        self._rollbacks += 1
        if self._rollbacks > self.config.max_rollbacks:
            raise DivergenceError(
                f"diverged again after {self.config.max_rollbacks} "
                f"rollback(s) — giving up (lower the LR or inspect the "
                f"data around step {int(state.step)})")
        if not self.config.train_dir:
            raise DivergenceError(
                f"{self.config.divergence_k} consecutive non-finite steps "
                f"and no --train-dir to roll back from")
        restored, path = restore_with_fallback(self.config.train_dir, state,
                                               self.log)
        if path is None:
            raise DivergenceError(
                f"{self.config.divergence_k} consecutive non-finite steps "
                f"and no restorable checkpoint under "
                f"{self.config.train_dir!r}")
        self.log(f"divergence rollback #{self._rollbacks}: restored {path} "
                 f"(step {int(restored.step)})")
        from_step, to_step = int(state.step), int(restored.step)
        if self.events is not None:
            from ..telemetry import events as ev
            self.events.emit(ev.DIVERGENCE_ROLLBACK, from_step=from_step,
                             to_step=to_step, rollback=self._rollbacks,
                             path=path)
        if self.telemetry is not None:
            self.telemetry.record_rollback(max(0, from_step - to_step))
        if hasattr(restored, "nonfinite_streak"):
            # flat trainers carry the divergence streak on device and
            # need it rezeroed; the pp trainer's host-side loss backstop
            # has no such field — its streak IS the host reading
            restored = restored.replace(
                nonfinite_streak=jnp.zeros_like(jnp.asarray(restored.step)))
        return restored


__all__ = [
    "PREEMPTED_EXIT", "WATCHDOG_STALL_EXIT", "FAULT_DIE_EXIT",
    "ENV_FAULT_INJECT", "ENV_STEP_DEADLINE", "ENV_STOP_CHECK_EVERY",
    "ENV_DRAIN_TARGET", "DRAIN_TARGET_SECONDS",
    "is_retryable_exit", "suggest_stop_check_every",
    "drain_latency_from_events", "auto_stop_check_every",
    "Preempted", "DivergenceError", "PreemptionListener", "gang_should_stop",
    "guard_nonfinite_update", "Watchdog", "FaultInjector",
    "corrupt_latest_checkpoint", "ResilienceConfig", "ResilienceContext",
]
