"""Distributed trainer — the data-plane training loop.

The reference's training loop lives entirely outside its repo (TensorFlow
tf_cnn_benchmarks + Horovod DistributedOptimizer inside the example image,
reference examples/tensorflow-benchmarks/Dockerfile:12-16). This module is
the TPU-native equivalent: a single jitted train step over a
`jax.sharding.Mesh` where the batch is sharded over the data axes and
parameters are replicated (or fsdp-sharded) — XLA inserts the gradient
AllReduce over ICI exactly where Horovod's ring allreduce sat (SURVEY §7).

Throughput is logged in the reference's observable format
(`total images/sec: ...`, reference README.md:113-131) so launcher-pod logs
stay comparable.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import batch_spec
from ..telemetry import TrainTelemetry, span
from ..utils import flops
from ..utils.profiling import WindowProfiler


class TrainState(struct.PyTreeNode):
    """Carries params + mutable BN stats + optimizer state."""
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Callable = struct.field(pytree_node=False)
    # consecutive non-finite (skipped) steps, maintained ON DEVICE by the
    # divergence guard (resilience.guard_nonfinite_update) so reading it
    # costs nothing until a log-window fetch; not persisted in
    # checkpoints (a restore starts a fresh streak)
    nonfinite_streak: Any = 0

    def apply_gradients(self, grads, batch_stats):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=batch_stats,
            opt_state=new_opt_state,
        )


def cross_entropy_loss(logits, labels, num_classes: int = 0):
    del num_classes  # derivable from logits; kept for call-site clarity
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_sgd(lr: float = 0.1, momentum: float = 0.9,
             nesterov: bool = False) -> optax.GradientTransformation:
    """tf_cnn_benchmarks' default optimizer (SGD + momentum)."""
    return optax.sgd(lr, momentum=momentum, nesterov=nesterov)


@dataclass
class TrainerConfig:
    global_batch_size: int = 128       # reference run: 128 global / 64 per dev
    image_size: int = 224
    num_classes: int = 1000
    learning_rate: float = 0.1
    momentum: float = 0.9
    log_every: int = 10
    # divergence guard: a step with non-finite loss/grad-norm applies NO
    # update (resilience.guard_nonfinite_update); the selects are
    # numerically a no-op on finite steps and fuse into the update
    guard_nonfinite: bool = True


class Trainer:
    """pjit-style trainer: params replicated, batch sharded over data axes.

    The collective story: `jax.grad` of the sharded-batch loss produces
    partial gradients per data shard; because params are replicated, XLA
    inserts an AllReduce over the data axes before the optimizer update —
    the same reduction Horovod performed in C++/NCCL, now compiled onto ICI.
    """

    def __init__(self, model, mesh: Mesh, config: Optional[TrainerConfig] = None,
                 tx: Optional[optax.GradientTransformation] = None):
        self.model = model
        self.mesh = mesh
        self.config = config or TrainerConfig()
        self.tx = tx or make_sgd(self.config.learning_rate, self.config.momentum)
        self.batch_sharding = NamedSharding(mesh, batch_spec())
        self.replicated = NamedSharding(mesh, P())
        self._train_step = None

    # -- initialization -----------------------------------------------------

    def init_state(self, rng: jax.Array) -> TrainState:
        from flax.core import meta

        dummy = jnp.zeros(
            (2, self.config.image_size, self.config.image_size, 3),
            jnp.float32,
        )

        def init_all(rng):
            variables = self.model.init(rng, dummy, train=False)
            # models annotated with logical partitioning (ViT) come back
            # boxed; unbox is a no-op for plain arrays (ResNet)
            variables = meta.unbox(variables)
            params = variables["params"]
            return (params, variables.get("batch_stats", FrozenDict()),
                    self.tx.init(params))

        # initialize DIRECTLY into the target (replicated) layout — params
        # AND optimizer state materialize once, laid out by XLA, with no
        # single-device staging copy (the same out_shardings discipline
        # LMTrainer's shard_init uses for ruled layouts)
        params, batch_stats, opt_state = jax.jit(
            init_all, out_shardings=self.replicated)(rng)
        return TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), self.replicated),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            tx=self.tx,
            apply_fn=self.model.apply,
            nonfinite_streak=jax.device_put(jnp.zeros((), jnp.int32),
                                            self.replicated),
        )

    # -- the jitted step ----------------------------------------------------

    def _step_fn(self, state: TrainState, images, labels):
        def loss_fn(params):
            logits, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(logits, labels, self.config.num_classes)
            # LayerNorm-only models (ViT) have no batch_stats collection
            return loss, (logits, mutated.get("batch_stats", state.batch_stats))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        # grads are partial sums per batch shard; with replicated params XLA
        # emits AllReduce(dp axes) here — the Horovod hook, compiler-inserted.
        new_state = state.apply_gradients(grads, new_stats)
        if self.config.guard_nonfinite:
            from .resilience import guard_nonfinite_update
            new_state = guard_nonfinite_update(state, new_state, loss, grads)
        state = new_state
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        return state, {"loss": loss, "accuracy": accuracy,
                       "nonfinite_streak": state.nonfinite_streak}

    def compile_step(self, state: TrainState):
        if self._train_step is None:
            self._train_step = jax.jit(
                self._step_fn,
                in_shardings=(self.replicated, self.batch_sharding,
                              self.batch_sharding),
                out_shardings=(self.replicated, self.replicated),
                donate_argnums=(0,),
            )
        return self._train_step

    def train_step(self, state, images, labels):
        return self.compile_step(state)(state, images, labels)

    # -- benchmark loop (the reference's observable, README.md:97-133) ------

    def benchmark(self, state: TrainState, dataset, num_steps: int = 100,
                  warmup_steps: int = 10,
                  log: Callable[[str], None] = print,
                  profile_dir: Optional[str] = None,
                  step_hook: Optional[Callable] = None,
                  resilience=None, telemetry: Optional[TrainTelemetry] = None,
                  ) -> Tuple[TrainState, Dict[str, float]]:
        """Windowed throughput measurement, tf_cnn_benchmarks-style.
        Returns (final_state, metrics) — the input state is DONATED by the
        jitted step, so callers must use the returned state afterwards.

        resilience: an entered train.resilience.ResilienceContext. Per
        step its on_step() folds signals/faults into the replicated stop
        bit — True writes the emergency checkpoint and raises Preempted
        (the gang drains at the same boundary). At window fetches the
        on-device non-finite streak escalates to rollback-from-checkpoint
        at divergence_k.

        Synchronization note: each window is closed by FETCHING the loss
        scalar to the host, not by `block_until_ready` — on remote-relay
        device transports (e.g. tunneled TPUs) only a real host read is a
        true barrier. The fetch itself happens OUTSIDE the timed window, so
        reported images/sec is pure step throughput. The headline number is
        the mean over steady-state windows (first window dropped — it
        absorbs pipeline fill), matching how tf_cnn_benchmarks averages
        per-step rates after warmup (ref README.md:113-131).

        telemetry: a telemetry.TrainTelemetry to feed (see
        LMTrainer.benchmark — same window-fetch-only discipline); a
        private recorder runs when None so step_time_p50/p99_ms and
        goodput always land in the returned metrics.
        """
        tel = telemetry if telemetry is not None else TrainTelemetry()
        if resilience is not None and resilience.telemetry is None:
            resilience.telemetry = tel    # rollback accounting → goodput
        step_fn = self.compile_step(state)
        it = iter(dataset)
        log_every = max(1, min(self.config.log_every, num_steps))
        # XLA's cost model for the exact executable (hits the compile
        # cache — same shapes as the benchmark steps), for MFU reporting.
        # The analysis sees the post-SPMD-partition module, so the count
        # is per device; scale to a global figure.
        probe = next(it)
        flops_per_step = flops.compiled_flops(
            step_fn.lower(state, *probe).compile())
        if flops_per_step is not None:
            flops_per_step *= self.mesh.size
        else:
            # analytic fallback resolved BEFORE the loop so per-window MFU
            # gauges have a numerator too, not just the final summary
            per_image = flops.resnet_train_flops_per_image(
                getattr(self.model, "arch", "") or "",
                self.config.image_size,
                stem=getattr(self.model, "stem", "conv7"))
            flops_per_step = (per_image * self.config.global_batch_size
                              if per_image else None)
        state, metrics = step_fn(state, *probe)
        for _ in range(max(0, warmup_steps - 1)):
            images, labels = next(it)
            state, metrics = step_fn(state, images, labels)
        float(metrics["loss"])       # true barrier (see docstring)
        base_step = int(state.step)  # one host read, OUTSIDE the loop

        window_ips = []
        profiler = WindowProfiler(profile_dir, log)
        profiler.start()
        wall0 = time.perf_counter()
        t0 = wall0
        try:
            for i in range(1, num_steps + 1):
                images, labels = next(it)
                with span("train.step"):
                    state, metrics = step_fn(state, images, labels)
                if step_hook is not None:
                    # periodic async checkpointing
                    # (train/checkpoint.periodic_saver)
                    step_hook(state, base_step + i)
                if resilience is not None \
                        and resilience.on_step(base_step + i):
                    from .resilience import Preempted
                    log(f"preemption drain: stopping the gang at step "
                        f"{base_step + i}")
                    resilience.emergency_save(state)
                    raise Preempted(base_step + i)
                if i % log_every == 0:
                    g0 = time.perf_counter()
                    loss = float(metrics["loss"])  # sync: closes the window
                    t1 = time.perf_counter()       # BEFORE the trace write
                    tel.host_gap_seconds.observe(t1 - g0)
                    profiler.stop_if_active()
                    ips = self.config.global_batch_size * log_every \
                        / (t1 - t0)
                    window_ips.append(ips)
                    tel.observe_steps((t1 - t0) / log_every, log_every)
                    tel.update_window(
                        examples_per_sec=ips,
                        mfu=flops.throughput_stats(
                            flops_per_step,
                            ips / self.config.global_batch_size,
                            self.mesh.size)["mfu"],
                        step=base_step + i)
                    streak = int(metrics.get("nonfinite_streak", 0))
                    if streak:
                        tel.record_streak(streak)
                    # tf_cnn_benchmarks log format (ref README.md:113-125)
                    log(f"{i}\timages/sec: {ips:.1f}\tloss: {loss:.3f}")
                    if resilience is not None \
                            and streak >= resilience.config.divergence_k:
                        state = resilience.rollback(state)
                        base_step = int(state.step) - i
                    t0 = time.perf_counter()       # fetch/log time excluded
        finally:
            profiler.stop_if_active()
        final_loss = float(metrics["loss"])
        wall = time.perf_counter() - wall0
        steady = window_ips[1:] if len(window_ips) > 1 else window_ips
        total_ips = sum(steady) / len(steady)
        n = self.mesh.size
        stats = flops.throughput_stats(
            flops_per_step, total_ips / self.config.global_batch_size, n)
        p50_ms, p99_ms = tel.step_percentiles_ms()
        gap50_ms, gap99_ms = tel.host_gap_percentiles_ms()
        log("-" * 40)
        log(f"total images/sec: {total_ips:.2f}")   # ref README.md:127-131
        if p50_ms is not None:
            log(f"step time: p50 {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms, "
                f"goodput {tel.goodput.value:.1%}")
        if stats["mfu"] is not None:
            log(f"per-device: {stats['tflops_per_sec_per_device']:.1f} "
                f"TFLOP/s, MFU {stats['mfu']:.1%}")
        log("-" * 40)
        return state, {
            "images_per_sec": total_ips,
            "images_per_sec_per_device": total_ips / n,
            "steps": num_steps,
            "wall_seconds": wall,
            "final_loss": final_loss,
            "step_time_p50_ms": p50_ms,
            "step_time_p99_ms": p99_ms,
            "host_gap_p50_ms": gap50_ms,
            "host_gap_p99_ms": gap99_ms,
            "goodput": tel.goodput.value,
            **stats,
        }


__all__ = ["TrainState", "Trainer", "TrainerConfig", "make_sgd",
           "cross_entropy_loss"]
