"""jax version compatibility — ONE place that knows which API vintage is
installed.

The codebase is written against the current jax surface (`jax.shard_map`,
`jax.typeof(...).vma`, `jax.lax.axis_size`); the container may carry an
older release (0.4.x) where shard_map still lives in jax.experimental with
the (check_rep, auto) parameter spelling. Every module imports the
new-style names from here instead of sniffing versions locally, so the
whole repo flips vintage in one file.

Exports:
  shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
            check_vma=None)
      — the modern keyword surface. On legacy jax, `axis_names` (the
      MANUAL axes) is translated to `auto` (its complement over
      mesh.axis_names) and `check_vma` to `check_rep`.
  out_struct(shape, dtype, *like)
      — jax.ShapeDtypeStruct carrying the union of the `like` operands'
      varying-manual-axes when the installed jax tracks VMA; a plain
      struct otherwise (legacy jax has no vma typing to satisfy).
  axis_bound(name)
      — True when `name` is a live collective axis at trace time.
"""
from __future__ import annotations

import jax

try:
    from jax import shard_map as _native_shard_map
except ImportError:                                   # jax < 0.6
    _native_shard_map = None
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

try:
    HAS_VMA = hasattr(jax.typeof(0.0), "vma")
except AttributeError:                                # jax < 0.6
    HAS_VMA = False


if _native_shard_map is not None:
    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return _native_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
else:
    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None):
        kw = {}
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
                # legacy partial-auto shard_map can't infer replication
                # through auto-axis regions; rep checking must be off
                # unless the caller explicitly asked for it
                if check_vma is None:
                    check_vma = False
        if check_vma is not None:
            kw["check_rep"] = bool(check_vma)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)


def out_struct(shape, dtype, *like):
    """Pallas out_shape carrying the varying-manual-axes of its inputs, so
    kernels type-check under shard_map's default VMA checker (ring
    attention launches them inside a manual region). Plain struct on
    legacy jax (no vma typing there to satisfy)."""
    if HAS_VMA:
        vma = frozenset().union(*(jax.typeof(x).vma for x in like))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def axis_size(name) -> int:
    """Static size of a bound collective axis — `jax.lax.axis_size` where
    it exists; `lax.psum(1, name)` (which constant-folds to a Python int
    at trace time) on legacy jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def axis_bound(name: str) -> bool:
    """True when `name` is a live collective axis (tracing inside
    shard_map/pmap over it)."""
    try:
        if hasattr(jax.lax, "axis_size"):
            jax.lax.axis_size(name)
        else:                                         # jax < 0.5
            jax.lax.axis_index(name)
        return True
    except NameError:
        return False


__all__ = ["shard_map", "out_struct", "axis_size", "axis_bound",
           "HAS_VMA"]
