"""jax version compatibility — ONE place that knows which API vintage is
installed.

The codebase is written against the current jax surface (`jax.shard_map`,
`jax.typeof(...).vma`, `jax.lax.axis_size`); the container may carry an
older release (0.4.x) where shard_map still lives in jax.experimental with
the (check_rep, auto) parameter spelling. Every module imports the
new-style names from here instead of sniffing versions locally, so the
whole repo flips vintage in one file.

Exports:
  shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
            check_vma=None)
      — the modern keyword surface. On legacy jax, `axis_names` (the
      MANUAL axes) is translated to `auto` (its complement over
      mesh.axis_names) and `check_vma` to `check_rep`.
  out_struct(shape, dtype, *like)
      — jax.ShapeDtypeStruct carrying the union of the `like` operands'
      varying-manual-axes when the installed jax tracks VMA; a plain
      struct otherwise (legacy jax has no vma typing to satisfy).
  axis_bound(name)
      — True when `name` is a live collective axis at trace time.
"""
from __future__ import annotations

import jax

try:
    from jax import shard_map as _native_shard_map
except ImportError:                                   # jax < 0.6
    _native_shard_map = None
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

try:
    HAS_VMA = hasattr(jax.typeof(0.0), "vma")
except AttributeError:                                # jax < 0.6
    HAS_VMA = False


if _native_shard_map is not None:
    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return _native_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
else:
    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None):
        kw = {}
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
                # legacy partial-auto shard_map can't infer replication
                # through auto-axis regions; rep checking must be off
                # unless the caller explicitly asked for it
                if check_vma is None:
                    check_vma = False
        if check_vma is not None:
            kw["check_rep"] = bool(check_vma)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)


def out_struct(shape, dtype, *like):
    """Pallas out_shape carrying the varying-manual-axes of its inputs, so
    kernels type-check under shard_map's default VMA checker (ring
    attention launches them inside a manual region). Plain struct on
    legacy jax (no vma typing there to satisfy)."""
    if HAS_VMA:
        vma = frozenset().union(*(jax.typeof(x).vma for x in like))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def axis_size(name) -> int:
    """Static size of a bound collective axis — `jax.lax.axis_size` where
    it exists; `lax.psum(1, name)` (which constant-folds to a Python int
    at trace time) on legacy jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def axis_bound(name: str) -> bool:
    """True when `name` is a live collective axis (tracing inside
    shard_map/pmap over it)."""
    try:
        if hasattr(jax.lax, "axis_size"):
            jax.lax.axis_size(name)
        else:                                         # jax < 0.5
            jax.lax.axis_index(name)
        return True
    except NameError:
        return False


def _patch_threefry_partitionable() -> None:
    """Modern jax defaults `jax_threefry_partitionable` to True; 0.4.x
    ships it False, where a jit with sharded out_shardings can produce
    DIFFERENT random bits than the same program unsharded. The repo's
    shard_init contract (parallel/sharding.py) — and every
    sharded-vs-replicated parity test — assumes the modern semantics:
    identical values regardless of layout. Flip the flag to the modern
    default; explicit user overrides (env/flag already set) are kept."""
    try:
        if not jax.config.jax_threefry_partitionable:
            import os
            if "JAX_THREEFRY_PARTITIONABLE" not in os.environ:
                jax.config.update("jax_threefry_partitionable", True)
    except AttributeError:      # flag removed once partitionable-only
        pass


_patch_threefry_partitionable()


def cpu_collectives_solo_fallback() -> None:
    """Make single-process CPU backend init survive a blanket
    `jax_cpu_collectives_implementation=gloo`.

    Multi-host launch wrappers set the gloo flag before the gang size is
    known (cross-process CPU collectives need it), but this jaxlib
    vintage's binding requires a live DistributedRuntimeClient —
    `make_gloo_tcp_collectives(distributed_client=None)` is a TypeError,
    so a process that (correctly) skipped jax.distributed.initialize
    because num_processes == 1 can't even build its CPU backend. Newer
    jaxlib accepts None. Called from bootstrap.initialize on the
    single-process path: with no distributed client connected, drop back
    to the in-process default before the backend first initializes."""
    try:
        from jax._src import distributed
        from jax._src import xla_bridge as _xb
        if distributed.global_state.client is not None:
            return                      # real gang: gloo is wanted
        # a flag, not a config-state attribute — read the holder directly
        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value == "gloo":
            jax.config.update("jax_cpu_collectives_implementation", "none")
    except (ImportError, AttributeError):
        pass                            # modern jaxlib: None is accepted


def _patch_flax_duplicate_logical_names() -> None:
    """flax >= 0.8 hard-errors when a parameter's logical axis names repeat
    (`flax/linen/spmd.py:_logical_to_mesh_axes` raises "Dimensions (...)
    occur more than once"). The repo's rule table takes the opposite,
    well-defined stance (parallel/sharding.logical_to_spec): a mesh axis
    shards at most one dim, so later duplicates REPLICATE — an
    ("embed", "embed") square kernel (MaskedLM's mlm_dense) shards its
    first dim and replicates the second. Rewrite duplicates to None before
    flax's checker sees them; first occurrence keeps its rule, which is
    exactly the layout logical_to_spec computes for the same names."""
    try:
        from flax.linen import spmd as _spmd
    except ImportError:
        return
    orig = getattr(_spmd, "_logical_to_mesh_axes", None)
    if orig is None or getattr(orig, "_dedup_wrapped", False):
        return

    def dedup(array_dim_names, rules=None):
        if array_dim_names is not None:
            seen = set()
            fixed = []
            for name in array_dim_names:
                fixed.append(None if name in seen else name)
                if isinstance(name, str):
                    seen.add(name)
            array_dim_names = tuple(fixed)
        return orig(array_dim_names, rules)

    dedup._dedup_wrapped = True
    _spmd._logical_to_mesh_axes = dedup


_patch_flax_duplicate_logical_names()


__all__ = ["shard_map", "out_struct", "axis_size", "axis_bound",
           "HAS_VMA"]
