"""FLOPs accounting and MFU (model FLOPs utilization).

The reference publishes raw images/sec only (README.md:113-131) — no
hardware-utilization story. On TPU the number that actually says whether a
program maps well onto the MXU is MFU: achieved *model* FLOP/s over the
chip's peak. Two sources:

  1. analytic per-model estimates (the standard 6N+attention / per-image
     formulas) — the conventional MFU numerator (model FLOPs, independent
     of remat or padding);
  2. XLA's cost model for the exact compiled executable
     (`Compiled.cost_analysis()["flops"]`). Two caveats make it the
     fallback, not the primary: it analyzes the post-SPMD-partition
     module, so the count is PER DEVICE (callers must scale by mesh size
     for a global figure), and Pallas kernels are opaque custom calls it
     scores as 0 FLOPs — on the flash-attention path it misses the whole
     attention share.

All `flops_per_step` values in this module's API are GLOBAL (whole-mesh)
per-step counts; MFU is flops_per_step * steps_per_sec / (n_devices * peak).
"""
from __future__ import annotations

from typing import Optional

# bf16 peak dense matmul FLOP/s per chip, by device_kind substring.
# (public figures: v2 45T, v3 123T, v4 275T, v5e 197T, v5p 459T, v6e 918T)
_PEAK_TABLE = (
    ("v6e", 918e12), ("v6 lite", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),              # plain "TPU v5" = v5p (observed kind)
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s for one device; None when unknown (CPU/GPU)."""
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and device.platform != "tpu":
        return None
    return _lookup(kind, _PEAK_TABLE)


# HBM bandwidth per chip (bytes/s), by device_kind substring — the decode
# roofline's denominator. Public figures: v2 700GB/s, v3 900, v4 1228,
# v5e 819, v5p 2765, v6e (Trillium) 1640.
_HBM_TABLE = (
    ("v6e", 1640e9), ("v6 lite", 1640e9), ("trillium", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5litepod", 819e9),
    ("v5", 2765e9),              # plain "TPU v5" = v5p (observed kind)
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

# The bare "v5" rows above are a last-resort fallback: real v5p chips
# report device_kind "TPU v5" verbatim, so dropping the rows would
# silently lose every mfu/mbu field on v5p. But an UNEXPECTED v5e kind
# spelling landing on them would overstate peak bandwidth ~3.4x and
# silently understate MBU — so any bare-marker match is logged loudly
# (the advisor-r04 visibility remedy).
_BARE_FALLBACK_WARNED = set()


def _lookup(kind: str, table) -> Optional[float]:
    for marker, val in table:
        if marker in kind:
            if marker == "v5" and kind not in _BARE_FALLBACK_WARNED:
                _BARE_FALLBACK_WARNED.add(kind)
                import sys
                print(f"# flops: device_kind {kind!r} matched only the "
                      f"bare 'v5' marker — assuming v5p peak figures; "
                      f"if this is a v5e spelling, MFU/MBU are wrong",
                      file=sys.stderr)
            return val
    return None


def device_hbm_bandwidth(device=None) -> Optional[float]:
    """Peak HBM bytes/s for one device; None when unknown (CPU/GPU)."""
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and device.platform != "tpu":
        return None
    return _lookup(kind, _HBM_TABLE)


def decode_bytes_per_step(num_params: int, num_layers: int,
                          num_kv_heads: int, head_dim: int,
                          batch: int, avg_len: float,
                          param_bytes: int = 2,
                          kv_cache_bytes: float = 2.0,
                          kv_scale_bytes: float = 0.0) -> float:
    """HBM bytes one autoregressive decode step must read — the roofline
    numerator for MBU (model bandwidth utilization). Decode at small batch
    is bandwidth-bound: every step re-reads the full parameter set once
    (amortized over the whole batch) plus each sequence's KV cache at its
    current length. `kv_cache_bytes` is per cached element (2 bf16, 1
    int8); `kv_scale_bytes` covers quantization scales per (position,
    head) pair per k/v tensor (4 for one f32 scale)."""
    params = num_params * param_bytes
    kv_per_pos = 2 * num_layers * num_kv_heads * (
        head_dim * kv_cache_bytes + kv_scale_bytes)
    return params + batch * avg_len * kv_per_pos


def mbu(bytes_per_step: float, steps_per_sec: float,
        device=None) -> Optional[float]:
    """Achieved fraction of peak HBM bandwidth (single device). None when
    the device's bandwidth is unknown."""
    bw = device_hbm_bandwidth(device)
    if not bw or not bytes_per_step:
        return None
    return bytes_per_step * steps_per_sec / bw


def compiled_flops(compiled) -> Optional[float]:
    """Total FLOPs of one execution of a jax `Compiled`, from XLA's cost
    model. Returns None when the backend doesn't report it."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent surface
        return None
    # versions differ: dict, or list with one dict per computation
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    flops = (analysis or {}).get("flops")
    return float(flops) if flops and flops > 0 else None


# ---------------------------------------------------------------------------
# analytic fallbacks
# ---------------------------------------------------------------------------

# forward FLOPs per 224×224 image. The widely-quoted "GFLOPs" table values
# (1.8/3.7/4.1/7.8/11.6) are multiply-ACCUMULATES; true FLOPs are 2× that.
# Cross-checked against XLA's cost model on the compiled forward (resnet101:
# 15.07 GFLOP/img vs 15.7 analytic — within conv-padding noise).
_RESNET_FWD_FLOPS_224 = {
    "resnet18": 3.64e9,
    "resnet34": 7.36e9,
    "resnet50": 8.24e9,
    "resnet101": 15.70e9,
    "resnet152": 23.16e9,
}


def resnet_train_flops_per_image(model_name: str,
                                 image_size: int = 224,
                                 stem: str = "conv7") -> Optional[float]:
    """fwd+bwd FLOPs per image ≈ 3× forward (bwd ≈ 2× fwd); conv FLOPs
    scale with spatial area, so rescale from the 224px table. The "s2d"
    stem (models/resnet.py) replaces the 7×7/s2 conv with a 2×2 conv on
    the 4×4 space-to-depth input — fewer actual FLOPs, so the table
    value is adjusted or the reported MFU would overstate work done."""
    fwd = _RESNET_FWD_FLOPS_224.get(model_name)
    if fwd is None:
        return None
    if stem == "s2d":
        # at 224px: conv7 stem = 2·112²·64·(7·7·3) = 236.0 MF fwd;
        # s2d stem = 2·56²·64·(2·2·48) = 77.1 MF fwd
        fwd = fwd - (236.0e6 - 77.1e6)
    return 3.0 * fwd * (image_size / 224.0) ** 2


def transformer_train_flops_per_token(num_params: int, num_layers: int,
                                      embed_dim: int, seq_len: int,
                                      causal: bool = True) -> float:
    """Standard accounting (PaLM appendix B): 6N matmul FLOPs per token for
    fwd+bwd, plus attention logits/values 12·L·E·S (halved for causal)."""
    attn = 12.0 * num_layers * embed_dim * seq_len
    if causal:
        attn /= 2.0
    return 6.0 * num_params + attn


def param_count(params) -> int:
    import jax

    return sum(int(x.size) for x in jax.tree.leaves(params))


def mfu(flops_per_step: Optional[float], steps_per_sec: float,
        n_devices: int, device=None) -> Optional[float]:
    """Achieved fraction of peak, per device. `flops_per_step` is the
    GLOBAL (whole-mesh) model FLOPs of one step. None when either side of
    the ratio is unknown."""
    peak = device_peak_flops(device)
    if not flops_per_step or not peak or n_devices <= 0:
        return None
    return flops_per_step * steps_per_sec / (n_devices * peak)


def throughput_stats(flops_per_step: Optional[float], steps_per_sec: float,
                     n_devices: int, device=None) -> dict:
    """The metric triple both trainers report: global flops_per_step,
    per-device TFLOP/s, and MFU (None-safe)."""
    tfl = (flops_per_step * steps_per_sec / n_devices / 1e12
           if flops_per_step and n_devices > 0 else None)
    return {
        "flops_per_step": flops_per_step,
        "tflops_per_sec_per_device": tfl,
        "mfu": mfu(flops_per_step, steps_per_sec, n_devices, device),
    }


__all__ = ["device_peak_flops", "device_hbm_bandwidth", "compiled_flops",
           "resnet_train_flops_per_image",
           "transformer_train_flops_per_token", "param_count", "mfu",
           "mbu", "decode_bytes_per_step", "throughput_stats"]
