"""Force the JAX host (CPU) platform with N virtual devices.

This environment ships an `axon` sitecustomize (PYTHONPATH) that forces the
TPU platform regardless of JAX_PLATFORMS; setting jax.config BEFORE any
backend is initialized is the reliable override channel.  Used by
tests/conftest.py and __graft_entry__.dryrun_multichip so the two callers
cannot drift.

Must be called before the jax backend initializes (importing jax is fine;
creating an array is not).
"""
import os


def force_host_platform(n_devices: int) -> None:
    """Point JAX at the host platform with exactly ``n_devices`` devices.

    Any pre-existing ``--xla_force_host_platform_device_count`` flag is
    replaced unconditionally: callers state the device count they validate
    against, and a stale value in either direction makes the validation
    lie (too few trips the caller's device-count assert with a misleading
    message; too many shards test meshes differently than intended).
    """
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax

    jax.config.update("jax_platforms", "cpu")
