"""First-window benchmark profiling (jax.profiler / XProf).

The reference has no profiling story at all (SURVEY §5 — glog only); this
exposes per-op device timelines, HBM traffic, and MXU occupancy for the
first measurement window of a benchmark loop. Kept as a tiny stateful
helper so both trainers share the exact same start/stop discipline:

  - the stop (which serializes the xplane file — real I/O) happens AFTER
    the window's closing timestamp is taken, so trace writing is never
    charged to reported throughput;
  - callers wrap their loop in try/finally with `stop_if_active()` so an
    exception mid-window can't leave the global profiler session running
    (a leaked session makes every later start_trace raise).
"""
from __future__ import annotations

from typing import Callable, Optional


class WindowProfiler:
    def __init__(self, profile_dir: Optional[str],
                 log: Callable[[str], None] = print):
        self._dir = profile_dir
        self._log = log
        self._active = False

    def start(self) -> None:
        if self._dir and not self._active:
            import jax

            jax.profiler.start_trace(self._dir)
            self._active = True

    def stop_if_active(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._log(f"profiler trace written to {self._dir}")


__all__ = ["WindowProfiler"]
