#!/usr/bin/env bash
# The blessed tier-1 gate — the ROADMAP.md "Tier-1 verify" command,
# verbatim. CI and local builders invoke THIS script so there is exactly
# one definition of "the tests pass"; if the command needs to change,
# change it in ROADMAP.md and mirror it here in the same commit.
#
# Semantics worth knowing before editing:
#   - JAX_PLATFORMS=cpu + tests/conftest.py give 8 virtual CPU devices
#     (real XLA collectives, no TPUs needed).
#   - -m 'not slow' excludes the multi-second compile variants; the
#     `multichip` marker (tests/conftest.py) stays INCLUDED here because
#     the virtual-device mesh satisfies it.
#   - timeout -k 10 1140: the whole suite must land in ~19 min (870
#     until 2026-08-05 — see the budget history note in ROADMAP.md).
#   - DOTS_PASSED counts progress dots from the captured log so the
#     driver can read a pass-count even when pytest's summary line is
#     cut off by the timeout.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1140 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
