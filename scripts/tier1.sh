#!/usr/bin/env bash
# The blessed tier-1 gate — the ROADMAP.md "Tier-1 verify" command,
# verbatim. CI and local builders invoke THIS script so there is exactly
# one definition of "the tests pass"; if the command needs to change,
# change it in ROADMAP.md and mirror it here in the same commit.
#
# Semantics worth knowing before editing:
#   - JAX_PLATFORMS=cpu + tests/conftest.py give 8 virtual CPU devices
#     (real XLA collectives, no TPUs needed).
#   - -m 'not slow' excludes the multi-second compile variants; the
#     `multichip` marker (tests/conftest.py) stays INCLUDED here because
#     the virtual-device mesh satisfies it, and so do the `serving` and
#     `hfta` markers (run `pytest -m hfta` to gate the fused-trainer
#     surface alone).
#   - timeout -k 10 1860: the whole suite must land in ~31 min (870,
#     then 1140, then 1320, then 1500 until 2026-08-05 — see the budget
#     history note in ROADMAP.md).
#   - DOTS_PASSED counts progress dots from the captured log so the
#     driver can read a pass-count even when pytest's summary line is
#     cut off by the timeout.
#
#   ./scripts/tier1.sh --resilience additionally runs the OUT-OF-PROCESS
#   preemption smoke below (real SIGTERM, real exit codes, real resume —
#   the in-process pytest e2e can't observe the exit-status contract).

if [ "${1:-}" = "--resilience" ]; then
  # Preemption smoke: kill the shipped lm_benchmark entrypoint at step 5
  # via the fault injector, assert the RETRYABLE exit code (215) and the
  # emergency checkpoint, then rerun clean and assert it resumes and
  # exits 0 — the controller-eye view of a preempted gang.
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  run_env=(env JAX_PLATFORMS=cpu
           TPU_COORDINATOR_ADDRESS=localhost:8476 TPU_NUM_PROCESSES=1)
  args=(python -m mpi_operator_tpu.examples.lm_benchmark
        --workload gpt2 --size test --batch-per-device 1 --seq-len 16
        --dtype float32 --warmup-steps 1 --num-steps 20
        --train-dir "$dir/ckpt")
  echo "== resilience smoke: preempt at step 5 =="
  "${run_env[@]}" TPU_FAULT_INJECT=sigterm-at-step:5 \
    "${args[@]}" > "$dir/preempt.log" 2>&1
  rc=$?
  if [ "$rc" -ne 215 ]; then
    echo "FAIL: preempted run exited $rc (want 215, the retryable band)"
    tail -20 "$dir/preempt.log"; exit 1
  fi
  if [ ! -d "$dir/ckpt/step_5" ]; then
    echo "FAIL: no emergency checkpoint at step_5"; ls "$dir/ckpt"; exit 1
  fi
  # the structured event log (telemetry/events.py, default path
  # <train-dir>/events.jsonl) must carry the drain sequence, fsync'd
  # BEFORE exit(215) — the durability contract a postmortem relies on
  if ! grep -q '"event": "preemption_drain"' "$dir/ckpt/events.jsonl"; then
    echo "FAIL: no preemption_drain record in the event log"
    cat "$dir/ckpt/events.jsonl" 2>/dev/null; exit 1
  fi
  if ! grep -q '"event": "emergency_checkpoint"' "$dir/ckpt/events.jsonl"; then
    echo "FAIL: no emergency_checkpoint record in the event log"
    cat "$dir/ckpt/events.jsonl" 2>/dev/null; exit 1
  fi
  echo "== resilience smoke: resume to step 8 =="
  "${run_env[@]}" "${args[@]}" --num-steps 20 --stop-at-step 8 \
    > "$dir/resume.log" 2>&1
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: resumed run exited $rc"; tail -20 "$dir/resume.log"; exit 1
  fi
  if ! grep -q "resumed from .*step_5" "$dir/resume.log"; then
    echo "FAIL: resumed run did not restore the emergency checkpoint"
    tail -20 "$dir/resume.log"; exit 1
  fi
  if [ ! -d "$dir/ckpt/step_8" ]; then
    echo "FAIL: resumed run did not reach global step 8"
    ls "$dir/ckpt"; exit 1
  fi
  echo "resilience smoke: OK (exit 215 -> emergency step_5 -> events -> resume -> step_8)"
  exit 0
fi

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1860 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --durations=15 --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
