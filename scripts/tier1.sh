#!/usr/bin/env bash
# The blessed tier-1 gate — the ROADMAP.md "Tier-1 verify" command,
# verbatim. CI and local builders invoke THIS script so there is exactly
# one definition of "the tests pass"; if the command needs to change,
# change it in ROADMAP.md and mirror it here in the same commit.
#
# Semantics worth knowing before editing:
#   - JAX_PLATFORMS=cpu + tests/conftest.py give 8 virtual CPU devices
#     (real XLA collectives, no TPUs needed).
#   - -m 'not slow' excludes the multi-second compile variants; the
#     `multichip` marker (tests/conftest.py) stays INCLUDED here because
#     the virtual-device mesh satisfies it, and so do the `serving` and
#     `hfta` markers (run `pytest -m hfta` to gate the fused-trainer
#     surface alone).
#   - timeout -k 10 3000: the whole suite must land in 50 min (870,
#     then 1140, 1320, 1500, 1860, 2400 until 2026-08-08 — see the budget
#     history note in ROADMAP.md).
#   - DOTS_PASSED counts progress dots from the captured log so the
#     driver can read a pass-count even when pytest's summary line is
#     cut off by the timeout.
#
#   ./scripts/tier1.sh --resilience additionally runs the OUT-OF-PROCESS
#   preemption smoke below (real SIGTERM, real exit codes, real resume —
#   the in-process pytest e2e can't observe the exit-status contract).
#
#   ./scripts/tier1.sh --serving runs the OUT-OF-PROCESS disaggregated
#   prefill/decode A/B smoke: the same greedy trace through the
#   colocated paged engine and the two-pool DisaggEngine, gated on
#   token identity + the per-pool compile pins + actual KV handoffs.
#
#   ./scripts/tier1.sh --router runs the OUT-OF-PROCESS front-door
#   smoke: 2 in-process engine replicas behind the prefix-affinity
#   router on a shared-system-prompt trace, gated on token identity vs
#   the single-engine oracle, a nonzero (and A/B-higher) affinity hit
#   rate, zero sheds at low load, and >= 1 shed + clean recovery at the
#   overload burst. Budget: ~5 min of the 10-min leg timeout on a cold
#   CPU cache (mirrored in ROADMAP.md).
#
#   ./scripts/tier1.sh --elastic runs the OUT-OF-PROCESS gang-resize
#   smoke: one training run resized 4 -> 2 -> 4 CPU-host devices via
#   SIGTERM drain + resharding restore (TPU_RESHARD_RESTORE=1), gated
#   on oracle loss parity, both gang_resize records in the merged
#   timeline, the resize_seconds phase split, and nonzero goodput.
#
#   ./scripts/tier1.sh --sched runs the OUT-OF-PROCESS fleet-scheduler
#   smoke: two competing jobs on a fake 4-device pool — the real
#   FleetScheduler preempts the low-priority elastic gang 4 -> 2 to
#   admit the high-priority job, grows it back after completion —
#   gated on BOTH jobs' final losses being token-identical to solo
#   oracles, the sched_* decision records in the merged timeline, and
#   the postmortem rendering its "scheduler actions:" section.

if [ "${1:-}" = "--serving" ]; then
  # Disagg A/B smoke via the benchmark CLI (examples/serve_benchmark.py
  # --disagg): one subprocess builds both engines from the same params,
  # replays one trace through each, and prints a JSON line. On CPU the
  # latency split is structural, so the gates are the CORRECTNESS
  # contracts: greedy tokens bitwise-identical across modes, prefill
  # pool compiled zero decode steps / decode pool zero prefills, and a
  # nonzero handoff count (pages actually moved between pools).
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  echo "== serving smoke: disagg vs colocated A/B =="
  env JAX_PLATFORMS=cpu python -m mpi_operator_tpu.examples.serve_benchmark \
    --disagg --size test --slots 4 --num-requests 8 --page-size 16 \
    > "$dir/disagg.json" 2> "$dir/disagg.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: disagg benchmark exited $rc"
    tail -20 "$dir/disagg.log"; exit 1
  fi
  if ! grep -q '"disagg_token_identical": true' "$dir/disagg.json"; then
    echo "FAIL: disagg tokens differ from the colocated engine's"
    cat "$dir/disagg.json"; exit 1
  fi
  if ! grep -q '"disagg_pool_pins_held": true' "$dir/disagg.json"; then
    echo "FAIL: a pool compiled the other role's program"
    cat "$dir/disagg.json"; exit 1
  fi
  if grep -q '"disagg_handoffs": 0' "$dir/disagg.json"; then
    echo "FAIL: no KV handoffs — the A/B never crossed the pool boundary"
    cat "$dir/disagg.json"; exit 1
  fi
  for key in disagg_kv_handoff_p50_ms disagg_kv_handoff_p99_ms \
             disagg_ttft_p99_ms coloc_ttft_p99_ms; do
    if ! grep -q "\"$key\":" "$dir/disagg.json"; then
      echo "FAIL: missing $key in the benchmark JSON"
      cat "$dir/disagg.json"; exit 1
    fi
  done
  # request tracing: every measured request must reconstruct as a full
  # prefill -> kv_handoff -> decode span tree, and the kv_handoff hops
  # must carry the page counts the transfer actually moved
  if ! grep -q '"disagg_trace_complete": true' "$dir/disagg.json"; then
    echo "FAIL: a disagg request's span tree is missing a hop (or a root)"
    cat "$dir/disagg.json"; exit 1
  fi
  if grep -q '"disagg_trace_handoff_pages": 0' "$dir/disagg.json"; then
    echo "FAIL: the kv_handoff hops carry zero moved pages"
    cat "$dir/disagg.json"; exit 1
  fi
  echo "serving smoke: OK (disagg A/B token-identical, pool pins held," \
       "$(grep -o '"disagg_handoffs": [0-9]*' "$dir/disagg.json" | grep -o '[0-9]*') handoffs)"
  exit 0
fi

if [ "${1:-}" = "--router" ]; then
  # Front-door smoke via the benchmark CLI (examples/serve_benchmark.py
  # --router): one subprocess builds replica fleets from the same
  # params, replays one seeded multi-tenant shared-prefix trace with
  # affinity ON vs OFF plus an overload burst, and prints a JSON line.
  # On CPU the latency split is structural, so the gates are the
  # CORRECTNESS contracts below.
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  echo "== router smoke: prefix-affinity front door over 2 replicas =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m mpi_operator_tpu.examples.serve_benchmark \
    --router --size test --slots 4 --num-requests 12 --page-size 16 \
    > "$dir/router.json" 2> "$dir/router.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: router benchmark exited $rc"
    tail -20 "$dir/router.log"; exit 1
  fi
  if ! grep -q '"router_token_identical": true' "$dir/router.json"; then
    echo "FAIL: routed tokens differ from the single-engine oracle"
    cat "$dir/router.json"; exit 1
  fi
  if ! grep -q '"router_affinity_nonzero": true' "$dir/router.json"; then
    echo "FAIL: zero affinity hit rate — routing never found a warm chain"
    cat "$dir/router.json"; exit 1
  fi
  if ! grep -q '"router_affinity_hit_gain": true' "$dir/router.json"; then
    echo "FAIL: affinity routing did not beat load-only on replica-side hit rate"
    cat "$dir/router.json"; exit 1
  fi
  if ! grep -q '"router_shed_low_load": 0' "$dir/router.json"; then
    echo "FAIL: the router shed requests at low offered load"
    cat "$dir/router.json"; exit 1
  fi
  if grep -q '"router_burst_sheds": 0' "$dir/router.json"; then
    echo "FAIL: the overload burst shed nothing — admission control never fired"
    cat "$dir/router.json"; exit 1
  fi
  if ! grep -q '"router_burst_recovery_clean": true' "$dir/router.json"; then
    echo "FAIL: post-burst recovery requests did not complete cleanly"
    cat "$dir/router.json"; exit 1
  fi
  if ! grep -q '"router_compile_pins_held": true' "$dir/router.json"; then
    echo "FAIL: a replica broke the compile-count pins"
    cat "$dir/router.json"; exit 1
  fi
  # request tracing: every routed request must reconstruct as one
  # queue_wait -> admission -> prefill -> decode span tree whose hop
  # durations sum to the root e2e within tolerance
  if ! grep -q '"router_trace_complete": true' "$dir/router.json"; then
    echo "FAIL: a routed request's span tree is incomplete or gapped"
    cat "$dir/router.json"; exit 1
  fi
  echo "router smoke: OK (token-identical, hit rate" \
       "$(grep -o '"router_affinity_hit_rate": [0-9.]*' "$dir/router.json" | grep -o '[0-9.]*$') vs" \
       "$(grep -o '"router_noaffinity_hit_rate": [0-9.]*' "$dir/router.json" | grep -o '[0-9.]*$') load-only," \
       "$(grep -o '"router_burst_sheds": [0-9]*' "$dir/router.json" | grep -o '[0-9]*$') burst sheds, clean recovery)"
  # Live-scale gate: the SAME trace through one +1 attach and one -1
  # graceful drain mid-trace. Zero sheds attributable to the steps,
  # bitwise token identity held for every request (drained-replica
  # failovers included), and the measured live_scale ledger total must
  # price strictly below the same trace's gang-restart total.
  echo "== livescale smoke: +1 attach / -1 drain mid-trace vs gang restart =="
  timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m mpi_operator_tpu.examples.serve_benchmark \
    --livescale --size test --slots 4 --num-requests 12 --page-size 16 \
    > "$dir/livescale.json" 2> "$dir/livescale.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: livescale benchmark exited $rc"
    tail -20 "$dir/livescale.log"; exit 1
  fi
  if ! grep -q '"livescale_attaches": 1' "$dir/livescale.json" \
      || ! grep -q '"livescale_detaches": 1' "$dir/livescale.json"; then
    echo "FAIL: the livescale trace did not execute exactly one attach and one detach"
    cat "$dir/livescale.json"; exit 1
  fi
  if ! grep -q '"livescale_dropped": 0' "$dir/livescale.json" \
      || ! grep -q '"livescale_sheds": 0' "$dir/livescale.json"; then
    echo "FAIL: the live scale step dropped or shed a request"
    cat "$dir/livescale.json"; exit 1
  fi
  if ! grep -q '"livescale_token_identical": true' "$dir/livescale.json" \
      || ! grep -q '"livescale_gang_token_identical": true' "$dir/livescale.json"; then
    echo "FAIL: tokens diverged from the never-scaled oracle across a scale step"
    cat "$dir/livescale.json"; exit 1
  fi
  if ! grep -q '"livescale_compile_pins_held": true' "$dir/livescale.json"; then
    echo "FAIL: a survivor (or the newcomer) recompiled across the live step"
    cat "$dir/livescale.json"; exit 1
  fi
  if ! grep -q '"livescale_ledger_vs_gang_ok": true' "$dir/livescale.json"; then
    echo "FAIL: live_scale ledger total did not beat the gang-restart total"
    cat "$dir/livescale.json"; exit 1
  fi
  # tracing across the scale steps: failed-over requests must still
  # reconstruct as ONE contiguous root each
  if ! grep -q '"livescale_trace_complete": true' "$dir/livescale.json"; then
    echo "FAIL: a live-arm request's span tree is incomplete across the scale step"
    cat "$dir/livescale.json"; exit 1
  fi
  echo "livescale smoke: OK (ledger" \
       "$(grep -o '"livescale_ledger_total_seconds": [0-9.]*' "$dir/livescale.json" | grep -o '[0-9.]*$')s live vs" \
       "$(grep -o '"livescale_gang_total_seconds": [0-9.]*' "$dir/livescale.json" | grep -o '[0-9.]*$')s gang, p99 TTFT" \
       "$(grep -o '"livescale_ttft_p99_ms": [0-9.]*' "$dir/livescale.json" | grep -o '[0-9.]*$')ms vs" \
       "$(grep -o '"livescale_gang_ttft_p99_ms": [0-9.]*' "$dir/livescale.json" | grep -o '[0-9.]*$')ms, zero drops)"
  exit 0
fi

if [ "${1:-}" = "--resilience" ]; then
  # Preemption smoke, four runs: (1) SIGTERM at step 5 → exit 215 +
  # emergency step_5; (2) resume → stop at step 8, exit 0; (3) hard
  # death (die-at-step:11) → exit 217, NO checkpoint; (4) resume from
  # step_8 → stop at step 12. The collector CLI plays the controller
  # between runs (gang_restart records), then merges controller+worker
  # logs into ONE timeline.jsonl and renders the federated goodput
  # ledger — the controller-eye view of a preempted gang, end to end.
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  run_env=(env JAX_PLATFORMS=cpu
           TPU_COORDINATOR_ADDRESS=localhost:8476 TPU_NUM_PROCESSES=1)
  args=(python -m mpi_operator_tpu.examples.lm_benchmark
        --workload gpt2 --size test --batch-per-device 1 --seq-len 16
        --dtype float32 --warmup-steps 1 --num-steps 20
        --train-dir "$dir/ckpt")
  emit=("${run_env[@]}" python -m mpi_operator_tpu.telemetry.collector
        emit --log "$dir/controller.jsonl" --job smoke)
  "${emit[@]}" job_created tpus=8 || exit 1
  echo "== resilience smoke: preempt at step 5 =="
  "${run_env[@]}" TPU_FAULT_INJECT=sigterm-at-step:5 \
    "${args[@]}" > "$dir/preempt.log" 2>&1
  rc=$?
  if [ "$rc" -ne 215 ]; then
    echo "FAIL: preempted run exited $rc (want 215, the retryable band)"
    tail -20 "$dir/preempt.log"; exit 1
  fi
  if [ ! -d "$dir/ckpt/step_5" ]; then
    echo "FAIL: no emergency checkpoint at step_5"; ls "$dir/ckpt"; exit 1
  fi
  # the structured event log (telemetry/events.py, default path
  # <train-dir>/events.jsonl) must carry the drain sequence, fsync'd
  # BEFORE exit(215) — the durability contract a postmortem relies on
  if ! grep -q '"event": "preemption_drain"' "$dir/ckpt/events.jsonl"; then
    echo "FAIL: no preemption_drain record in the event log"
    cat "$dir/ckpt/events.jsonl" 2>/dev/null; exit 1
  fi
  if ! grep -q '"event": "emergency_checkpoint"' "$dir/ckpt/events.jsonl"; then
    echo "FAIL: no emergency_checkpoint record in the event log"
    cat "$dir/ckpt/events.jsonl" 2>/dev/null; exit 1
  fi
  # play the controller's role: record the restart in the controller-
  # side log the merge below folds into the job timeline
  "${emit[@]}" gang_restart exit_code=215 restart=1 || exit 1
  echo "== resilience smoke: resume to step 8 =="
  "${run_env[@]}" "${args[@]}" --num-steps 20 --stop-at-step 8 \
    > "$dir/resume.log" 2>&1
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: resumed run exited $rc"; tail -20 "$dir/resume.log"; exit 1
  fi
  if ! grep -q "resumed from .*step_5" "$dir/resume.log"; then
    echo "FAIL: resumed run did not restore the emergency checkpoint"
    tail -20 "$dir/resume.log"; exit 1
  fi
  if [ ! -d "$dir/ckpt/step_8" ]; then
    echo "FAIL: resumed run did not reach global step 8"
    ls "$dir/ckpt"; exit 1
  fi
  # Hard-death leg: the injector die()s at step 11 — os._exit(217), NO
  # emergency checkpoint — so the resume must fall back to step_8 and
  # RE-EXECUTE steps 9-11. That re-execution is exactly what the
  # restart-aware goodput ledger charges as lost steps: the durable
  # fault_injected record (fsync'd before _exit) is the only surviving
  # evidence of the pre-death step frontier.
  echo "== resilience smoke: hard death at step 11 =="
  "${run_env[@]}" TPU_FAULT_INJECT=die-at-step:11 \
    "${args[@]}" > "$dir/die.log" 2>&1
  rc=$?
  if [ "$rc" -ne 217 ]; then
    echo "FAIL: fault-injected run exited $rc (want 217)"
    tail -20 "$dir/die.log"; exit 1
  fi
  if [ -d "$dir/ckpt/step_11" ]; then
    echo "FAIL: hard death must NOT leave a step_11 checkpoint"; exit 1
  fi
  if ! grep -q '"event": "fault_injected"' "$dir/ckpt/events.jsonl"; then
    echo "FAIL: no durable fault_injected record (the step frontier is lost)"
    exit 1
  fi
  "${emit[@]}" gang_restart exit_code=217 restart=2 || exit 1
  echo "== resilience smoke: resume to step 12 =="
  "${run_env[@]}" "${args[@]}" --num-steps 20 --stop-at-step 12 \
    > "$dir/resume2.log" 2>&1
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: second resume exited $rc"; tail -20 "$dir/resume2.log"; exit 1
  fi
  if [ ! -d "$dir/ckpt/step_12" ]; then
    echo "FAIL: second resume did not reach global step 12"
    ls "$dir/ckpt"; exit 1
  fi
  "${emit[@]}" job_succeeded || exit 1
  # Merge controller + worker logs into the job timeline and render the
  # federated goodput series — the same code path the operator's
  # /metrics uses (telemetry/collector.py goodput_ledger).
  echo "== resilience smoke: merged timeline + goodput ledger =="
  "${run_env[@]}" python -m mpi_operator_tpu.telemetry.collector merge \
    --job smoke --controller "$dir/controller.jsonl" \
    --worker "worker-0=$dir/ckpt/events.jsonl" \
    --out "$dir/timeline.jsonl" --metrics-out "$dir/federated.prom" \
    > "$dir/merge.json" || { echo "FAIL: timeline merge"; exit 1; }
  if [ ! -s "$dir/timeline.jsonl" ]; then
    echo "FAIL: no merged timeline.jsonl"; exit 1
  fi
  # ts-order interleave: the worker's drain records must land BEFORE the
  # controller's first gang_restart in the merged file (the controller
  # only learns of the exit after the worker drained)
  drain_line=$(grep -n '"event": "preemption_drain"' "$dir/timeline.jsonl" | head -1 | cut -d: -f1)
  ckpt_line=$(grep -n '"event": "emergency_checkpoint"' "$dir/timeline.jsonl" | head -1 | cut -d: -f1)
  restart_line=$(grep -n '"event": "gang_restart"' "$dir/timeline.jsonl" | head -1 | cut -d: -f1)
  if [ -z "$drain_line" ] || [ -z "$ckpt_line" ] || [ -z "$restart_line" ]; then
    echo "FAIL: merged timeline is missing drain/checkpoint/restart records"
    cat "$dir/timeline.jsonl"; exit 1
  fi
  if [ "$drain_line" -ge "$restart_line" ] || [ "$ckpt_line" -ge "$restart_line" ]; then
    echo "FAIL: timeline not in ts order (drain=$drain_line ckpt=$ckpt_line restart=$restart_line)"
    cat "$dir/timeline.jsonl"; exit 1
  fi
  # ledger arithmetic, checkable by hand from the timeline: the hard
  # death at step 11 forced a resume from step_8 — steps 9-11 re-ran, so
  # lost=3; the run finished at step 12, so useful=12 and
  # goodput = 12/(12+3) = 0.8. The clean drain (restore step == drain
  # step) contributes NOTHING — that's the point of the ledger.
  if ! grep -Eq 'tpu_job_steps_lost_total\{job="smoke"\} 3$' "$dir/federated.prom"; then
    echo "FAIL: federated steps_lost != 3"; cat "$dir/federated.prom"; exit 1
  fi
  if ! grep -Eq 'tpu_job_goodput\{job="smoke"\} 0\.8$' "$dir/federated.prom"; then
    echo "FAIL: federated goodput != 0.8"; cat "$dir/federated.prom"; exit 1
  fi
  # the postmortem CLI must render the timeline (exit 0) and refuse an
  # empty one (nonzero — the "did the run leave a usable postmortem"
  # one-liner)
  "${run_env[@]}" python -m mpi_operator_tpu.postmortem "$dir/timeline.jsonl" \
    > "$dir/postmortem.txt" \
    || { echo "FAIL: postmortem CLI on a real timeline"; exit 1; }
  : > "$dir/empty.jsonl"
  if "${run_env[@]}" python -m mpi_operator_tpu.postmortem "$dir/empty.jsonl" \
      > /dev/null 2>&1; then
    echo "FAIL: postmortem CLI must exit nonzero on an empty timeline"
    exit 1
  fi
  echo "resilience smoke: OK (215 -> step_5 -> resume 8 -> 217 -> resume 12; timeline + goodput 0.8, lost 3)"
  exit 0
fi

#   ./scripts/tier1.sh --chaos runs the OUT-OF-PROCESS chaos soak as a
#   TWO-SEED matrix (the given seed, default 42, plus seed+1000 — two
#   independent fault/kill schedules, so a schedule-shaped bug can't
#   hide behind one lucky seed): 25 mixed job lifecycles
#   (create/restart/resize/pack/serving/teardown) against seeded API
#   fault injection (transient writes, status conflicts, stale reads,
#   dropped watch events) with the controller killed at EVERY write
#   boundary, gated on oracle convergence, zero leaked resources, and
#   zero wedged workqueue keys — PLUS the data-plane legs: scrape
#   faults (one rank hard-dark, the rest flaky) must produce a
#   DegradedGang window and ZERO restarts; a wedged serving gang must
#   be caught via the frozen token frontier within
#   progressDeadlineSeconds; request timeouts must leak zero slots and
#   zero KV pages; bursty (time-varying) scrape faults must neither trip
#   nor disarm the serving lease; a mid-trace replica kill behind
#   the router must lose zero requests; and the same kill under a
#   sample=1.0 tracer must leave every request's span tree complete
#   (zero orphans, failovers folded into their roots) — PLUS the
#   fleet-scheduler legs:
#   the priority rebalance (preempt -> admit -> grow-back) must converge
#   under crash-at-every-write with zero double-shrinks and zero lost
#   admissions, the anti-thrash gate must record an explicit sched_skip
#   instead of a resize, and the degraded-rank migration must fire at
#   most ONCE per degraded window with zero gang restarts burned.
#   Deterministic per seed; each seed's reproducer line is printed on
#   failure (and a deliberately-failing run per seed proves it).

if [ "${1:-}" = "--chaos" ]; then
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  seed="${2:-42}"
  for s in "$seed" "$((seed + 1000))"; do
  echo "== chaos soak: 25 fault-injected, crash-interrupted lifecycles + data plane + scheduler (seed $s) =="
  timeout -k 10 1200 env JAX_PLATFORMS=cpu \
    python -m mpi_operator_tpu.controller.chaos \
    --seed "$s" --lifecycles 25 \
    > "$dir/chaos-$s.json" 2> "$dir/chaos-$s.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: chaos soak exited $rc (reproduce: python -m" \
         "mpi_operator_tpu.controller.chaos --seed $s --lifecycles 25)"
    tail -30 "$dir/chaos-$s.log"; cat "$dir/chaos-$s.json" 2>/dev/null
    exit 1
  fi
  if ! grep -q '"completed": 25' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s soak did not complete all 25 lifecycles"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"crashes": 0,' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: zero injected crashes — the kill schedule never ran"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"total_faults": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: zero injected faults — the fault rules never fired"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # data-plane gates: the degraded window opened and healed with no
  # false-positive restart, the wedged serving gang was caught via the
  # token frontier, and request timeouts reclaimed every slot and page
  if ! grep -q '"false_positive_restarts": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: scrape flakiness restarted a gang (or the degraded leg never ran)"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"degraded_windows": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"degraded_windows":' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: no DegradedGang window under the partial partition"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"scrape_faults_injected": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: zero injected scrape faults — the data-plane rules never fired"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if ! grep -q '"serving_stalls_detected": 1' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: wedged serving gang not detected via the token frontier"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if ! grep -q '"leaked_pages": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"leaked_slots": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: request timeouts leaked slots or KV pages"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"request_timeouts": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"request_timeouts":' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the request-timeout leg retired nothing"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # bursty scrape faults must oscillate without a false-positive restart
  # and still catch the real post-burst stall (lease re-armed)
  if ! grep -q '"burst_false_positive_restarts": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"burst_real_stall_detected": 1' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the bursty-scrape leg tripped the lease (or never ran)"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # the router must survive a mid-trace replica kill with zero lost
  # requests (resubmits to survivors, token-identical replays)
  if ! grep -q '"router_failover_lost": 0' "$dir/chaos-$s.json" \
      || grep -q '"router_resubmitted": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the router-failover leg lost or never resubmitted requests"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # trace completeness under the same kill: every request (shed and
  # failed-over alike) must reconstruct as ONE rooted span tree with
  # zero orphaned spans, the failover riding as an event inside the
  # surviving root, and hop sums within tolerance of the root e2e
  if ! grep -q '"trace_complete_orphans": 0' "$dir/chaos-$s.json" \
      || grep -q '"trace_complete_requests": 0' "$dir/chaos-$s.json" \
      || grep -q '"trace_complete_failover_roots": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the trace-completeness leg orphaned spans or never ran"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # live decode-pool scaling under burst scrape faults with the
  # controller crashed at the scalingReplica marker: replay must not
  # double-apply the step (exactly 2 ledger records, zero duplicate
  # tokens, zero gang entries), and the engine-level attach/drain cycle
  # must lose nothing and reclaim every page
  if ! grep -q '"live_scale_marker_crashes": 2' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_ledger_records": 2' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_double_records": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_gang_entries": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: live-scale marker replay double-applied, gang-restarted, or never ran"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if ! grep -q '"live_scale_lost": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_shed": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_token_mismatches": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"live_scale_leaked_pages": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the live attach/drain cycle lost requests, diverged tokens, or leaked pages"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # fleet-scheduler gates: the rebalance converged crash-consistently
  # (no double-shrink, no lost admission, no leak), the anti-thrash
  # cost gate recorded an explicit skip instead of a resize, and the
  # degraded-rank migration fired exactly once per window with zero
  # gang restarts burned
  if ! grep -q '"sched_double_shrinks": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_admissions_lost": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_leaked": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the scheduler rebalance double-shrank, lost an admission, or leaked"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"sched_preempts": 0' "$dir/chaos-$s.json" \
      || grep -q '"sched_grow_backs": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the scheduler leg never preempted or never grew back"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if grep -q '"sched_skips_recorded": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_thrash_resizes": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: the anti-thrash gate resized instead of recording sched_skip"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  if ! grep -q '"sched_migrations": 1' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_migrations_per_window_max": 1' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_migration_restarts": 0' "$dir/chaos-$s.json" \
      || ! grep -q '"sched_restarts_burned": 0' "$dir/chaos-$s.json"; then
    echo "FAIL: seed $s: degraded-rank migration missing, repeated, or burned a restart"
    cat "$dir/chaos-$s.json"; exit 1
  fi
  # failure discipline: a soak that DOES fail must print THIS seed's
  # reproducer. Every rank dark turns the degraded leg's partition
  # total, which must trip its zero-false-positive assertion — expected
  # exit 1 with the seed named on stderr.
  echo "== chaos soak: reproducer-seed discipline (deliberate failure, seed $s) =="
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m mpi_operator_tpu.controller.chaos \
      --seed "$s" --lifecycles 0 --scrape-faults '*/fail=1' \
      > "$dir/fail-$s.json" 2> "$dir/fail-$s.log"; then
    echo "FAIL: seed $s: all-ranks-dark soak was expected to fail and did not"
    cat "$dir/fail-$s.json"; exit 1
  fi
  if ! grep -q "CHAOS SOAK FAILED" "$dir/fail-$s.log" \
      || ! grep -q "seed=$s" "$dir/fail-$s.log" \
      || ! grep -q "^reproduce: python -m mpi_operator_tpu.controller.chaos" "$dir/fail-$s.log"; then
    echo "FAIL: seed $s: failing soak did not print the reproducer seed line"
    cat "$dir/fail-$s.log"; exit 1
  fi
  echo "chaos soak seed $s: OK ($(grep -o '"crashes": [0-9]*' "$dir/chaos-$s.json" | grep -o '[0-9]*') crashes," \
       "$(grep -o '"total_faults": [0-9]*' "$dir/chaos-$s.json" | grep -o '[0-9]*') API faults," \
       "$(grep -o '"scrape_faults_injected": [0-9]*' "$dir/chaos-$s.json" | grep -o '[0-9]*$') scrape faults," \
       "$(grep -o '"sched_preempts": [0-9]*' "$dir/chaos-$s.json" | grep -o '[0-9]*$') preempts)"
  done
  echo "chaos soak: OK (2-seed matrix $seed + $((seed + 1000)): lifecycles converged, degraded windows healed, scheduler crash-consistent, zero leaks)"
  exit 0
fi

if [ "${1:-}" = "--elastic" ]; then
  # Elastic gang-resize smoke (examples/elastic_benchmark.py): three
  # subprocess phases of ONE run — 4 devices, SIGTERM at step 5, exit
  # 215 -> gang_resize -> 2 devices resuming the dp=4 checkpoint via
  # the resharding reader, SIGTERM at step 10 -> gang_resize -> 4
  # devices to step 14, exit 0 — plus a straight-through oracle. The
  # orchestrator itself gates phase exit codes, 2 completed resizes
  # with drain/restore/recompile splits, and oracle loss parity; the
  # greps below re-check the contracts from the artifacts.
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  echo "== elastic smoke: 4 -> 2 -> 4 gang resize =="
  timeout -k 10 1200 env JAX_PLATFORMS=cpu \
    python -m mpi_operator_tpu.examples.elastic_benchmark \
    --out-dir "$dir" > "$dir/elastic.json" 2> "$dir/elastic.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: elastic benchmark exited $rc"
    tail -30 "$dir/elastic.log"; cat "$dir/elastic.json" 2>/dev/null
    exit 1
  fi
  if ! grep -q '"elastic_token_identical": true' "$dir/elastic.json"; then
    echo "FAIL: resumed loss differs from the straight-through oracle"
    cat "$dir/elastic.json"; exit 1
  fi
  if ! grep -q '"resharded_restores": 2' "$dir/elastic.json"; then
    echo "FAIL: a resume went through the cold path, not the resharding reader"
    cat "$dir/elastic.json"; exit 1
  fi
  if [ "$(grep -c '"event": "gang_resize"' "$dir/timeline.jsonl")" -ne 2 ]; then
    echo "FAIL: merged timeline does not carry both gang_resize records"
    cat "$dir/timeline.jsonl"; exit 1
  fi
  # the worker-side restore must log its wall time + leaf count
  if ! grep -Eq 'INFO: restored .* in [0-9.]+s \([0-9]+ leaves\)' \
      "$dir"/phase1.log; then
    echo "FAIL: no restore INFO line (wall time + leaf count) in phase 1"
    tail -20 "$dir/phase1.log"; exit 1
  fi
  if ! grep -q 'tpu_job_resize_seconds_count{job="elastic"} 2' \
      "$dir/federated.prom"; then
    echo "FAIL: resize_seconds histogram missing both resizes"
    cat "$dir/federated.prom"; exit 1
  fi
  if grep -Eq 'tpu_job_goodput\{job="elastic"\} 0(\.0+)?$' \
      "$dir/federated.prom"; then
    echo "FAIL: zero federated goodput across the resizes"
    cat "$dir/federated.prom"; exit 1
  fi
  # the postmortem renders the resize phase split + the auto-cadence hint
  env JAX_PLATFORMS=cpu python -m mpi_operator_tpu.postmortem \
    "$dir/timeline.jsonl" > "$dir/postmortem.txt" \
    || { echo "FAIL: postmortem CLI on the elastic timeline"; exit 1; }
  if ! grep -q 'gang resizes:' "$dir/postmortem.txt"; then
    echo "FAIL: postmortem does not render the gang-resize section"
    cat "$dir/postmortem.txt"; exit 1
  fi
  if ! grep -q 'suggested --stop-check-every' "$dir/postmortem.txt"; then
    echo "FAIL: postmortem missing the stop-check-every suggestion"
    cat "$dir/postmortem.txt"; exit 1
  fi
  echo "elastic smoke: OK ($(grep -o '"resize_seconds": \[[^]]*\]' "$dir/elastic.json"); token-identical, goodput intact)"
  exit 0
fi

if [ "${1:-}" = "--sched" ]; then
  # Fleet-scheduler smoke (examples/sched_benchmark.py): two competing
  # jobs on a fake 4-device pool, every decision made by the REAL
  # FleetScheduler policy object — lo (priority 0, elastic, 4 devices)
  # is preempted 4 -> 2 to admit hi (priority 1, 2 devices), hi runs
  # solo to completion, lo grows back to 4 and finishes. The
  # orchestrator itself gates phase exit codes, both plan decisions,
  # 2 completed resizes, and solo-oracle loss parity for BOTH jobs;
  # the greps below re-check the contracts from the artifacts.
  set -u
  dir=$(mktemp -d)
  trap 'rm -rf "$dir"' EXIT
  echo "== sched smoke: preempt-to-admit + grow-back on a 4-device pool =="
  timeout -k 10 1200 env JAX_PLATFORMS=cpu \
    python -m mpi_operator_tpu.examples.sched_benchmark \
    --out-dir "$dir" > "$dir/sched.json" 2> "$dir/sched.log"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "FAIL: sched benchmark exited $rc"
    tail -30 "$dir/sched.log"; cat "$dir/sched.json" 2>/dev/null
    exit 1
  fi
  # the scheduler may cost a job TIME, never data: both final losses
  # must be token-identical to uninterrupted solo runs
  if ! grep -q '"lo_token_identical": true' "$dir/sched.json"; then
    echo "FAIL: preempted job's loss differs from its solo oracle"
    cat "$dir/sched.json"; exit 1
  fi
  if ! grep -q '"hi_token_identical": true' "$dir/sched.json"; then
    echo "FAIL: admitted job's loss differs from its solo oracle"
    cat "$dir/sched.json"; exit 1
  fi
  if ! grep -q '"action": "preempt"' "$dir/sched.json" \
      || ! grep -q '"action": "grow_back"' "$dir/sched.json"; then
    echo "FAIL: the policy object did not decide preempt then grow_back"
    cat "$dir/sched.json"; exit 1
  fi
  # the merged timeline carries the decision records (shrink + grow)
  for evt in sched_queue sched_preempt sched_admit sched_grow_back; do
    if ! grep -q "\"event\": \"$evt\"" "$dir/timeline.jsonl"; then
      echo "FAIL: merged timeline is missing the $evt record"
      cat "$dir/timeline.jsonl"; exit 1
    fi
  done
  if [ "$(grep -c '"event": "gang_resize"' "$dir/timeline.jsonl")" -ne 2 ]; then
    echo "FAIL: merged timeline does not carry both gang_resize records"
    cat "$dir/timeline.jsonl"; exit 1
  fi
  # the postmortem tells the scheduler's story, with the preempt's
  # predicted cost paired against the measured resize total
  if ! grep -q 'scheduler actions:' "$dir/postmortem.txt"; then
    echo "FAIL: postmortem does not render the scheduler-actions section"
    cat "$dir/postmortem.txt"; exit 1
  fi
  if ! grep -q 'preempt .*victim .*beneficiary .*measured' "$dir/postmortem.txt" \
      || ! grep -q 'grow back' "$dir/postmortem.txt"; then
    echo "FAIL: postmortem scheduler section missing the preempt/grow-back lines"
    cat "$dir/postmortem.txt"; exit 1
  fi
  echo "sched smoke: OK ($(grep -o '"resize_seconds": \[[^]]*\]' "$dir/sched.json"); both jobs token-identical, scheduler actions rendered)"
  exit 0
fi

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 3000 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --durations=15 --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
