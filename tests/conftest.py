"""Test configuration.

Data-plane tests simulate multi-worker collectives on 8 virtual CPU devices
(the reference tests multi-node declaratively with fake clientsets,
SURVEY.md §4; we additionally own a data plane, so we use
--xla_force_host_platform_device_count to exercise real XLA collectives
without TPUs).

NOTE: this environment ships an `axon` sitecustomize (PYTHONPATH) that
forces the TPU platform regardless of JAX_PLATFORMS; overriding via
jax.config BEFORE any backend is initialized is the reliable channel.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_operator_tpu.utils.hostplatform import force_host_platform  # noqa: E402

force_host_platform(8)

# debug builds pay for the O(num_pages) PageAllocator.check() audit on
# every engine reset(); production resets skip it (serve/engine.py)
os.environ.setdefault("TPU_DEBUG_PAGES", "1")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_checkpoint_saved_state():
    """Clear checkpoint.py's per-directory last-saved records between
    tests: tmp_path reuse across back-to-back in-process runs would
    otherwise make maybe_save skip a save the second test legitimately
    needs. sys.modules.get, not an import — tests that never touch
    checkpoints must not pay the jax/orbax import."""
    yield
    mod = sys.modules.get("mpi_operator_tpu.train.checkpoint")
    if mod is not None:
        mod.reset_saved_state()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second compile variants, excluded from the tier-1 "
        "gate (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "multichip: needs >1 device to be meaningful (tp/ring/pp meshes); "
        "satisfied here by the 8 virtual CPU devices, but deselect with "
        "-m 'not multichip' on a single real chip without the virtual "
        "mesh")
    config.addinivalue_line(
        "markers",
        "serving: continuous-batching engine tests (serve/); select with "
        "-m serving to gate the serving surface alone")
    config.addinivalue_line(
        "markers",
        "hfta: horizontally fused trainer tests (train/hfta.py); select "
        "with -m hfta to gate the job-packing data plane alone")
    config.addinivalue_line(
        "markers",
        "spec: speculative-decoding tests (multi-token verify, drafting, "
        "rewind); select with -m spec to gate the speculation surface "
        "alone")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection / crash-consistency soak tests "
        "(controller/chaos.py harness); select with -m chaos, or run the "
        "longer out-of-process soak via scripts/tier1.sh --chaos")
