"""In-process fake Kubernetes API server (plain HTTP) for kubeclient tests.

Plays the role the reference's generated fake clientset plays in its tests
(mpi_job_controller_test.go:145-146) — but at the WIRE level: the real
`KubeAPIServer` adapter speaks actual HTTP/JSON to this server, so tests pin
the exact manifests the operator would send a real cluster (paths, verbs,
camelCase bodies), not just in-process method calls.

Implemented subset (what the adapter uses):
  POST   /api|apis/.../namespaces/{ns}/{plural}            create
  GET    .../{plural}                                      list
  GET    .../{plural}?watch=true&resourceVersion=N         watch (streaming)
  GET    .../{plural}/{name}                               get
  PUT    .../{plural}/{name}                               update
  PUT    .../{plural}/{name}/status                        update status only
  DELETE .../{plural}/{name}                               delete
Plus: monotonic string resourceVersions, uid assignment, 404/409 Status
bodies, watch resume from a resourceVersion with 410 Gone on expiry, and a
request log (`server.requests`) for wire-format assertions.
"""
from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

# path: /api/v1/... or /apis/group/version/...
_PATH = re.compile(
    r"^/(?:api/(?P<corev>v1)|apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<sub>status))?$"
)


@dataclass
class LoggedRequest:
    method: str
    path: str
    body: Optional[dict] = None


@dataclass
class _State:
    # (plural, ns, name) -> manifest
    store: Dict[Tuple[str, str, str], dict] = field(default_factory=dict)
    rv: int = 0
    uid: int = 0
    # retained event log for watch resume: (rv, plural, type, manifest)
    events: List[Tuple[int, str, str, dict]] = field(default_factory=list)
    cond: threading.Condition = field(default_factory=threading.Condition)


class FakeKubeAPIServer:
    """Lifecycle wrapper: start()/stop() an HTTP server on an ephemeral
    localhost port; expose `url`, the object `store`, and the `requests`
    log."""

    def __init__(self):
        self.state = _State()
        self.requests: List[LoggedRequest] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.url = ""

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FakeKubeAPIServer":
        state, requests = self.state, self.requests

        class Handler(_Handler):
            pass

        Handler.state = state
        Handler.requests = requests
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-kube", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # wake any parked watch handlers so their threads exit
        with self.state.cond:
            self.state.cond.notify_all()

    # -- test-side mutation helpers (play kubelet) --------------------------

    def set_status(self, plural: str, ns: str, name: str,
                   status: dict) -> None:
        """Merge a status in as a kubelet/controller-manager would."""
        st = self.state
        with st.cond:
            obj = st.store[(plural, ns, name)]
            obj.setdefault("status", {}).update(status)
            st.rv += 1
            obj["metadata"]["resourceVersion"] = str(st.rv)
            st.events.append((st.rv, plural, "MODIFIED",
                              json.loads(json.dumps(obj))))
            st.cond.notify_all()

    def get_object(self, plural: str, ns: str, name: str) -> Optional[dict]:
        with self.state.cond:
            obj = self.state.store.get((plural, ns, name))
            return json.loads(json.dumps(obj)) if obj else None

    def objects_of(self, plural: str) -> List[dict]:
        with self.state.cond:
            return [json.loads(json.dumps(o))
                    for (p, _, _), o in sorted(self.state.store.items())
                    if p == plural]

    def requests_of(self, method: str, plural: str) -> List[LoggedRequest]:
        return [r for r in self.requests
                if r.method == method and f"/{plural}" in r.path]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: _State = None        # set by FakeKubeAPIServer.start
    requests: List[LoggedRequest] = None

    def log_message(self, *a):   # silence
        pass

    # -- helpers ------------------------------------------------------------

    def _send_json(self, code: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _status(self, code: int, reason: str, message: str) -> None:
        self._send_json(code, {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code})

    def _read_body(self) -> Optional[dict]:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return None
        return json.loads(self.rfile.read(length))

    def _route(self):
        parsed = urlparse(self.path)
        m = _PATH.match(parsed.path)
        if not m:
            self._status(404, "NotFound", f"no route {parsed.path}")
            return None
        return m.groupdict(), parse_qs(parsed.query)

    # -- verbs --------------------------------------------------------------

    def do_POST(self):
        routed = self._route()
        if not routed:
            return
        g, _ = routed
        body = self._read_body()
        self.requests.append(LoggedRequest("POST", self.path, body))
        st = self.state
        ns = g["ns"] or "default"
        name = (body.get("metadata") or {}).get("name", "")
        key = (g["plural"], ns, name)
        with st.cond:
            if key in st.store:
                self._status(409, "AlreadyExists",
                             f"{g['plural']} {name!r} already exists")
                return
            st.rv += 1
            st.uid += 1
            meta = body.setdefault("metadata", {})
            meta["namespace"] = ns
            meta["resourceVersion"] = str(st.rv)
            meta.setdefault("uid", f"uid-{st.uid}")
            st.store[key] = body
            st.events.append((st.rv, g["plural"], "ADDED",
                              json.loads(json.dumps(body))))
            st.cond.notify_all()
            self._send_json(201, body)

    def do_GET(self):
        routed = self._route()
        if not routed:
            return
        g, q = routed
        self.requests.append(LoggedRequest("GET", self.path))
        st = self.state
        if g["name"]:
            with st.cond:
                obj = st.store.get((g["plural"], g["ns"] or "default",
                                    g["name"]))
            if obj is None:
                self._status(404, "NotFound", f"{g['name']!r} not found")
            else:
                self._send_json(200, obj)
            return
        if q.get("watch", ["false"])[0] == "true":
            self._watch(g, q)
            return
        selector = {}
        for clause in q.get("labelSelector", [""])[0].split(","):
            if "=" in clause:
                k, _, v = clause.partition("=")
                selector[k] = v
        with st.cond:
            items = [o for (p, ns, _), o in sorted(st.store.items())
                     if p == g["plural"]
                     and (g["ns"] is None or ns == g["ns"])
                     and all((o["metadata"].get("labels") or {})
                             .get(k) == v for k, v in selector.items())]
            rv = st.rv
        self._send_json(200, {
            "kind": "List", "apiVersion": "v1",
            "metadata": {"resourceVersion": str(rv)},
            "items": items})

    def do_PUT(self):
        routed = self._route()
        if not routed:
            return
        g, _ = routed
        body = self._read_body()
        self.requests.append(LoggedRequest("PUT", self.path, body))
        st = self.state
        key = (g["plural"], g["ns"] or "default", g["name"])
        with st.cond:
            old = st.store.get(key)
            if old is None:
                self._status(404, "NotFound", f"{g['name']!r} not found")
                return
            sent_rv = (body.get("metadata") or {}).get("resourceVersion")
            if sent_rv and sent_rv != old["metadata"]["resourceVersion"]:
                # optimistic concurrency, like a real apiserver: a stale
                # resourceVersion is rejected, the client must re-read
                self._status(409, "Conflict",
                             f"resourceVersion {sent_rv} is stale")
                return
            st.rv += 1
            if g["sub"] == "status":
                # status subresource: only .status changes
                new = json.loads(json.dumps(old))
                new["status"] = body.get("status", {})
            else:
                new = body
                # real servers with the status subresource enabled (the
                # TPUJob CRD, and all built-in workload kinds) STRIP .status
                # from plain PUTs — the old status is preserved verbatim
                if "status" in old:
                    new["status"] = old["status"]
                else:
                    new.pop("status", None)
                new["metadata"] = {**old["metadata"],
                                   **(body.get("metadata") or {})}
                new["metadata"]["uid"] = old["metadata"]["uid"]
            new["metadata"]["resourceVersion"] = str(st.rv)
            st.store[key] = new
            st.events.append((st.rv, g["plural"], "MODIFIED",
                              json.loads(json.dumps(new))))
            st.cond.notify_all()
            self._send_json(200, new)

    def do_DELETE(self):
        routed = self._route()
        if not routed:
            return
        g, _ = routed
        self.requests.append(LoggedRequest("DELETE", self.path))
        st = self.state
        key = (g["plural"], g["ns"] or "default", g["name"])
        with st.cond:
            obj = st.store.pop(key, None)
            if obj is None:
                self._status(404, "NotFound", f"{g['name']!r} not found")
                return
            st.rv += 1
            st.events.append((st.rv, g["plural"], "DELETED",
                              json.loads(json.dumps(obj))))
            st.cond.notify_all()
            self._send_json(200, {"kind": "Status", "status": "Success"})

    # -- watch streaming ----------------------------------------------------

    def _watch(self, g, q):
        st = self.state
        since = int(q.get("resourceVersion", ["0"])[0] or 0)
        timeout = float(q.get("timeoutSeconds", ["5"])[0])
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(event_type: str, obj: dict) -> bool:
            try:
                line = json.dumps({"type": event_type,
                                   "object": obj}).encode() + b"\n"
                self.wfile.write(f"{len(line):x}\r\n".encode()
                                 + line + b"\r\n")
                self.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        deadline = time.monotonic() + timeout
        cursor = since
        while time.monotonic() < deadline:
            with st.cond:
                pending = [
                    (rv, etype, obj) for rv, plural, etype, obj in st.events
                    if rv > cursor and plural == g["plural"]
                    and (g["ns"] is None
                         or obj["metadata"].get("namespace") == g["ns"])]
                if not pending:
                    st.cond.wait(timeout=min(
                        0.2, max(0.01, deadline - time.monotonic())))
            for rv, etype, obj in pending:
                cursor = rv
                if not emit(etype, obj):
                    return
        try:
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass
