"""API type + validation tests (the reference enforces these via the CRD's
openAPIV3 schema, deploy/0-crd.yaml:16-99; SURVEY.md §2.1)."""
import pytest

from mpi_operator_tpu.api.types import (
    COND_FAILED, COND_RUNNING, COND_SUCCEEDED, JobCondition, ObjectMeta,
    OwnerReference, TPUJobSpec, TPUJobStatus, is_controlled_by, new_tpu_job,
)
from mpi_operator_tpu.api.validation import (
    ValidationError, default_topology, validate_spec,
)


def test_exactly_one_sizing_mode_required():
    with pytest.raises(ValidationError, match="exactly one"):
        validate_spec(TPUJobSpec())
    with pytest.raises(ValidationError, match="mutually exclusive"):
        validate_spec(TPUJobSpec(tpus=8, replicas=2))
    with pytest.raises(ValidationError, match="mutually exclusive"):
        validate_spec(TPUJobSpec(tpus=8, processing_units=8))


@pytest.mark.parametrize("n", [1, 2, 4, 8, 16, 32, 64, 128, 256])
def test_valid_slice_chip_counts(n):
    validate_spec(TPUJobSpec(tpus=n))


@pytest.mark.parametrize("n", [3, 5, 6, 7, 12, 24, 48, 100])
def test_invalid_slice_chip_counts(n):
    """Invalid shapes fail at admission, not at runtime (SURVEY §7)."""
    with pytest.raises(ValidationError, match="slice chip count"):
        validate_spec(TPUJobSpec(tpus=n))


def test_topology_must_match_chip_count():
    validate_spec(TPUJobSpec(tpus=32, slice_topology="4x8"))
    with pytest.raises(ValidationError, match="does not match"):
        validate_spec(TPUJobSpec(tpus=32, slice_topology="4x4"))


def test_default_topology():
    assert default_topology(32) == "4x8"
    assert default_topology(4) == "2x2"
    with pytest.raises(ValidationError):
        default_topology(13)


def test_resource_type_restricted():
    """ref cmd/mpi-operator/main.go:108-110."""
    with pytest.raises(ValidationError, match="processingResourceType"):
        validate_spec(TPUJobSpec(tpus=8, processing_resource_type="nvidia.com/gpu"))


def test_clean_pod_policy_restricted():
    with pytest.raises(ValidationError, match="cleanPodPolicy"):
        validate_spec(TPUJobSpec(tpus=8, clean_pod_policy="Sometimes"))


def test_backoff_and_deadline_bounds():
    with pytest.raises(ValidationError, match="backoffLimit"):
        validate_spec(TPUJobSpec(tpus=8, backoff_limit=-1))
    with pytest.raises(ValidationError, match="activeDeadlineSeconds"):
        validate_spec(TPUJobSpec(tpus=8, active_deadline_seconds=0))


def test_is_controlled_by():
    owner = new_tpu_job("job1")
    owner.metadata.uid = "u1"
    child = ObjectMeta(
        name="c", owner_references=[owner.controller_owner_reference()]
    )
    assert is_controlled_by(child, owner.metadata)
    other = ObjectMeta(name="c", owner_references=[OwnerReference(
        api_version="v1", kind="TPUJob", name="job1", uid="u2")])
    assert not is_controlled_by(other, owner.metadata)


def test_conditions_model():
    """v1alpha2 condition semantics (ref common_types.go:101-127)."""
    st = TPUJobStatus()
    st.set_condition(JobCondition(COND_RUNNING, "True"))
    assert not st.is_done()
    st.set_condition(JobCondition(COND_SUCCEEDED, "True"))
    assert st.is_done()
    # terminal condition flips Running to False
    assert st.get_condition(COND_RUNNING).status == "False"
    # last-writer-wins per type: no duplicates
    st.set_condition(JobCondition(COND_SUCCEEDED, "True"))
    assert sum(1 for c in st.conditions if c.type == COND_SUCCEEDED) == 1


def test_condition_transition_time_stable_when_unchanged():
    st = TPUJobStatus()
    st.set_condition(JobCondition(COND_RUNNING, "True", reason="r"))
    t0 = st.get_condition(COND_RUNNING).last_transition_time
    st.set_condition(JobCondition(COND_RUNNING, "True", reason="r"))
    assert st.get_condition(COND_RUNNING).last_transition_time == t0


def test_example_manifests_validate():
    """Every shipped examples/*.yaml must deserialize into a TPUJob whose
    spec passes admission validation (the reference's examples are its
    primary user documentation; shipping an invalid one would be a bug)."""
    import glob
    import os

    import yaml

    from mpi_operator_tpu.cluster.serialize import from_manifest

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifests = sorted(glob.glob(os.path.join(repo, "examples", "*.yaml")))
    assert len(manifests) >= 8
    for path in manifests:
        with open(path) as f:
            doc = yaml.safe_load(f)
        job = from_manifest(doc)
        validate_spec(job.spec)   # raises on violation


def test_crd_carries_cel_validation_rules():
    """deploy/0-crd.yaml must enforce the api/validation.py invariants
    SERVER-side via x-kubernetes-validations (the reference's schema-first
    posture — ALL its sizing constraints live in the CRD schema so
    `kubectl create` rejects bad specs, ref deploy/0-crd.yaml:16-99).
    Real clusters never run our in-process admission for user objects."""
    import os

    import yaml

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "deploy", "0-crd.yaml")) as f:
        crd = yaml.safe_load(f)
    spec_schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
        "properties"]["spec"]
    validations = spec_schema["x-kubernetes-validations"]
    rules = "\n".join(v["rule"] for v in validations)
    # every invariant family api/validation.py enforces is represented
    assert "numSlices" in rules          # slice divisibility
    assert "tpusPerWorker" in rules      # Mode A divisibility
    assert "processingUnitsPerWorker" in rules
    assert "sliceTopology" in rules      # topology-product check
    for v in validations:
        assert v.get("message"), f"CEL rule without a message: {v['rule']}"


def test_mode_a_explicit_per_worker_divisibility():
    """Explicit per-worker counts are checkable at admission (parity with
    the CRD CEL rules); the flag-default case stays a controller backstop
    that converges to Failed/InvalidTPUJobSpec."""
    with pytest.raises(ValidationError, match="multiple"):
        validate_spec(TPUJobSpec(tpus=16, tpus_per_worker=5))
    with pytest.raises(ValidationError, match="multiple"):
        validate_spec(TPUJobSpec(processing_units=10,
                                 processing_units_per_worker=4))
    validate_spec(TPUJobSpec(tpus=16, tpus_per_worker=4))
    # total < perWorker is the legal single-worker form (ref :573-582)
    validate_spec(TPUJobSpec(tpus=2, tpus_per_worker=8))
    # zero/negative per-worker is rejected for BOTH mode-A fields (a zero
    # would otherwise reach allocation's divide)
    with pytest.raises(ValidationError, match="processingUnitsPerWorker"):
        validate_spec(TPUJobSpec(processing_units=10,
                                 processing_units_per_worker=0))


def test_elastic_validation():
    """spec.elastic needs a topology ladder to walk: tpus mode, one
    slice; minTpus requires elastic and must be a valid count <= tpus."""
    validate_spec(TPUJobSpec(tpus=8, elastic=True))
    validate_spec(TPUJobSpec(tpus=16, elastic=True, min_tpus=4))
    with pytest.raises(ValidationError, match="tpus sizing mode"):
        validate_spec(TPUJobSpec(replicas=2, elastic=True))
    with pytest.raises(ValidationError, match="numSlices"):
        validate_spec(TPUJobSpec(tpus=16, elastic=True, num_slices=2,
                                 slice_topology="2x4"))
    with pytest.raises(ValidationError, match="requires spec.elastic"):
        validate_spec(TPUJobSpec(tpus=8, min_tpus=4))
    with pytest.raises(ValidationError, match="not a valid v5e"):
        validate_spec(TPUJobSpec(tpus=8, elastic=True, min_tpus=3))
    with pytest.raises(ValidationError, match="exceeds"):
        validate_spec(TPUJobSpec(tpus=8, elastic=True, min_tpus=16))


def test_elastic_fields_round_trip_serialization():
    from mpi_operator_tpu.api.types import ObjectMeta, TPUJob
    from mpi_operator_tpu.cluster.serialize import (from_manifest,
                                                    to_manifest)

    job = TPUJob(metadata=ObjectMeta(name="e", namespace="d"),
                 spec=TPUJobSpec(tpus=16, elastic=True, min_tpus=4))
    job.status.elastic_tpus = 8
    job.status.elastic_since = 1234567890.0
    back = from_manifest(to_manifest(job))
    assert back.spec.elastic is True
    assert back.spec.min_tpus == 4
    assert back.status.elastic_tpus == 8
    assert abs(back.status.elastic_since - 1234567890.0) < 1.0


def test_multislice_validation_is_per_slice():
    """Slice-shape constraints apply PER SLICE: tpus=512 over 2 slices is
    two valid v5e-256 slices; non-divisible counts fail at admission (the
    SURVEY §7 hard part: invalid shapes must not reach runtime)."""
    validate_spec(TPUJobSpec(tpus=512, num_slices=2,
                             slice_topology="16x16"))
    validate_spec(TPUJobSpec(tpus=96, num_slices=3, slice_topology="4x8"))
    with pytest.raises(ValidationError, match="divide into 3"):
        validate_spec(TPUJobSpec(tpus=64, num_slices=3))
    with pytest.raises(ValidationError, match="processingUnits"):
        validate_spec(TPUJobSpec(processing_units=9, num_slices=2,
                                 slice_topology="2x2"))


def test_mode_b_zero_chip_rejected_at_admission():
    """replicas mode with TPU resource type and NO google.com/tpu limit
    would give every worker zero chips. The reference allocates 0 silently
    (mpi_job_controller.go:587-593) and the job fails at runtime; we
    reject at admission instead (documented divergence — "fail at
    admission, not at runtime")."""
    from mpi_operator_tpu.api.types import RESOURCE_CPU, RESOURCE_TPU

    with pytest.raises(ValidationError, match="resource limit"):
        validate_spec(TPUJobSpec(replicas=2))
    # an explicit TPU resource type without the limit is equally invalid
    with pytest.raises(ValidationError, match="resource limit"):
        validate_spec(TPUJobSpec(replicas=2,
                                 processing_resource_type=RESOURCE_TPU))
    # with the limit present the spec is fine
    spec = TPUJobSpec(replicas=2)
    spec.template.main_container().limits = {RESOURCE_TPU: 4}
    validate_spec(spec)
    # the check follows the EFFECTIVE resource type: Mode B sizes each
    # worker from the matching container limit whatever the type, so a
    # cpu-resource spec without a cpu limit is equally degenerate
    with pytest.raises(ValidationError, match="resource limit"):
        validate_spec(TPUJobSpec(replicas=2,
                                 processing_resource_type=RESOURCE_CPU))
    spec = TPUJobSpec(replicas=2, processing_resource_type=RESOURCE_CPU)
    spec.template.main_container().limits = {RESOURCE_CPU: 2}
    validate_spec(spec)


def test_multislice_mode_a_per_worker_divisibility_at_admission():
    """Mode A with an explicit per-worker count: the derived worker count
    must divide into numSlices AT ADMISSION (tpus=16/16-per-worker = 1
    worker can't split over 2 slices); the flag-default case stays a
    controller backstop."""
    with pytest.raises(ValidationError, match="does not divide into 2"):
        validate_spec(TPUJobSpec(tpus=16, tpus_per_worker=16, num_slices=2,
                                 slice_topology="2x4"))
    # divisible derivations pass
    validate_spec(TPUJobSpec(tpus=16, tpus_per_worker=8, num_slices=2,
                             slice_topology="2x4"))
    with pytest.raises(ValidationError, match="does not divide into 2"):
        validate_spec(TPUJobSpec(replicas=3, num_slices=2))
