"""bench.py harness behavior: the per-leg JSONL contract.

Every measured leg appends one fsync'd {"leg": ...} record to --jsonl
BEFORE the ladder moves on, so a bench process killed mid-ladder (the
driver timeout, an OOM kill, a lost tunnel) still leaves the finished
legs parseable on disk. The test runs a real bench.py subprocess on a
SHRUNKEN leg list (--decode-legs), SIGKILLs it the moment the first
record lands, and parses what survived — the acceptance shape of the
failure mode this feature exists for.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _read_records(path):
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def test_killed_mid_ladder_leaves_parseable_leg_records(tmp_path):
    jsonl = str(tmp_path / "legs.jsonl")
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--smoke", "--workload", "generate",
         "--decode-legs", "gpt2_decode,llama_decode",
         "--jsonl", jsonl],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if _read_records(jsonl):
                break                       # first leg landed — kill now
            if proc.poll() is not None:
                break                       # finished before we could kill
            time.sleep(0.5)
        else:
            pytest.fail("no leg record within 300s")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)

    records = _read_records(jsonl)          # must parse line-by-line
    assert records, "killed ladder left no per-leg records"
    assert all("leg" in r for r in records)
    first = next(r for r in records if r["leg"] == "gpt2_decode")
    assert first["gpt2_decode_tokens_per_sec"] > 0
