"""bench.py harness behavior: the per-leg JSONL contract and the
signal-flush path.

Every measured leg appends one fsync'd {"leg": ...} record to --jsonl
BEFORE the ladder moves on, so a bench process killed mid-ladder (the
driver timeout, an OOM kill, a lost tunnel) still leaves the finished
legs parseable on disk — and an EXTERNAL timeout (SIGTERM, `timeout`'s
default) additionally gets a flushed summary line built from the
completed legs. Both tests run a real bench.py subprocess on a SHRUNKEN
leg list (--decode-legs) and signal it the moment the first record
lands — the acceptance shape of the failure modes these features exist
for. The ~60s jax-import+compile warmup dominates each subprocess, so
the module fixture launches BOTH concurrently and each test polls its
own: the pair costs one warmup of wall-clock, not two, keeping the
tier-1 gate inside its timeout.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _read_records(path):
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _wait_first_record(proc, jsonl, secs=300):
    deadline = time.monotonic() + secs
    while time.monotonic() < deadline:
        if _read_records(jsonl):
            return                      # first leg landed — signal now
        if proc.poll() is not None:
            return                      # finished before we could signal
        time.sleep(0.5)
    pytest.fail(f"no leg record within {secs}s")


@pytest.fixture(scope="module")
def bench_procs(tmp_path_factory):
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    procs = {}
    for name, stdout in (("kill", subprocess.DEVNULL),
                         ("term", subprocess.PIPE)):
        jsonl = str(tmp_path_factory.mktemp(name) / "legs.jsonl")
        proc = subprocess.Popen(
            [sys.executable, BENCH, "--smoke", "--workload", "generate",
             "--decode-legs", "gpt2_decode,llama_decode",
             "--jsonl", jsonl],
            cwd=REPO, env=env, stdout=stdout,
            stderr=subprocess.DEVNULL,
            text=(stdout == subprocess.PIPE))
        procs[name] = (proc, jsonl)
    yield procs
    for proc, _ in procs.values():
        if proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=60)
        except Exception:
            pass


def test_killed_mid_ladder_leaves_parseable_leg_records(bench_procs):
    proc, jsonl = bench_procs["kill"]
    try:
        _wait_first_record(proc, jsonl)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)

    records = _read_records(jsonl)          # must parse line-by-line
    assert records, "killed ladder left no per-leg records"
    assert all("leg" in r for r in records)
    first = next(r for r in records if r["leg"] == "gpt2_decode")
    assert first["gpt2_decode_tokens_per_sec"] > 0


def test_sigterm_flushes_summary_json(bench_procs):
    """An EXTERNAL timeout is a SIGTERM, not a SIGKILL (`timeout`'s
    default; r05's rc=124 record carried parsed=null because the summary
    line never printed). bench.py's handler must flush a summary JSON
    built from the legs that completed before the signal — stdout must
    end with one parseable line, exit code 0."""
    proc, jsonl = bench_procs["term"]
    try:
        _wait_first_record(proc, jsonl)
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)

    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert lines, "SIGTERM'd bench printed no summary line"
    summary = json.loads(lines[-1])
    assert summary.get("metric"), summary
    if proc.returncode == 0 and "interrupted" in summary:
        # killed mid-ladder: the flush path ran; completed legs made it in
        assert summary["interrupted"] == "SIGTERM"
        assert summary.get("gpt2_decode_tokens_per_sec", 0) > 0
    # (if the ladder won the race and finished first, the normal summary
    # satisfies the same contract: a parseable record, never a null)


@pytest.mark.parametrize("argv", [
    [BENCH],
    ["-m", "mpi_operator_tpu.examples.lm_benchmark"],
    ["-m", "mpi_operator_tpu.examples.serve_benchmark"],
], ids=["bench", "lm_benchmark", "serve_benchmark"])
def test_benchmark_cli_help_exits_zero(argv):
    """`--help` on every benchmark entrypoint must exit 0 without
    touching jax device state — a flag typo in an argparse block
    otherwise surfaces only when a cluster run dies at parse time."""
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, *argv, "--help"], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stdout
    assert "usage" in proc.stdout.lower()
