"""Bootstrap-path tests: the env/hostname → jax.distributed resolution that
replaces the reference's hostfile + kubexec rsh agent (SURVEY §2.4)."""
import time

import pytest

from mpi_operator_tpu.bootstrap import (
    BootstrapError, initialize, process_info, resolve_worker_ordinal,
)
from mpi_operator_tpu.bootstrap.bootstrap import (
    ENV_COORDINATOR, ENV_LAUNCHER, ENV_NUM_PROCESSES, ENV_WORKER_ID,
)


def _env(**kw):
    base = {
        ENV_COORDINATOR: "job-worker-0.job-worker.default.svc:8476",
        ENV_NUM_PROCESSES: "4",
    }
    base.update(kw)
    return base


def test_ordinal_from_hostname():
    assert resolve_worker_ordinal("job-worker-3") == 3
    assert resolve_worker_ordinal("a-b-c-worker-12") == 12
    with pytest.raises(BootstrapError, match="ordinal"):
        resolve_worker_ordinal("launcher")


def test_process_info_from_worker_hostname():
    info = process_info(env=_env(), hostname="job-worker-2")
    assert info.process_id == 2
    assert info.num_processes == 4
    assert not info.is_launcher
    assert not info.is_coordinator
    assert process_info(env=_env(), hostname="job-worker-0").is_coordinator


def test_explicit_worker_id_overrides_hostname():
    info = process_info(env=_env(**{ENV_WORKER_ID: "1"}),
                        hostname="job-worker-3")
    assert info.process_id == 1


def test_launcher_gets_rank_zero_without_ordinal():
    info = process_info(env=_env(**{ENV_LAUNCHER: "1"}), hostname="job-launcher-xyz12")
    assert info.is_launcher and info.process_id == 0


def test_missing_coordinator_is_actionable_error():
    with pytest.raises(BootstrapError, match="TPU_COORDINATOR_ADDRESS"):
        process_info(env={}, hostname="job-worker-0")


def test_ordinal_out_of_range_rejected():
    with pytest.raises(BootstrapError, match=">= num_processes"):
        process_info(env=_env(), hostname="job-worker-9")


def test_initialize_single_process_skips_distributed():
    """num_processes == 1 must not call jax.distributed (dev flow)."""
    info = initialize(env={ENV_COORDINATOR: "localhost:8476",
                           ENV_NUM_PROCESSES: "1"},
                      hostname="job-worker-0")
    assert info.num_processes == 1


def test_slots_interleave_global_rank():
    """slots>1: global rank = ordinal*slots + local (hostfile `slots=` parity,
    ref mpi_job_controller.go:857-869)."""
    env = _env(**{"TPU_SLOTS_PER_WORKER": "4", "TPU_NUM_PROCESSES": "8",
                  "TPU_LOCAL_RANK": "2"})
    info = process_info(env=env, hostname="job-worker-1")
    assert info.process_id == 6
    with pytest.raises(BootstrapError, match="TPU_LOCAL_RANK"):
        process_info(env=_env(**{"TPU_SLOTS_PER_WORKER": "2",
                                 "TPU_LOCAL_RANK": "2"}),
                     hostname="job-worker-0")


def test_launcher_never_joins_process_group():
    """The launcher must not call jax.distributed.initialize — rank 0 lives
    on worker-0 (rank-collision regression)."""
    import types
    import unittest.mock as mock

    import mpi_operator_tpu.bootstrap.bootstrap as bs

    calls = []
    sentinel_jax = types.ModuleType("jax")
    sentinel_dist = types.ModuleType("jax.distributed")
    sentinel_dist.initialize = lambda *a, **kw: calls.append((a, kw))
    sentinel_jax.distributed = sentinel_dist

    # num_processes=4 would normally trigger distributed init
    env = _env(**{ENV_LAUNCHER: "1"})
    with mock.patch.dict("sys.modules", {"jax": sentinel_jax,
                                         "jax.distributed": sentinel_dist}):
        info = bs.initialize(env=env, hostname="anything")
    assert info.is_launcher and info.process_id == 0
    assert calls == [], "launcher must never call jax.distributed.initialize"


def test_status_channel_and_launcher_wait():
    """rank-0 StatusServer ←poll— launcher: running → done <code>."""
    import threading
    from mpi_operator_tpu.bootstrap.bootstrap import (
        ProcessInfo, StatusServer, launcher_wait, poll_status,
    )
    server = StatusServer(port=0)
    try:
        assert poll_status("localhost", server.port) == "running"
        info = ProcessInfo(coordinator_address=f"localhost:8476",
                           num_processes=2, process_id=0, is_launcher=True)
        result = {}
        t = threading.Thread(target=lambda: result.update(
            code=launcher_wait(info, port=server.port, poll_interval=0.05)))
        t.start()
        server.set_done(3, linger=5.0)
        t.join(timeout=5)
        assert result["code"] == 3
    finally:
        server.close()


def test_launcher_wait_startup_timeout():
    from mpi_operator_tpu.bootstrap.bootstrap import ProcessInfo, launcher_wait
    info = ProcessInfo(coordinator_address="localhost:1", num_processes=2,
                       process_id=0, is_launcher=True)
    with pytest.raises(BootstrapError, match="unreachable"):
        launcher_wait(info, port=1, poll_interval=0.05, startup_timeout=0.3)


def test_launcher_wait_loss_then_recovery():
    """LOST → re-contact resets all windows; completion still observed."""
    import threading
    from mpi_operator_tpu.bootstrap.bootstrap import (
        LAUNCHER_LOST_EXIT, ProcessInfo, StatusServer, launcher_wait,
    )
    # phase 1: server up, launcher sees "running"
    server = StatusServer(port=0)
    port = server.port
    info = ProcessInfo(coordinator_address="localhost:8476",
                       num_processes=2, process_id=0, is_launcher=True)
    result = {}
    t = threading.Thread(target=lambda: result.update(code=launcher_wait(
        info, port=port, poll_interval=0.05,
        startup_timeout=5.0, lost_timeout=0.4)), daemon=True)
    t.start()
    time.sleep(0.3)              # launcher has made contact (RUNNING)
    # phase 2: outage longer than lost_timeout → launcher goes LOST then
    # RESTARTING, but must NOT give up: a fresh startup window applies
    server.close()
    time.sleep(0.8)
    # phase 3: "pod restarted" — new server on the same port; done observed
    server2 = StatusServer(port=port)
    try:
        server2.set_done(0, linger=5.0)
        t.join(timeout=10)
        assert not t.is_alive()
        assert result["code"] == 0
        assert result["code"] != LAUNCHER_LOST_EXIT
    finally:
        server2.close()


def test_launcher_wait_loss_then_timeout_returns_lost_exit():
    """LOST → RESTARTING → fresh startup window expires → LAUNCHER_LOST_EXIT
    (not BootstrapError: contact was established, so this is infra loss)."""
    from mpi_operator_tpu.bootstrap.bootstrap import (
        LAUNCHER_LOST_EXIT, ProcessInfo, StatusServer, launcher_wait,
    )
    server = StatusServer(port=0)
    port = server.port
    info = ProcessInfo(coordinator_address="localhost:8476",
                       num_processes=2, process_id=0, is_launcher=True)
    import threading
    result = {}
    t = threading.Thread(target=lambda: result.update(code=launcher_wait(
        info, port=port, poll_interval=0.05,
        startup_timeout=0.3, lost_timeout=0.2)), daemon=True)
    t.start()
    time.sleep(0.2)              # contact made
    server.close()               # permanent loss
    t.join(timeout=10)
    assert not t.is_alive()
    assert result["code"] == LAUNCHER_LOST_EXIT


def test_status_channel_token_handshake():
    """A wrong-token poller is denied and cannot consume the done-linger;
    the real launcher (right token) still observes completion."""
    import threading
    from mpi_operator_tpu.bootstrap.bootstrap import (
        StatusServer, poll_status,
    )
    server = StatusServer(port=0, token="job-uid-42")
    try:
        assert poll_status("localhost", server.port,
                           token="wrong") == "denied"
        assert poll_status("localhost", server.port,
                           token="job-uid-42") == "running"
        done = threading.Event()
        t = threading.Thread(
            target=lambda: (server.set_done(7, linger=10.0), done.set()))
        t.start()
        time.sleep(0.1)
        # stray connections hammering the channel must not end the linger
        for _ in range(5):
            assert poll_status("localhost", server.port,
                               token="wrong") == "denied"
        assert not done.is_set()
        assert poll_status("localhost", server.port,
                           token="job-uid-42") == "done 7"
        t.join(timeout=5)
        assert done.is_set()
    finally:
        server.close()


def test_controller_injects_job_token():
    """The controller's discovery env carries TPU_JOB_TOKEN = job uid for
    the status-channel handshake."""
    from mpi_operator_tpu.api.types import new_tpu_job
    from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer
    from mpi_operator_tpu.controller import ControllerConfig, TPUJobController

    api_server = InMemoryAPIServer()
    controller = TPUJobController(api_server, config=ControllerConfig())
    job = new_tpu_job("tok", tpus=8)
    job.metadata.uid = "uid-abc"
    alloc = controller.allocate_processing_units(job, False)
    worker = controller.new_worker(job, alloc)
    launcher = controller.new_launcher(job, alloc)
    for obj in (worker.spec.template, launcher.spec.template):
        assert obj.main_container().env["TPU_JOB_TOKEN"] == "uid-abc"


def test_launch_forks_slots_and_propagates_failure(tmp_path):
    """The orted-replacement: forks slots processes with TPU_LOCAL_RANK and
    returns the first non-zero exit code."""
    import sys
    from mpi_operator_tpu.bootstrap.launch import launch
    out = tmp_path / "ranks"
    out.mkdir()
    code = launch([sys.executable, "-c",
                   "import os, pathlib; pathlib.Path("
                   f"'{out}', os.environ['TPU_LOCAL_RANK']).write_text('x')"],
                  slots=3)
    assert code == 0
    assert sorted(p.name for p in out.iterdir()) == ["0", "1", "2"]
    code = launch([sys.executable, "-c",
                   "import os, sys; sys.exit(5 if "
                   "os.environ['TPU_LOCAL_RANK']=='1' else 0)"], slots=2)
    assert code == 5


def test_config_dir_fallback(tmp_path):
    (tmp_path / "coordinator-address").write_text("cm-host:8476\n")
    (tmp_path / "num-processes").write_text("2\n")
    info = process_info(env={"TPU_CONFIG_PATH": str(tmp_path)},
                        hostname="job-worker-1")
    assert info.coordinator_address == "cm-host:8476"
    assert info.num_processes == 2 and info.process_id == 1


WORKER_SCRIPT = r'''
import os, sys
rank, port, repo = int(sys.argv[1]), sys.argv[2], sys.argv[3]
# fresh process: force the host platform (one local device) before any
# backend init, same channel as utils/hostplatform
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
# cross-process CPU collectives need the gloo transport (XLA CPU default
# cannot psum across processes)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, repo)
from mpi_operator_tpu.bootstrap import initialize
env = dict(os.environ)
env["TPU_COORDINATOR_ADDRESS"] = "127.0.0.1:" + port
env["TPU_NUM_PROCESSES"] = "2"
info = initialize(env, hostname="e2e-worker-%d" % rank)
assert info.process_id == rank, (info.process_id, rank)
assert jax.process_count() == 2
import jax.numpy as jnp
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones((jax.local_device_count(),)))
assert float(out[0]) == float(len(jax.devices())), float(out[0])
print("rank %d psum ok" % rank, flush=True)
'''


def _spawn_and_collect(cmds, markers):
    """Run the worker commands as real processes; assert each exits 0 and
    prints its marker. Shared by the single- and multi-slice rendezvous
    e2e tests so the harness (timeouts, cleanup, asserts) can't drift."""
    import os
    import subprocess

    base_env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [subprocess.Popen(c, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              env=base_env) for c in cmds]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out, marker) in enumerate(zip(procs, outs, markers)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert marker in out, f"worker {i} missing {marker!r}:\n{out}"


def test_multiprocess_rendezvous_e2e(tmp_path):
    """The full distributed-bootstrap slice as two REAL processes: the
    controller's env contract (TPU_COORDINATOR_ADDRESS / TPU_NUM_PROCESSES)
    plus StatefulSet-hostname rank derivation feed jax.distributed, and a
    cross-process psum proves the collective fabric is live — the
    capability the reference assembles from hostfile + kubexec + mpirun +
    orted (ref mpi_job_controller.go:849-885, :1123-1131), with zero exec
    machinery."""
    import os
    import socket
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:            # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    _spawn_and_collect(
        [[sys.executable, str(script), str(rank), str(port), repo]
         for rank in (0, 1)],
        [f"rank {rank} psum ok" for rank in (0, 1)])


GANG_SCRIPT = r'''
import os, sys
repo = sys.argv[1]
# fresh process: force the host platform BEFORE any backend init (the
# axon sitecustomize overrides JAX_PLATFORMS; this channel works)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, repo)
from mpi_operator_tpu.examples import lm_benchmark
sys.exit(lm_benchmark.main(sys.argv[2:]))
'''


def test_resize_and_resume_e2e(tmp_path):
    """The resize contract end-to-end with REAL processes (the way the
    rendezvous e2e proves bootstrap): a 2-process gang boots from the
    controller-MATERIALIZED worker env, trains the shipped lm_benchmark
    CLI and checkpoints into a shared dir; the user resizes the spec
    (tpus 8→4); the controller gang-restarts onto the new template; the
    new 1-process gang boots from the NEW env and RESUMES from the
    checkpoint — global-step continuity, not a from-scratch restart."""
    import os
    import re
    import socket
    import subprocess
    import sys

    from mpi_operator_tpu.api import types as api
    from mpi_operator_tpu.api.types import (
        Container, ObjectMeta, PodTemplateSpec, TPUJob, TPUJobSpec)
    from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer
    from mpi_operator_tpu.controller import TPUJobController

    srv = InMemoryAPIServer()
    ctrl = TPUJobController(srv)
    srv.create(TPUJob(
        metadata=ObjectMeta(name="resize", namespace="default"),
        spec=TPUJobSpec(tpus=8, template=PodTemplateSpec(containers=[
            Container(name="train", image="bench:latest")]))))
    ctrl.sync_handler("default/resize")
    sts = srv.get("StatefulSet", "default", "resize-worker")
    env_2proc = dict(sts.spec.template.main_container().env)
    assert env_2proc["TPU_NUM_PROCESSES"] == "2"

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    train_dir = str(tmp_path / "ckpt")
    script = tmp_path / "gang.py"
    script.write_text(GANG_SCRIPT)
    with socket.socket() as s:               # free coordinator port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def gang_env(materialized, rank):
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env.update(materialized)
        # the test machine is not a pod: rank comes from the explicit
        # override instead of the StatefulSet hostname, the coordinator
        # DNS name becomes loopback, and the chip gate is dropped (no
        # TPU on a 1-CPU-device world)
        env["TPU_WORKER_ID"] = str(rank)
        env["TPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        for k in ("TPU_READY_FILE", "TPU_EXPECTED_CHIPS",
                  "TPU_CONFIG_PATH"):
            env.pop(k, None)
        return env

    cli = ["--workload", "gpt2", "--size", "test", "--batch-per-device",
           "4", "--seq-len", "32", "--warmup-steps", "1", "--dtype",
           "float32", "--train-dir", train_dir, "--ckpt-every", "6",
           # full LR from step 1: the default 100-step warmup would keep
           # the LR ~0 for this whole short run and flatline the loss
           # signal the continuity assertion reads
           "--lr-warmup-steps", "1"]

    def run_gang(materialized, nprocs, num_steps):
        procs = [subprocess.Popen(
            [sys.executable, str(script), repo] + cli
            + ["--num-steps", str(num_steps)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=gang_env(materialized, rank)) for rank in range(nprocs)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=300)[0])
        finally:
            for p in procs:
                p.kill()
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"gang rank {i} failed:\n{out}"
        return outs[0]                       # rank 0 logs

    out1 = run_gang(env_2proc, nprocs=2, num_steps=12)
    losses1 = [float(x) for x in re.findall(r"loss: ([0-9.]+)", out1)]
    assert losses1, out1
    ckpts = sorted(os.listdir(train_dir))
    assert any(d.startswith("step_") for d in ckpts), ckpts

    # user resizes the job: 8 chips → 4 (2 workers → 1). The controller
    # reconciles it as a checkpointed gang restart onto the new topology.
    job = srv.get(api.KIND, "default", "resize")
    job.spec.tpus = 4
    srv.update(job)
    ctrl.sync_handler("default/resize")
    sts = srv.get("StatefulSet", "default", "resize-worker")
    assert sts.spec.replicas == 1
    env_1proc = dict(sts.spec.template.main_container().env)
    assert env_1proc["TPU_NUM_PROCESSES"] == "1"

    out2 = run_gang(env_1proc, nprocs=1, num_steps=4)
    m = re.search(r"resumed from \S*step_(\d+)", out2)
    assert m, f"no resume line in:\n{out2}"
    assert int(m.group(1)) == 13       # probe + warmup(1) + 12 steps
    losses2 = [float(x) for x in re.findall(r"loss: ([0-9.]+)", out2)]
    assert losses2, out2
    # continuity: the resumed gang restored step 13 (above) and its step
    # counter carries on — 13 + probe + 4 steps lands the final
    # checkpoint at GLOBAL step 18, where a from-scratch run would be at
    # 5. (Streams are step-keyed for token-identical resume, so phase 2
    # sees FRESH batches; the old memorization signal — resumed loss
    # below phase-1's start — no longer exists on uniform random tokens,
    # where every fresh-data loss sits at ~ln(vocab). Bitwise resume
    # identity is pinned in test_resilience.py.)
    assert "step_18" in os.listdir(train_dir), sorted(os.listdir(train_dir))
    assert losses2[0] < 11.0, (losses1, losses2)   # sane, not diverged


def test_elastic_shrink_and_resume_e2e(tmp_path):
    """The ELASTIC path end-to-end with REAL processes (VERDICT r04 next
    #6 — shrink was controller-tested only): a 2-process elastic gang
    boots from the controller-materialized env, trains the shipped CLI
    and checkpoints; the gang then goes not-Ready past the degraded
    window (no spec edit — capacity loss); the controller SHRINKS via
    status.elasticTpus to the next valid size; the 1-process degraded
    gang boots from the NEW env and resumes from the checkpoint with
    global-step continuity. Restore stays controller-tested
    (tests/test_controller.py::test_elastic_restores_after_recovery_window)."""
    import os
    import re
    import socket
    import subprocess
    import sys

    from mpi_operator_tpu.api import types as api
    from mpi_operator_tpu.api.types import (
        Container, ObjectMeta, PodTemplateSpec, TPUJob, TPUJobSpec)
    from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer
    from mpi_operator_tpu.cluster.resources import JobStatus, \
        StatefulSetStatus
    from mpi_operator_tpu.controller import TPUJobController, \
        ControllerConfig

    class Clock:
        t = 1000.0

        def __call__(self):
            return self.t

    clock = Clock()
    srv = InMemoryAPIServer()
    ctrl = TPUJobController(srv, config=ControllerConfig(
        elastic_degraded_seconds=60, elastic_recovery_seconds=120))
    ctrl.now = clock
    srv.create(TPUJob(
        metadata=ObjectMeta(name="el", namespace="default"),
        spec=TPUJobSpec(tpus=8, elastic=True, min_tpus=4,
                        template=PodTemplateSpec(containers=[
                            Container(name="train", image="bench:latest")]))))
    ctrl.sync_handler("default/el")
    sts = srv.get("StatefulSet", "default", "el-worker")
    env_2proc = dict(sts.spec.template.main_container().env)
    assert env_2proc["TPU_NUM_PROCESSES"] == "2"

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    train_dir = str(tmp_path / "ckpt")
    script = tmp_path / "gang.py"
    script.write_text(GANG_SCRIPT)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def gang_env(materialized, rank):
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env.update(materialized)
        env["TPU_WORKER_ID"] = str(rank)
        env["TPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        for k in ("TPU_READY_FILE", "TPU_EXPECTED_CHIPS",
                  "TPU_CONFIG_PATH"):
            env.pop(k, None)
        return env

    cli = ["--workload", "gpt2", "--size", "test", "--batch-per-device",
           "4", "--seq-len", "32", "--warmup-steps", "1", "--dtype",
           "float32", "--train-dir", train_dir, "--ckpt-every", "6",
           "--lr-warmup-steps", "1"]

    def run_gang(materialized, nprocs, num_steps):
        procs = [subprocess.Popen(
            [sys.executable, str(script), repo] + cli
            + ["--num-steps", str(num_steps)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=gang_env(materialized, rank)) for rank in range(nprocs)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=300)[0])
        finally:
            for p in procs:
                p.kill()
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"gang rank {i} failed:\n{out}"
        return outs[0]

    # phase 1: the full-size gang trains and checkpoints (playing kubelet
    # around it: workers Ready, launcher active → Running lands, which is
    # what arms the elastic degraded timer)
    sts.status = StatefulSetStatus(ready_replicas=2, replicas=2)
    srv.update(sts)
    ctrl.sync_handler("default/el")           # readiness gate → launcher
    launcher = srv.get("Job", "default", "el-launcher")
    launcher.status = JobStatus(active=1, start_time=clock.t)
    srv.update(launcher)
    ctrl.sync_handler("default/el")           # Running condition persists
    job = srv.get(api.KIND, "default", "el")
    assert job.status.get_condition(api.COND_RUNNING) is not None

    out1 = run_gang(env_2proc, nprocs=2, num_steps=12)
    losses1 = [float(x) for x in re.findall(r"loss: ([0-9.]+)", out1)]
    assert losses1, out1
    assert any(d.startswith("step_") for d in os.listdir(train_dir))

    # capacity loss: workers stop being Ready and STAY down past the
    # degraded window — NO spec edit anywhere
    sts = srv.get("StatefulSet", "default", "el-worker")
    sts.status = StatefulSetStatus(ready_replicas=0, replicas=2)
    srv.update(sts)
    ctrl.sync_handler("default/el")           # not-Ready timer arms
    clock.t += 61
    ctrl.sync_handler("default/el")           # → ElasticShrink decision
    job = srv.get(api.KIND, "default", "el")
    assert job.spec.tpus == 8                 # spec untouched
    assert job.status.elastic_tpus == 4
    assert job.status.get_condition(api.COND_DEGRADED).status == "True"
    ctrl.sync_handler("default/el")           # materialize the 1-worker world
    sts = srv.get("StatefulSet", "default", "el-worker")
    assert sts.spec.replicas == 1
    env_1proc = dict(sts.spec.template.main_container().env)
    assert env_1proc["TPU_NUM_PROCESSES"] == "1"

    # the degraded gang resumes from the checkpoint — step continuity
    # (see the resize e2e above for why the old memorization-based loss
    # assertion can't survive step-keyed, token-identical streams)
    out2 = run_gang(env_1proc, nprocs=1, num_steps=4)
    m = re.search(r"resumed from \S*step_(\d+)", out2)
    assert m, f"no resume line in:\n{out2}"
    assert int(m.group(1)) == 13
    losses2 = [float(x) for x in re.findall(r"loss: ([0-9.]+)", out2)]
    assert losses2, out2
    assert "step_18" in os.listdir(train_dir), sorted(os.listdir(train_dir))
    assert losses2[0] < 11.0, (losses1, losses2)   # sane, not diverged


# ---------------------------------------------------------------------------
# TPU-health readiness gate (SURVEY §7 "Readiness vs ICI formation")
# ---------------------------------------------------------------------------

def test_device_check_counts_local_devices():
    from mpi_operator_tpu.bootstrap.bootstrap import device_check

    import jax
    n = len(jax.local_devices())
    assert device_check() == n
    assert device_check(expected_chips=n) == n


def test_device_check_chip_mismatch_is_actionable():
    from mpi_operator_tpu.bootstrap.bootstrap import device_check

    with pytest.raises(BootstrapError, match="allocated 99 chips"):
        device_check(expected_chips=99)


def test_mark_ready_atomic_and_gated(tmp_path):
    from mpi_operator_tpu.bootstrap.bootstrap import mark_ready

    marker = tmp_path / "tpu-ready"
    # no path configured (env unset) → no-op, no litter
    assert mark_ready(None) is None
    assert not marker.exists()
    out = mark_ready(str(marker))
    assert out == str(marker)
    assert marker.read_text() == "ok\n"
    # no torn temp file left behind (atomic os.replace)
    assert list(tmp_path.iterdir()) == [marker]


def test_initialize_writes_marker_after_device_check(tmp_path):
    """The full gate: initialize() under the controller-injected env must
    leave the readiness marker only after the runtime enumerated the
    expected devices — the exec probe's contract."""
    import jax
    from mpi_operator_tpu.bootstrap.bootstrap import (
        ENV_EXPECTED_CHIPS, ENV_READY_FILE)

    marker = tmp_path / "tpu-ready"
    n = len(jax.local_devices())
    info = initialize(env={ENV_COORDINATOR: "localhost:8476",
                           ENV_NUM_PROCESSES: "1",
                           ENV_READY_FILE: str(marker),
                           ENV_EXPECTED_CHIPS: str(n)},
                      hostname="job-worker-0")
    assert info.num_processes == 1
    assert marker.exists()                      # probe would now pass


def test_initialize_leaves_no_marker_on_sick_runtime(tmp_path):
    """A chip-count mismatch (sick TPU) must raise AND leave no marker —
    the pod stays NotReady and the launcher gate holds."""
    from mpi_operator_tpu.bootstrap.bootstrap import (
        ENV_EXPECTED_CHIPS, ENV_READY_FILE)

    marker = tmp_path / "tpu-ready"
    with pytest.raises(BootstrapError, match="allocated 99 chips"):
        initialize(env={ENV_COORDINATOR: "localhost:8476",
                        ENV_NUM_PROCESSES: "1",
                        ENV_READY_FILE: str(marker),
                        ENV_EXPECTED_CHIPS: "99"},
                   hostname="job-worker-0")
    assert not marker.exists()


# ---------------------------------------------------------------------------
# multi-slice rank derivation (SURVEY §7 "Multi-slice (DCN) bootstrap")
# ---------------------------------------------------------------------------

def test_multislice_global_rank_is_slice_major():
    """Pod `<job>-worker-s<k>-<i>` + TPU_SLICE_ID=k → global worker index
    k*workers_per_slice + i, matching the controller's rank-major
    worker-hostnames order (the hostfile-analogue topology truth)."""
    from mpi_operator_tpu.bootstrap.bootstrap import (
        ENV_SLICE_ID, ENV_WORKERS_PER_SLICE)

    env = {ENV_COORDINATOR: "ms-worker-s0-0.ms-worker.default.svc:8476",
           ENV_NUM_PROCESSES: "4", "TPU_NUM_SLICES": "2",
           ENV_SLICE_ID: "1", ENV_WORKERS_PER_SLICE: "2"}
    info = process_info(env=env, hostname="ms-worker-s1-0")
    assert info.process_id == 2            # slice 1 starts at rank 2
    assert info.slice_id == 1
    assert info.num_slices == 2
    assert info.workers_per_slice == 2
    info = process_info(env={**env, ENV_SLICE_ID: "0"},
                        hostname="ms-worker-s0-1")
    assert info.process_id == 1


def test_multislice_workers_per_slice_derivable():
    """workers-per-slice can be derived from num_processes/slots/slices
    when the env omits it (older ConfigMaps)."""
    env = {ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "8",
           "TPU_NUM_SLICES": "2", "TPU_SLICE_ID": "1"}
    info = process_info(env=env, hostname="j-worker-s1-3")
    assert info.workers_per_slice == 4
    assert info.process_id == 7


def test_multislice_slots_interleave_within_slice():
    """slots>1 × multi-slice: rank = (slice*wps + ordinal)*slots + local."""
    from mpi_operator_tpu.bootstrap.bootstrap import ENV_LOCAL_RANK

    env = {ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "8",
           "TPU_NUM_SLICES": "2", "TPU_SLICE_ID": "1",
           "TPU_WORKERS_PER_SLICE": "2", "TPU_SLOTS_PER_WORKER": "2",
           ENV_LOCAL_RANK: "1"}
    info = process_info(env=env, hostname="j-worker-s1-1")
    assert info.process_id == (1 * 2 + 1) * 2 + 1    # == 7


def test_slice_id_out_of_range_rejected():
    with pytest.raises(BootstrapError, match="TPU_SLICE_ID=3"):
        process_info(env={ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "4",
                          "TPU_NUM_SLICES": "2", "TPU_SLICE_ID": "3"},
                     hostname="j-worker-s3-0")


def test_hybrid_mesh_from_env_contract():
    """bootstrap.hybrid_mesh builds the dcn×dp mesh straight from the
    controller-injected env — the REAL env contract, no hand-built mesh."""
    from mpi_operator_tpu.bootstrap.bootstrap import hybrid_mesh

    import jax
    n = jax.device_count()
    info = process_info(
        env={ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "1",
             "TPU_NUM_SLICES": "2"},
        hostname="j-worker-s0-0")
    mesh = hybrid_mesh(info)
    assert dict(mesh.shape)["dcn"] == 2
    assert dict(mesh.shape)["dp"] == n // 2


def test_slice_id_from_hostname_fallback():
    """ConfigMap-fallback processes (no slice env) recover the slice id
    from the pod name's group token — defaulting to 0 would collide
    global ranks across slices."""
    env = {ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "4",
           "TPU_NUM_SLICES": "2", "TPU_WORKERS_PER_SLICE": "2"}
    info = process_info(env=env, hostname="job-worker-s1-0")
    assert info.slice_id == 1
    assert info.process_id == 2
    # a multi-slice worker with NO slice identity at all is a hard error
    with pytest.raises(BootstrapError, match="identifies this"):
        process_info(env=env, hostname="job-worker-0")
    # launchers have no slice hostname and must not trip the check
    info = process_info(env={**env, "TPU_LAUNCHER": "1"},
                        hostname="job-launcher-abc12")
    assert info.is_launcher and info.slice_id == 0


def test_empty_slice_id_env_treated_as_unset():
    """TPU_SLICE_ID: "" (a YAML templating artifact) must not crash with
    a raw int() ValueError — it falls back to the hostname token."""
    env = {ENV_COORDINATOR: "c:1", ENV_NUM_PROCESSES: "4",
           "TPU_NUM_SLICES": "2", "TPU_WORKERS_PER_SLICE": "2",
           "TPU_SLICE_ID": ""}
    info = process_info(env=env, hostname="job-worker-s1-1")
    assert info.slice_id == 1 and info.process_id == 3


MULTISLICE_WORKER_SCRIPT = r'''
import json, os, sys
env_file, hostname, port, repo = sys.argv[1:5]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, repo)
from mpi_operator_tpu.bootstrap import initialize
env = dict(os.environ)
env.update(json.load(open(env_file)))
# the pod DNS name is unreachable outside the cluster; the CONTRACT under
# test is the topology resolution, so only the address is overridden
env["TPU_COORDINATOR_ADDRESS"] = "127.0.0.1:" + port
info = initialize(env, hostname=hostname)
expect_slice = int(env["TPU_SLICE_ID"])
assert info.slice_id == expect_slice, (info.slice_id, expect_slice)
assert info.process_id == expect_slice, (info.process_id, expect_slice)
assert jax.process_count() == 2
import jax.numpy as jnp
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones((jax.local_device_count(),)))
assert float(out[0]) == 2.0, float(out[0])
print("slice %d rank %d psum ok" % (info.slice_id, info.process_id),
      flush=True)
'''


def test_multislice_cross_slice_rendezvous_e2e(tmp_path):
    """Two REAL processes — slice-0 worker-0 and slice-1 worker-0 — form
    ONE jax.distributed world from the env the CONTROLLER materialized
    (per-slice StatefulSets, TPU_SLICE_ID, slice-major ranks) and run a
    cross-slice psum. This is the megascale bootstrap contract end to
    end: controller → env → rank derivation → collective fabric (SURVEY
    §7 "Multi-slice (DCN) bootstrap")."""
    import json
    import os
    import socket
    import subprocess
    import sys

    from mpi_operator_tpu.api import new_tpu_job
    from mpi_operator_tpu.cluster import InMemoryAPIServer
    from mpi_operator_tpu.controller import TPUJobController

    api_server = InMemoryAPIServer()
    ctrl = TPUJobController(api_server)
    ctrl.factory.start_all()
    job = new_tpu_job("mse2e", tpus=8, namespace="default")
    job.spec.num_slices = 2
    job.spec.slice_topology = "2x2"
    api_server.create(job)
    ctrl.sync_handler("default/mse2e")

    env_files = {}
    for k in (0, 1):
        sts = api_server.get("StatefulSet", "default", f"mse2e-worker-s{k}")
        env = dict(sts.spec.template.main_container().env)
        # the controller's topology env (TPU_NUM_PROCESSES=2,
        # TPU_WORKERS_PER_SLICE=1 for tpus=8 over 2 slices) is used
        # VERBATIM — only the chip-count gate is dropped (the CPU-sim
        # process sees 1 device, not the allocated 4 chips)
        env.pop("TPU_EXPECTED_CHIPS", None)
        env.pop("TPU_READY_FILE", None)
        p = tmp_path / f"env-s{k}.json"
        p.write_text(json.dumps(env))
        env_files[k] = str(p)

    script = tmp_path / "worker.py"
    script.write_text(MULTISLICE_WORKER_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    _spawn_and_collect(
        [[sys.executable, str(script), env_files[k],
          f"mse2e-worker-s{k}-0", str(port), repo] for k in (0, 1)],
        [f"slice {k} rank {k} psum ok" for k in (0, 1)])


# ---------------------------------------------------------------------------
# Distributed-init retry (bootstrap._initialize_distributed)
# ---------------------------------------------------------------------------

def _init_info():
    from mpi_operator_tpu.bootstrap.bootstrap import ProcessInfo
    return ProcessInfo(coordinator_address="job-worker-0:8476",
                       num_processes=2, process_id=1)


def test_init_retry_backoff_then_success():
    from mpi_operator_tpu.bootstrap.bootstrap import _initialize_distributed

    calls, sleeps = [], []

    def init_fn():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("failed to connect to coordinator")

    _initialize_distributed(_init_info(), {}, log=lambda s: None,
                            init_fn=init_fn, sleep=sleeps.append)
    assert len(calls) == 3
    assert sleeps == [1.0, 2.0]        # exponential from the 1s default


def test_init_retry_non_retryable_raises_immediately():
    from mpi_operator_tpu.bootstrap.bootstrap import _initialize_distributed

    calls, sleeps = [], []

    def bad_rank():
        calls.append(1)
        raise RuntimeError("process id 3 does not match num_processes 2")

    with pytest.raises(RuntimeError, match="process id"):
        _initialize_distributed(_init_info(), {}, log=lambda s: None,
                                init_fn=bad_rank, sleep=sleeps.append)
    assert len(calls) == 1 and sleeps == []    # no retry on config bugs

    def bad_value():
        raise ValueError("coordinator_address must be host:port")

    with pytest.raises(ValueError):
        _initialize_distributed(_init_info(), {}, log=lambda s: None,
                                init_fn=bad_value, sleep=sleeps.append)
    assert sleeps == []


def test_init_retry_exhaustion_raises_bootstrap_error():
    from mpi_operator_tpu.bootstrap.bootstrap import (
        ENV_INIT_RETRIES, _initialize_distributed)

    calls, sleeps = [], []

    def always_down():
        calls.append(1)
        raise RuntimeError("DEADLINE_EXCEEDED: coordinator unreachable")

    with pytest.raises(BootstrapError, match="after 3 attempt"):
        _initialize_distributed(_init_info(), {ENV_INIT_RETRIES: "3"},
                                log=lambda s: None,
                                init_fn=always_down, sleep=sleeps.append)
    assert len(calls) == 3
    assert sleeps == [1.0, 2.0]        # no sleep after the final attempt


def test_init_retry_delay_coordinator_fault():
    """TPU_FAULT_INJECT=delay-coordinator:K makes the first K attempts
    fail before init_fn even runs — the injectable drill for coordinator-
    late startup."""
    from mpi_operator_tpu.bootstrap.bootstrap import _initialize_distributed

    calls, sleeps = [], []
    env = {"TPU_FAULT_INJECT": "delay-coordinator:2"}
    _initialize_distributed(_init_info(), env, log=lambda s: None,
                            init_fn=lambda: calls.append(1),
                            sleep=sleeps.append)
    assert len(calls) == 1             # attempts 1-2 injected, 3rd real
    assert sleeps == [1.0, 2.0]


# ---------------------------------------------------------------------------
# launcher_wait window-reset proofs (fake clock: LOST -> RESTARTING ->
# contact must FULLY reset both windows)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t

    def sleep(self, seconds):
        self.t += seconds


def _run_launcher_wait(monkeypatch, responses, default=None, **kw):
    """Drive launcher_wait against a scripted poll_status sequence on a
    fake clock; each poll consumes one response (then `default` forever).
    Returns (exit_code_or_exception, clock, contact_times)."""
    from mpi_operator_tpu.bootstrap import bootstrap as bs

    clock = _FakeClock()
    monkeypatch.setattr(time, "monotonic", clock.monotonic)
    monkeypatch.setattr(time, "sleep", clock.sleep)
    script = list(responses)
    contacts = []

    def fake_poll(host, port, timeout=2.0, token=None):
        status = script.pop(0) if script else default
        if status is not None:
            contacts.append(clock.t)
        return status

    monkeypatch.setattr(bs, "poll_status", fake_poll)
    info = _init_info()
    kw.setdefault("poll_interval", 1.0)
    try:
        return bs.launcher_wait(info, **kw), clock, contacts
    except BootstrapError as exc:
        return exc, clock, contacts


def test_launcher_wait_transient_outages_never_accumulate(monkeypatch):
    """Outages each SHORTER than lost_timeout, repeated well past it in
    total, must never reach RESTARTING/give-up: any contact fully resets
    the loss window."""
    responses = []
    for _ in range(10):                 # 10 x 9s outages = 90s total loss
        responses += ["running"] + [None] * 9
    responses += ["done 0"]
    code, clock, _ = _run_launcher_wait(
        monkeypatch, responses, lost_timeout=10.0, startup_timeout=50.0)
    assert code == 0                    # survived 9x the lost budget


def test_launcher_wait_restarting_contact_resets_windows(monkeypatch):
    """Contact during RESTARTING returns to RUNNING with BOTH windows
    reset: a second total outage must again take the full
    lost_timeout + startup_timeout before the give-up exit."""
    from mpi_operator_tpu.bootstrap.bootstrap import LAUNCHER_LOST_EXIT

    # contact -> outage long enough to reach RESTARTING -> recovery
    # contact -> permanent outage
    responses = ["running"] + [None] * 15 + ["running"]
    code, clock, contacts = _run_launcher_wait(
        monkeypatch, responses, default=None,
        lost_timeout=10.0, startup_timeout=30.0)
    assert code == LAUNCHER_LOST_EXIT
    recovery_t = contacts[-1]
    # after the recovery the launcher owed a FULL fresh budget: 10s to
    # re-enter RESTARTING plus 30s of restart window
    assert clock.t - recovery_t >= 10.0 + 30.0
