"""Chaos-hardened control plane tests.

Three layers, mirroring the harness (mpi_operator_tpu/controller/chaos.py):

- **Fault injection** (cluster/chaos.py): seeded per-verb/kind rules are
  deterministic and replayable; the controller absorbs transient errors
  via rate-limited requeue (visible in tpu_operator_requeues_total),
  retries conflicts bounded and in place, and converges anyway.
- **Crash-consistent reconcile**: the controller is killed at EVERY
  write boundary (ControllerCrash after the write lands — the
  SIGKILL-shaped schedule) across each lifecycle shape — create,
  restart, resize, pack, disagg serving split, teardown — and must
  converge to the same terminal conditions, restart count, and owned
  resource set as the uninterrupted oracle, leak nothing, wedge no key.
- **Stuck-gang detection** (spec.progressDeadlineSeconds): a Running
  gang whose federated step frontier stops advancing is declared stuck,
  restarted through the ordinary restart-policy path (counted against
  backoffLimit), and the stall window lands in the postmortem.
"""
import io

import pytest

from mpi_operator_tpu.api import types as api
from mpi_operator_tpu.api.types import COND_STUCK
from mpi_operator_tpu.api.validation import validate_spec
from mpi_operator_tpu.cluster import (
    ConflictError,
    ControllerCrash,
    FaultingAPIServer,
    FaultRule,
    InMemoryAPIServer,
    TransientApiError,
    is_transient,
)
from mpi_operator_tpu.controller import chaos as chaos_mod
from mpi_operator_tpu.controller.chaos import (
    ChaosHarness,
    ConvergenceError,
    SCENARIOS,
    oracle_snapshots,
    soak,
)
from mpi_operator_tpu.controller.controller import ControllerConfig
from mpi_operator_tpu.controller.metrics import render_metrics
from mpi_operator_tpu.telemetry.collector import JobObservatory
from mpi_operator_tpu import postmortem

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# fault rules: parsing, matching, determinism
# ---------------------------------------------------------------------------

def test_fault_rule_parses_the_documented_syntax():
    rule = FaultRule.parse("update-status/TPUJob=0.3:conflict")
    assert rule == FaultRule(verb="update-status", kind="TPUJob",
                             rate=0.3, error="conflict")
    assert FaultRule.parse("mutate/*=0.1:transient").matches("delete", "Pod")
    assert not FaultRule.parse("mutate/*=1:transient").matches("get", "Pod")
    wildcard = FaultRule.parse("*/*=1:drop")
    assert wildcard.matches("watch", "StatefulSet")


@pytest.mark.parametrize("bad", [
    "nonsense", "create/Pod=2.0:transient", "create/Pod=0.5:explode"])
def test_fault_rule_rejects_malformed_rules(bad):
    with pytest.raises(ValueError):
        FaultRule.parse(bad)


def test_fault_injection_is_deterministic_per_seed():
    def run(seed):
        server = FaultingAPIServer(InMemoryAPIServer(),
                                   rules=["create/*=0.5:transient"],
                                   seed=seed)
        outcomes = []
        for i in range(40):
            job = api.TPUJob(metadata=api.ObjectMeta(name=f"j{i}",
                                                     namespace="default"),
                             spec=api.TPUJobSpec(replicas=1))
            try:
                server.create(job)
                outcomes.append("ok")
            except TransientApiError:
                outcomes.append("fault")
        return outcomes

    assert run(7) == run(7)
    assert run(7) != run(8)   # astronomically unlikely to collide


def test_transient_fault_leaves_store_unchanged():
    """Faults fire BEFORE the write applies: the client saw an error, the
    server never committed — the retry must find a clean slate."""
    server = FaultingAPIServer(InMemoryAPIServer(),
                               rules=["create/*=1:transient"], seed=0)
    job = api.TPUJob(metadata=api.ObjectMeta(name="j", namespace="default"),
                     spec=api.TPUJobSpec(replicas=1))
    with pytest.raises(TransientApiError) as err:
        server.create(job)
    assert is_transient(err.value)
    assert server.inner.try_get("TPUJob", "default", "j") is None
    assert server.fault_count("transient") == 1


def test_stale_read_serves_previous_version():
    server = FaultingAPIServer(InMemoryAPIServer(),
                               rules=["get/*=1:stale"], seed=0)
    job = api.TPUJob(metadata=api.ObjectMeta(name="j", namespace="default"),
                     spec=api.TPUJobSpec(replicas=1))
    created = server.inner.create(job)
    created.spec.replicas = 2
    server.update(created)                      # snapshots the prior version
    stale = server.get("TPUJob", "default", "j")
    assert stale.spec.replicas == 1             # the lagging watch cache
    assert server.inner.get("TPUJob", "default", "j").spec.replicas == 2


def test_crash_fires_after_the_write_lands():
    """ControllerCrash semantics: the store HAS the write; the client
    never saw the response — the mid-flight state replay must absorb."""
    server = FaultingAPIServer(InMemoryAPIServer(), seed=0)
    job = api.TPUJob(metadata=api.ObjectMeta(name="j", namespace="default"),
                     spec=api.TPUJobSpec(replicas=1))
    server.arm_crash(after_writes=1)
    with pytest.raises(ControllerCrash):
        server.create(job)
    assert server.inner.get("TPUJob", "default", "j") is not None
    assert isinstance(ControllerCrash("x"), BaseException)
    assert not isinstance(ControllerCrash("x"), Exception)  # ≈ SIGKILL


# ---------------------------------------------------------------------------
# client-go discipline: requeue on transient, bounded in-place conflict retry
# ---------------------------------------------------------------------------

def test_transient_fault_requeues_and_counts_reason():
    h = ChaosHarness(rules=["create/ConfigMap=1:transient"], seed=3)
    h.create_job("t1")
    h.drive()
    # every sync dies at the ConfigMap create -> rate-limited requeue
    counters = h.controller.sync_counters
    assert counters.requeues_snapshot().get("transient", 0) >= 1
    text = render_metrics(h.controller)
    assert 'tpu_operator_requeues_total{reason="transient"}' in text
    # lifting the fault lets the SAME key converge (never dropped)
    h.api.rules = []
    h.resync()
    h.drive_until(lambda: h.worker_sets("t1"), "t1 converges after faults")


def test_status_conflicts_are_retried_in_place_and_converge():
    h = ChaosHarness(rules=["update-status/TPUJob=0.5:conflict"], seed=11)
    h.create_job("c1")
    h.drive_until(lambda: h.worker_sets("c1"), "c1 sts")
    h.make_workers_ready("c1")
    h.drive_until(lambda: h.launcher("c1") is not None, "c1 launcher")
    h.set_launcher_active("c1")
    h.finish_launcher("c1")
    h.drive_until(lambda: h.cond("c1", api.COND_SUCCEEDED) == "True",
                  "c1 succeeds through conflicts")
    assert h.api.fault_count("conflict") >= 1
    assert h.controller.sync_counters.requeues_snapshot().get(
        "conflict", 0) >= 0  # most conflicts retire in place, not by requeue


def test_conflict_retry_is_bounded():
    """A conflict storm (rate 1.0) must exhaust MAX_CONFLICT_RETRIES and
    surface as a requeue — not spin in place forever."""
    from mpi_operator_tpu.controller.controller import MAX_CONFLICT_RETRIES
    h = ChaosHarness(rules=["update-status/TPUJob=1:conflict"], seed=5)
    h.create_job("b1")
    before = h.api.fault_count("conflict")
    h.drive(max_items=30)
    per_sync = MAX_CONFLICT_RETRIES + 1
    assert h.api.fault_count("conflict") >= per_sync
    assert h.controller.sync_counters.requeues_snapshot()["conflict"] >= 1
    assert before == 0


# ---------------------------------------------------------------------------
# crash-consistent reconcile: every lifecycle, killed at every write boundary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", sorted(SCENARIOS))
def test_lifecycle_converges_with_crash_at_every_write(kind):
    chaos = ChaosHarness(crash_every_write=True, seed=0)
    got = chaos_mod._normalize(SCENARIOS[kind](chaos, f"x-{kind}"),
                               f"x-{kind}")
    want = chaos_mod._normalize(oracle_snapshots(kind, f"o-{kind}"),
                                f"o-{kind}")
    assert got == want
    assert chaos.api.crashes > 0                 # the schedule actually ran
    assert all(not s["leaked"] for s in got.values())
    assert not chaos.queue_wedged()


def test_gang_restart_is_counted_once_across_crash_replays():
    """The launcher-uid marker in the Restarting condition: a crash
    between the status write and the launcher delete replays the sync,
    which must NOT charge a second restart against backoffLimit."""
    h = ChaosHarness(seed=0)
    h.create_job("g1", restart_policy="OnFailure")
    h.drive_until(lambda: h.worker_sets("g1"), "g1 sts")
    h.make_workers_ready("g1")
    h.drive_until(lambda: h.launcher("g1") is not None, "g1 launcher")
    h.set_launcher_active("g1")
    h.drive_until(lambda: h.cond("g1", api.COND_RUNNING) == "True", "g1 run")
    h.finish_launcher("g1", exit_code=137)
    # crash exactly at the restart-count status write: the count lands,
    # the launcher delete does not
    h.api.arm_crash(after_writes=1)
    h.resync()
    with pytest.raises(ControllerCrash):
        while h.controller.process_next_work_item(timeout=0.02):
            pass
    assert h.job("g1").status.restart_count == 1
    assert h.launcher("g1") is not None          # delete never happened
    h.kill_controller()
    h.drive_until(
        lambda: (h.launcher("g1") is not None
                 and not h.launcher("g1").failed()),
        "g1 fresh launcher after replay")
    assert h.job("g1").status.restart_count == 1  # replay did not re-count


def test_small_soak_in_process():
    """The tier-1-sized soak: one pass over every lifecycle shape with
    the full fault mix + crash-every-write. The 25-lifecycle version
    runs out of process via scripts/tier1.sh --chaos."""
    report = soak(seed=0, lifecycles=5)
    assert report["completed"] == 5
    assert report["crashes"] > 0
    assert report["total_faults"] > 0


def test_soak_failure_names_the_reproducer_seed():
    with pytest.raises(ConvergenceError, match="seed=99"):
        raise ConvergenceError("synthetic", seed=99)


# ---------------------------------------------------------------------------
# stuck-gang detection: progress lease end to end
# ---------------------------------------------------------------------------

def _stuck_fixture(tmp_path, policy="OnFailure", deadline=60):
    """A Running gang scraped through a fake clock + frozen step gauge."""
    h = ChaosHarness(config=ControllerConfig(worker_metrics_port=9100))
    clock = {"now": 1000.0}
    step = {"v": 5}

    def fetch(url):
        if url.endswith("/metrics"):
            return f"tpu_worker_step {step['v']}\n"
        raise IOError("no events endpoint in this fixture")

    obs = JobObservatory(events_dir=str(tmp_path),
                         clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0)
    h.controller.observatory = obs
    h.create_job("s1", restart_policy=policy,
                 progress_deadline_seconds=deadline)
    sync = lambda: h.controller.sync_handler("default/s1")  # noqa: E731
    sync()
    h.resync()
    h.make_workers_ready("s1")
    sync()
    h.resync()
    h.set_launcher_active("s1")
    h.resync()
    sync()                                   # Running; lease armed at step 5
    h.resync()
    return h, clock, step, sync, obs


def test_progress_deadline_requires_positive_seconds():
    spec = api.TPUJobSpec(tpus=8, progress_deadline_seconds=0)
    with pytest.raises(ValueError, match="progressDeadlineSeconds"):
        validate_spec(spec)


def test_stall_below_deadline_is_not_stuck(tmp_path):
    h, clock, _step, sync, _obs = _stuck_fixture(tmp_path)
    clock["now"] += 30                       # 30s of zero progress, < 60s
    sync()
    job = h.job("s1")
    assert job.status.get_condition(COND_STUCK) is None
    assert job.status.restart_count == 0


def test_stuck_gang_restarts_and_lands_in_postmortem(tmp_path):
    h, clock, step, sync, obs = _stuck_fixture(tmp_path)
    clock["now"] += 70                       # stall 70s >= deadline 60s
    sync()
    h.resync()

    job = h.job("s1")
    stuck = job.status.get_condition(COND_STUCK)
    assert stuck is not None and stuck.status == "True"
    assert stuck.reason == "ProgressDeadlineExceeded"
    assert "no observed step progress for 70s" in stuck.message
    # the ordinary restart-policy path: counted against backoffLimit
    assert job.status.restart_count == 1
    restarting = job.status.get_condition(api.COND_RESTARTING)
    assert restarting.reason == "GangStuck"
    assert h.launcher("s1") is None          # gang torn down
    assert any(e.reason == "GangStuck" and e.type == "Warning"
               for e in h.controller.recorder.events)

    # timeline: gang_stuck then gang_restart, stall window named
    records = obs.merged_records("s1")
    kinds = [r["event"] for r in records]
    assert "gang_stuck" in kinds
    assert kinds.index("gang_stuck") < kinds.index("gang_restart")
    stuck_rec = next(r for r in records if r["event"] == "gang_stuck")
    assert stuck_rec["stall_seconds"] == pytest.approx(70.0)
    assert stuck_rec["progress_deadline_seconds"] == 60

    # postmortem renders the stall as an incident with its resolution
    summary = postmortem.summarize(records)
    assert len(summary["stalls"]) == 1
    stall = summary["stalls"][0]
    assert stall["stall_seconds"] == pytest.approx(70.0)
    assert stall["resolution"] == "gang_restart"
    buf = io.StringIO()
    postmortem.render(summary, buf)
    out = buf.getvalue()
    assert "stuck gangs:" in out
    assert "no step progress for" in out

    # recovery: gang comes back, step advances, verdict retires
    sync()                                   # recreates the launcher
    h.resync()
    assert h.launcher("s1") is not None
    h.set_launcher_active("s1")
    h.resync()
    step["v"] = 6
    clock["now"] += 5
    sync()                                   # re-arms lease on fresh scrape
    h.resync()
    clock["now"] += 5
    sync()
    h.resync()
    resumed = h.job("s1").status.get_condition(COND_STUCK)
    assert resumed.status == "False"
    assert resumed.reason == "ProgressResumed"


def test_stuck_gang_with_policy_never_fails_terminally(tmp_path):
    h, clock, _step, sync, obs = _stuck_fixture(tmp_path, policy="Never")
    clock["now"] += 120
    sync()
    h.resync()
    job = h.job("s1")
    failed = job.status.get_condition(api.COND_FAILED)
    assert failed is not None and failed.status == "True"
    assert failed.reason == "StuckGang"
    assert job.status.restart_count == 0
    assert h.launcher("s1") is None
    records = obs.merged_records("s1")
    assert [r["event"] for r in records
            if r["event"] in ("gang_stuck", "job_failed")] == [
        "gang_stuck", "job_failed"]
    stall = postmortem.summarize(records)["stalls"][0]
    assert stall["resolution"] == "job_failed"
    # crash replay after the terminal verdict: the level-triggered
    # teardown clause must finish deleting a resurrected launcher
    sync()
    assert h.launcher("s1") is None


def test_all_scrapes_stale_freezes_the_frontier(tmp_path):
    """A dead metrics plane reads as a stall BY DESIGN: an unobservable
    gang cannot prove liveness."""
    h, clock, _step, sync, obs = _stuck_fixture(tmp_path)

    def broken(_url):
        raise IOError("metrics endpoint dark")

    obs.fetch = broken
    clock["now"] += 70                       # every scrape now fails
    sync()
    h.resync()
    assert h.job("s1").status.restart_count == 1
    assert h.job("s1").status.get_condition(COND_STUCK).status == "True"


# ---------------------------------------------------------------------------
# dropped watch events: the informer re-list heals a wedged cache
# ---------------------------------------------------------------------------

def test_relist_evicts_objects_whose_delete_event_was_dropped():
    h = ChaosHarness(seed=0)
    h.create_job("d1")
    h.drive_until(lambda: h.worker_sets("d1"), "d1 sts")
    # drop EVERY watch event from here on: the controller never hears
    # about the deletion
    h.api.rules = [FaultRule.parse("watch/*=1:drop")]
    uid = h.job("d1").metadata.uid
    h.inner.delete("TPUJob", "default", "d1")
    h.inner.cascade_delete(uid)
    assert h.controller.job_lister.try_get("default", "d1") is not None
    h.resync()                               # the periodic re-list
    assert h.controller.job_lister.try_get("default", "d1") is None
    h.drive()
    assert h.owned(uid) == []
