"""Checkpoint/resume tests (orbax; operator/workload boundary per SURVEY §5)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.models.resnet import create_model
from mpi_operator_tpu.parallel import MeshConfig, make_mesh
from mpi_operator_tpu.train import (
    Trainer, TrainerConfig, latest_checkpoint, restore_checkpoint,
    save_checkpoint,
)
from mpi_operator_tpu.data import synthetic_image_batch


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(MeshConfig.data_parallel(8))
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32)
    trainer = Trainer(model, mesh,
                      TrainerConfig(global_batch_size=16, image_size=32,
                                    num_classes=10))
    state = trainer.init_state(jax.random.PRNGKey(0))
    return mesh, trainer, state


def test_save_restore_round_trip(setup, tmp_path):
    _, trainer, state = setup
    save_checkpoint(tmp_path, state)
    restored = restore_checkpoint(str(tmp_path), state)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # shardings survive restore
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding == jax.tree_util.tree_leaves(state.params)[0].sharding


def test_resume_continues_training(setup, tmp_path):
    """Train 2 steps → checkpoint → restore → the step counter and params
    carry over and training proceeds."""
    _, trainer, state = setup
    # train_step donates its input state; work on a copy so the shared
    # module-scoped fixture's buffers survive for later tests
    state = jax.tree.map(jnp.copy, state)
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)
    imgs = jax.device_put(imgs, trainer.batch_sharding)
    labels = jax.device_put(labels, trainer.batch_sharding)
    for _ in range(2):
        state, _ = trainer.train_step(state, imgs, labels)
    save_checkpoint(tmp_path, state)

    fresh = trainer.init_state(jax.random.PRNGKey(0))
    resumed = restore_checkpoint(str(tmp_path), fresh)
    assert int(resumed.step) == 2
    resumed, m = trainer.train_step(resumed, imgs, labels)
    assert int(resumed.step) == 3 and np.isfinite(float(m["loss"]))


def test_latest_checkpoint_picks_max_step(setup, tmp_path):
    _, trainer, state = setup
    save_checkpoint(tmp_path, state, step=1)
    save_checkpoint(tmp_path, state, step=10)
    save_checkpoint(tmp_path, state, step=2)
    assert latest_checkpoint(str(tmp_path)).endswith("step_10")


def test_restore_missing_dir_errors(setup, tmp_path):
    _, _, state = setup
    with pytest.raises(FileNotFoundError, match="no checkpoints"):
        restore_checkpoint(str(tmp_path / "empty"), state)
