"""Checkpoint/resume tests (orbax; operator/workload boundary per SURVEY §5)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.models.resnet import create_model
from mpi_operator_tpu.parallel import MeshConfig, make_mesh
from mpi_operator_tpu.train import (
    Trainer, TrainerConfig, latest_checkpoint, restore_checkpoint,
    save_checkpoint,
)
from mpi_operator_tpu.data import synthetic_image_batch


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(MeshConfig.data_parallel(8))
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32)
    trainer = Trainer(model, mesh,
                      TrainerConfig(global_batch_size=16, image_size=32,
                                    num_classes=10))
    state = trainer.init_state(jax.random.PRNGKey(0))
    return mesh, trainer, state


def test_save_restore_round_trip(setup, tmp_path):
    _, trainer, state = setup
    save_checkpoint(tmp_path, state)
    restored = restore_checkpoint(str(tmp_path), state)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # shardings survive restore
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding == jax.tree_util.tree_leaves(state.params)[0].sharding


def test_resume_continues_training(setup, tmp_path):
    """Train 2 steps → checkpoint → restore → the step counter and params
    carry over and training proceeds."""
    _, trainer, state = setup
    # train_step donates its input state; work on a copy so the shared
    # module-scoped fixture's buffers survive for later tests
    state = jax.tree.map(jnp.copy, state)
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)
    imgs = jax.device_put(imgs, trainer.batch_sharding)
    labels = jax.device_put(labels, trainer.batch_sharding)
    for _ in range(2):
        state, _ = trainer.train_step(state, imgs, labels)
    save_checkpoint(tmp_path, state)

    fresh = trainer.init_state(jax.random.PRNGKey(0))
    resumed = restore_checkpoint(str(tmp_path), fresh)
    assert int(resumed.step) == 2
    resumed, m = trainer.train_step(resumed, imgs, labels)
    assert int(resumed.step) == 3 and np.isfinite(float(m["loss"]))


def test_latest_checkpoint_picks_max_step(setup, tmp_path):
    _, trainer, state = setup
    save_checkpoint(tmp_path, state, step=1)
    save_checkpoint(tmp_path, state, step=10)
    save_checkpoint(tmp_path, state, step=2)
    assert latest_checkpoint(str(tmp_path)).endswith("step_10")


def test_restore_missing_dir_errors(setup, tmp_path):
    _, _, state = setup
    with pytest.raises(FileNotFoundError, match="no checkpoints"):
        restore_checkpoint(str(tmp_path / "empty"), state)


def test_lm_state_save_restore_sharded(tmp_path):
    """LMTrainState (no batch_stats) round-trips with tp/fsdp shardings
    intact — the gang-restart resume path for the transformer ladder."""
    from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
    from mpi_operator_tpu.train.lm_trainer import LMTrainer, LMTrainerConfig

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    tr = LMTrainer(CausalLM(cfg), mesh,
                   LMTrainerConfig(global_batch_size=8, seq_len=16))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 128),
        tr.batch_sharding)
    state, _ = tr.train_step(state, toks, jnp.roll(toks, -1, 1))
    save_checkpoint(tmp_path, state)

    fresh = tr.init_state(jax.random.PRNGKey(2))
    resumed = restore_checkpoint(str(tmp_path), fresh)
    assert int(resumed.step) == 1
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding     # sharded layout survives
    resumed, m = tr.train_step(resumed, toks, jnp.roll(toks, -1, 1))
    assert int(resumed.step) == 2 and np.isfinite(float(m["loss"]))


def test_lm_benchmark_resume_surface(tmp_path):
    """run_lm_benchmark writes a checkpoint and resumes from it."""
    from mpi_operator_tpu.examples.lm_benchmark import run_lm_benchmark

    logs = []
    _s, m1 = run_lm_benchmark(
        workload="gpt2", size="test", batch_per_device=1, seq_len=16,
        num_steps=2, warmup_steps=0, dtype_name="float32",
        train_dir=str(tmp_path), log=logs.append)
    assert latest_checkpoint(str(tmp_path)) is not None
    _s, m2 = run_lm_benchmark(
        workload="gpt2", size="test", batch_per_device=1, seq_len=16,
        num_steps=2, warmup_steps=0, dtype_name="float32",
        train_dir=str(tmp_path), log=logs.append)
    assert any("resumed from" in l for l in logs)


def test_pp_trainer_checkpoint_roundtrip(tmp_path):
    """PPTrainState (pipeline layout: stacked pp-sharded blocks) must
    survive save/restore with values and shardings intact."""
    import optax

    from mpi_operator_tpu.models.transformer import gpt2_config
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.train import LMTrainerConfig, PipelineLMTrainer
    from mpi_operator_tpu.train.checkpoint import (latest_checkpoint,
                                                   restore_checkpoint,
                                                   save_checkpoint)

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=16)
    mesh = make_mesh(MeshConfig(pp=2, dp=4))
    t = PipelineLMTrainer(cfg, mesh,
                          LMTrainerConfig(global_batch_size=16, seq_len=8),
                          num_microbatches=4, tx=optax.sgd(0.1))
    state = t.init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (16, 9), 0, 128)
    state, _ = t.train_step(state, *t.microbatch(toks[:, :-1], toks[:, 1:]))
    save_checkpoint(str(tmp_path), state)
    latest = latest_checkpoint(str(tmp_path))
    assert latest is not None

    fresh = t.init_state(jax.random.PRNGKey(7))
    restored = restore_checkpoint(latest, fresh)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding.is_equivalent_to(a.sharding, a.ndim)


def test_periodic_async_checkpointing(tmp_path):
    """Mid-run resumability: periodic_saver fires non-blocking async
    checkpoints every N steps during the benchmark loop; a mid-run
    checkpoint exists (not just the final one), restores cleanly after
    wait_for_checkpoints, and carries the right step counter."""
    import optax

    from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
    from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig
    from mpi_operator_tpu.train.checkpoint import (
        periodic_saver, wait_for_checkpoints)

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                   LMTrainerConfig(global_batch_size=8, seq_len=32,
                                   log_every=2),
                   tx=optax.sgd(0.1))
    state = tr.init_state(jax.random.PRNGKey(0))

    class Stream:
        def __iter__(self):
            return self

        def __next__(self):
            toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
            return (jax.device_put(toks, tr.batch_sharding),
                    jax.device_put(jnp.roll(toks, -1, 1),
                                   tr.batch_sharding))

    hook = periodic_saver(str(tmp_path), every=2, log=lambda s: None)
    state, _ = tr.benchmark(state, Stream(), num_steps=6, warmup_steps=1,
                            log=lambda s: None, step_hook=hook)
    wait_for_checkpoints()
    steps = sorted(int(p.name[5:]) for p in tmp_path.iterdir()
                   if p.name.startswith("step_"))
    assert steps == [2, 4, 6], steps          # every 2, warmup excluded
    mid = restore_checkpoint(str(tmp_path / "step_4"),
                             tr.init_state(jax.random.PRNGKey(0)))
    assert int(mid.step) == 4
    # disabled modes
    assert periodic_saver(None, 2) is None
    assert periodic_saver(str(tmp_path), 0) is None
    # the final maybe_save must SKIP (not delete-and-rewrite) a step the
    # periodic hook already committed
    from mpi_operator_tpu.train.checkpoint import maybe_save
    logs = []
    maybe_save(str(tmp_path), state, log=logs.append)   # step 7: writes
    assert "step_7" in logs[-1] and "already" not in logs[-1]
    maybe_save(str(tmp_path), state, log=logs.append)   # step 7 again
    assert "already written" in logs[-1]


def test_nonblocking_periodic_saves_gc_and_restore_exact(tmp_path):
    """The non-blocking hook's join -> gc -> dispatch ordering: back-to-
    back firings with keep_last=1 never delete the newest committed
    checkpoint out from under the in-flight write, every surviving
    step_N is intact, and the final restore is bit-identical to the
    state that was saved."""
    import optax

    from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
    from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig
    from mpi_operator_tpu.train.checkpoint import (
        maybe_save, periodic_saver, verify_checkpoint,
        wait_for_checkpoints)

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=16)
    tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                   LMTrainerConfig(global_batch_size=8, seq_len=8),
                   tx=optax.sgd(0.1))
    state = tr.init_state(jax.random.PRNGKey(0))

    hook = periodic_saver(str(tmp_path), every=1, log=lambda s: None,
                          keep_last=1)
    # fire WITHOUT intervening waits — each firing joins the previous
    # write itself before gc runs, so no gc can see a half-written dir
    for step in range(1, 6):
        hook(state.replace(step=jnp.asarray(step)), step)
    wait_for_checkpoints()
    steps = sorted(int(p.name[5:]) for p in tmp_path.iterdir()
                   if p.name.startswith("step_"))
    # keep_last=1 gc runs BEFORE each dispatch, so the previous step
    # survives alongside the newest: {4, 5} after five firings
    assert steps == [4, 5], steps
    for s in steps:
        assert verify_checkpoint(str(tmp_path / f"step_{s}"))
    restored = restore_checkpoint(str(tmp_path / "step_5"),
                                  tr.init_state(jax.random.PRNGKey(3)))
    assert int(restored.step) == 5
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the benchmark-exit path: maybe_save(block=False) overlaps the
    # final write; after the explicit join it restores bit-identical too
    final = state.replace(step=jnp.asarray(9))
    maybe_save(str(tmp_path), final, log=lambda s: None, block=False)
    wait_for_checkpoints()
    back = restore_checkpoint(str(tmp_path / "step_9"),
                              tr.init_state(jax.random.PRNGKey(4)))
    assert int(back.step) == 9
    for a, b in zip(jax.tree.leaves(back.params),
                    jax.tree.leaves(final.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
