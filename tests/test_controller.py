"""Controller reconcile tests.

Mirrors the reference's single test layer — controller unit tests against
fake clientsets asserting the exact emitted write actions
(reference pkg/controllers/mpi_job_controller_test.go, 16 scenarios at
:466-789; fixture/oracle mechanics at :48-311, SURVEY.md §4).

The fixture here plays the same roles: the InMemoryAPIServer is both the
fake object tracker (recording Actions) and the informer source; sync_handler
is called synchronously with zero concurrency (ref alwaysReady stubs :169-177).
"""
import pytest

from mpi_operator_tpu.api import types as api
from mpi_operator_tpu.api.types import (
    Container, ObjectMeta, PodTemplateSpec, TPUJob, TPUJobSpec,
)
from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer
from mpi_operator_tpu.cluster.resources import (
    ConfigMap, Job, JobStatus, Role, ServiceAccount, StatefulSet,
    StatefulSetSpec, StatefulSetStatus, RoleBinding,
)
from mpi_operator_tpu.controller import (
    ControllerConfig, ForeignOwnershipError, TPUJobController,
)
from mpi_operator_tpu.controller.controller import (
    CONFIG_SUFFIX, LAUNCHER_SUFFIX, WORKER_SUFFIX,
)


# ---------------------------------------------------------------------------
# fixture (ref mpi_job_controller_test.go:48-267)
# ---------------------------------------------------------------------------

class Fixture:
    def __init__(self, **config_kwargs):
        self.api = InMemoryAPIServer()
        self.controller = TPUJobController(
            self.api, config=ControllerConfig(**config_kwargs)
        )
        self.controller.factory.start_all()

    def seed(self, obj):
        """Seed both the fake tracker and the informer cache, like setUp*
        helpers (ref :401-445). Watch events keep informers in sync."""
        return self.api.create(obj)

    def run(self, key, expect_error=None):
        """ref: fixture.run/runController (:214-267). Clears setup actions so
        assertions see only what sync emitted."""
        self.api.clear_actions()
        if expect_error is None:
            self.controller.sync_handler(key)
        else:
            with pytest.raises(expect_error):
                self.controller.sync_handler(key)
        return self.api.write_actions()


def new_job(name="test", tpus=8, **kw) -> TPUJob:
    spec = TPUJobSpec(
        tpus=tpus,
        template=PodTemplateSpec(
            containers=[Container(name="train", image="tpu-bench:latest")]
        ),
        **kw,
    )
    return TPUJob(metadata=ObjectMeta(name=name, namespace="default"), spec=spec)


def owned(job: TPUJob):
    return [job.controller_owner_reference()]


def verbs(actions):
    return [(a.verb, a.kind) for a in actions]


# ---------------------------------------------------------------------------
# no-op paths (ref TestDoNothingWithInvalidKey / NonexistentMPIJob :466-477)
# ---------------------------------------------------------------------------

def test_invalid_key_is_noop():
    f = Fixture()
    actions = f.run("metadata")     # no namespace separator
    assert actions == []


def test_nonexistent_job_is_noop():
    f = Fixture()
    actions = f.run("default/nonexistent")
    assert actions == []


# ---------------------------------------------------------------------------
# full creation fan-out (ref TestAllResourcesCreated :533-562)
# ---------------------------------------------------------------------------

def test_all_resources_created():
    f = Fixture()
    job = f.seed(new_job(tpus=8))   # 8 chips / 4 per worker = 2 workers
    actions = f.run("default/test")
    assert verbs(actions) == [
        ("create", "ConfigMap"),
        ("create", "Service"),      # headless worker DNS (no ref equivalent)
        ("create", "ServiceAccount"),
        ("create", "Role"),
        ("create", "RoleBinding"),
        ("create", "StatefulSet"),
        ("update-status", "TPUJob"),   # status subresource: Created condition
    ]
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 2
    assert sts.spec.pod_management_policy == "Parallel"
    assert sts.metadata.owner_references[0].uid == job.metadata.uid
    # TPU resource limits injected, zero nvidia.com/gpu anywhere (BASELINE.md)
    limits = sts.spec.template.main_container().limits
    assert limits == {api.RESOURCE_TPU: 4}
    cm = f.api.get("ConfigMap", "default", "test" + CONFIG_SUFFIX)
    assert cm.data["worker-hostnames"] == (
        "test-worker-0.test-worker.default.svc\n"
        "test-worker-1.test-worker.default.svc\n"
    )
    assert cm.data["coordinator-address"].startswith("test-worker-0.")
    assert cm.data["num-processes"] == "2"


def test_worker_service_headless_and_selects_workers():
    """The headless Service must exist (worker DNS backing) and its selector
    must match the worker pod labels, or jax.distributed rendezvous gets
    NXDOMAIN on a real cluster."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    f.run("default/test")
    svc = f.api.get("Service", "default", "test" + WORKER_SUFFIX)
    assert svc.cluster_ip == "None"                   # headless
    assert svc.metadata.owner_references[0].uid == job.metadata.uid
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.service_name == svc.metadata.name
    pod_labels = sts.spec.template.metadata.labels
    for k, v in svc.selector.items():
        assert pod_labels.get(k) == v, (k, v, pod_labels)


def test_single_worker_when_total_below_per_worker():
    """ref allocateProcessingUnits: total < perNode → 1 worker (:573-578)."""
    f = Fixture()
    f.seed(new_job(tpus=2))
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 1
    assert sts.spec.template.main_container().limits[api.RESOURCE_TPU] == 2


def test_indivisible_total_converges_to_invalid_spec_failed():
    """ref: total % perNode != 0 → error (:580). The reference requeues
    that error forever with nothing in status; here the sync converges to
    a terminal Failed/InvalidTPUJobSpec condition + Warning Event in ONE
    sync. Per-worker comes from the operator FLAG (the case admission and
    the CRD CEL rules cannot see)."""
    f = Fixture(tpus_per_worker=5)
    f.seed(new_job(tpus=16))
    actions = f.run("default/test")
    assert verbs(actions) == [("update-status", "TPUJob")]
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond is not None
    assert cond.reason == "InvalidTPUJobSpec"
    assert "multiple" in cond.message
    assert any(e.type == "Warning" and e.reason == "InvalidTPUJobSpec"
               for e in f.controller.recorder.events)
    # second sync is a converged no-op, not a hot loop
    assert f.run("default/test") == []


def test_invalid_spec_bypassing_admission_forgets_key():
    """A spec only a real API server would admit (it enforces just the
    CRD-schema subset of api/validation.py) must not hot-loop the
    workqueue (the reference rate-limited-requeues forever, :399-404):
    one sync lands the Failed condition and the queue forgets the key."""
    f = Fixture()
    f.api._admission.clear()        # simulate schema-only enforcement
    job = new_job(tpus=None)
    job.spec.replicas = 3
    job.spec.num_slices = 2         # 3 workers % 2 slices → backstop error
    job.spec.template.main_container().limits = {api.RESOURCE_TPU: 4}
    f.seed(job)
    f.controller.enqueue_tpu_job(job)
    # drain: the status write re-enqueues once via its own watch event;
    # the follow-up sync is a converged no-op
    while f.controller.process_next_work_item(timeout=0.05):
        pass
    assert f.controller.queue.num_requeues("default/test") == 0
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond is not None
    assert cond.reason == "InvalidTPUJobSpec"


def test_invalid_spec_recovers_when_fixed():
    """InvalidTPUJobSpec is level-triggered, not terminal: fixing the spec
    clears the condition and reconciliation resumes (the reference
    recovered here too — by retrying forever)."""
    f = Fixture(tpus_per_worker=5)
    f.seed(new_job(tpus=16))
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.get_condition(api.COND_FAILED).status == "True"
    job.spec.tpus_per_worker = 4           # user fixes the spec
    f.api.update(job)
    actions = f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond.status == "False"
    assert cond.reason == "SpecValidated"
    assert ("create", "StatefulSet") in verbs(actions)


def test_invalid_spec_message_refreshes_on_different_breakage():
    """A spec re-broken a DIFFERENT way must refresh the condition message
    instead of freezing the first failure text."""
    f = Fixture(tpus_per_worker=5)
    f.api._admission.clear()
    f.seed(new_job(tpus=16))
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    first_msg = job.status.get_condition(api.COND_FAILED).message
    job.spec.tpus = None
    job.spec.replicas = 3
    job.spec.num_slices = 2
    job.spec.template.main_container().limits = {api.RESOURCE_TPU: 4}
    f.api.update(job)
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond.status == "True"
    assert cond.message != first_msg
    assert "numSlices" in cond.message


def test_invalid_spec_edit_never_resurrects_terminal_job():
    """A job already terminally Failed (reason TPUJobFailed) whose spec is
    later edited invalid must keep its terminal condition — converting it
    to the level-triggered InvalidTPUJobSpec reason would let a
    subsequent spec FIX clear Failed and resurrect a finished job despite
    restartPolicy Never (advisor r04)."""
    f = Fixture()
    f.api._admission.clear()
    job = f.seed(new_job(tpus=8, restart_policy="Never"))
    _seed_finished_launcher(f, job, succeeded=False)
    f.run("default/test")                  # terminal: Failed/TPUJobFailed
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.get_condition(api.COND_FAILED).reason == "TPUJobFailed"
    job.spec.tpus = 7                      # edit the dead job's spec invalid
    f.api.update(job)
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond.status == "True"
    assert cond.reason == "TPUJobFailed"   # NOT InvalidTPUJobSpec
    job.spec.tpus = 8                      # ...and fixing it changes nothing
    f.api.update(job)
    actions = f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.get_condition(api.COND_FAILED).status == "True"
    assert ("create", "Job") not in verbs(actions)   # stays dead


def test_midrun_invalid_spec_tears_down_gang():
    """A RUNNING job edited into an invalid spec must not strand its gang
    burning chips behind a Failed status: the launcher is deleted and the
    workers scale to 0 in the same sync that records the condition."""
    f = Fixture()
    f.api._admission.clear()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=2)
    f.run("default/test")                   # creates the launcher
    assert f.api.try_get("Job", "default", "test" + LAUNCHER_SUFFIX) \
        is not None
    job = f.api.get(api.KIND, "default", "test")
    job.spec.tpus = 10                      # 10 % 4 != 0 → invalid
    f.api.update(job)
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.get_condition(api.COND_FAILED).reason == \
        "InvalidTPUJobSpec"
    assert f.api.try_get("Job", "default", "test" + LAUNCHER_SUFFIX) is None
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 0


def test_zero_per_worker_flag_is_invalid_spec_not_crash():
    """--tpus-per-worker 0 (a flag admission never sees) must surface as
    the ValueError the invalid-spec path converges on, not a
    ZeroDivisionError that requeues forever."""
    f = Fixture(tpus_per_worker=0)
    f.seed(new_job(tpus=8))
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    cond = job.status.get_condition(api.COND_FAILED)
    assert cond is not None
    assert cond.reason == "InvalidTPUJobSpec"
    assert ">= 1" in cond.message


# ---------------------------------------------------------------------------
# elastic membership (spec.elastic — checkpoint-restart elasticity)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _elastic_fixture(degraded=60, recovery=120, **job_kw):
    f = Fixture(elastic_degraded_seconds=degraded,
                elastic_recovery_seconds=recovery)
    clock = FakeClock()
    f.controller.now = clock
    job = new_job(tpus=8)
    job.spec.elastic = True
    for k, v in job_kw.items():
        setattr(job.spec, k, v)
    f.seed(job)
    return f, clock


def _elastic_go_running(f, name="test", workers=2):
    """Walk a fresh elastic gang to its first Running observation, then
    break readiness. The degraded countdown only arms after the gang has
    been Ready at least once (persisted as the Running condition) — a
    brand-new gang still scheduling/pulling images is not lost capacity,
    so without this warmup no elastic timer ever starts."""
    f.run(f"default/{name}")               # creates the worker STS
    _seed_ready(f, name, workers, workers)
    f.run(f"default/{name}")               # readiness gate → launcher
    launcher = f.api.get("Job", "default", name + LAUNCHER_SUFFIX)
    launcher.status.active = 1
    f.api.update(launcher)
    f.run(f"default/{name}")               # Running condition lands
    _seed_ready(f, name, 0, workers)       # ...and capacity is lost


def test_elastic_shrinks_after_persistent_unavailability():
    """Workers stuck not-Ready past the degraded window → the job shrinks
    to the next valid v5e size via STATUS (spec untouched), records a
    Degraded condition + Warning Event, and the next sync materializes
    the smaller world through the ordinary resize machinery."""
    f, clock = _elastic_fixture()
    _elastic_go_running(f)                 # first Ready observed, then lost
    f.run("default/test")                  # not-Ready timer arms
    clock.t += 61                          # past elastic_degraded_seconds
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.spec.tpus == 8              # spec never edited
    assert job.status.elastic_tpus == 4    # next valid count below 8
    cond = job.status.get_condition(api.COND_DEGRADED)
    assert cond is not None and cond.status == "True"
    assert any(e.reason == "ElasticShrink" and e.type == "Warning"
               for e in f.controller.recorder.events)
    # next sync: the worker set converges to the 1-worker degraded world
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 1
    env = sts.spec.template.main_container().env
    assert env["TPU_NUM_PROCESSES"] == "1"


def test_elastic_restores_after_recovery_window():
    """A shrunken job that has run Ready for the recovery window retries
    the full spec size (Degraded flips False, gang resizes back up)."""
    f, clock = _elastic_fixture()
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms
    clock.t += 61
    f.run("default/test")                  # shrink decision
    f.run("default/test")                  # materialize 1-worker world
    # the degraded gang comes up Ready
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    from mpi_operator_tpu.cluster.resources import StatefulSetStatus
    sts.status = StatefulSetStatus(ready_replicas=1, replicas=1)
    f.api.update(sts)
    f.run("default/test")                  # running degraded; timer arms
    clock.t += 121                         # past elastic_recovery_seconds
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None
    cond = job.status.get_condition(api.COND_DEGRADED)
    assert cond is not None and cond.status == "False"
    assert any(e.reason == "ElasticRestore"
               for e in f.controller.recorder.events)
    # next sync resizes the worker set back toward the full world
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 2


def test_elastic_shrink_recomputes_topology_selector():
    """The shrunken world must NOT stay pinned to the full size's
    sliceTopology nodepool — that is exactly the capacity that's gone.
    The selector is recomputed for the degraded chip count."""
    f, clock = _elastic_fixture(slice_topology="2x4")
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms
    clock.t += 61
    f.run("default/test")                  # shrink 8 -> 4
    f.run("default/test")                  # materialize
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    sel = sts.spec.template.node_selector
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"   # 4 chips


def test_elastic_recovery_counts_from_ready_not_shrink():
    """A shrunken gang that took longer than the recovery window to
    become Ready must still get a FULL window of degraded running before
    restore — the countdown arms at the first Ready observation."""
    f, clock = _elastic_fixture()
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms
    clock.t += 61
    f.run("default/test")                  # shrink at t0
    f.run("default/test")                  # materialize 1-worker world
    clock.t += 200                         # way past recovery (120s)...
    _seed_ready(f, "test", 1, 1)
    f.run("default/test")                  # ...but Ready only NOW: arms
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus == 4    # NOT restored yet
    clock.t += 121                         # a full window of Ready
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None # now restored


def test_elastic_respects_min_tpus_floor():
    """minTpus floors the ladder: a job already at the floor stays
    pending instead of shrinking further."""
    f, clock = _elastic_fixture(min_tpus=8)
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms
    clock.t += 61
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None          # 8 is the floor
    assert job.status.get_condition(api.COND_DEGRADED) is None


def test_elastic_timer_clears_when_workers_recover():
    """Workers turning Ready inside the window must clear the countdown —
    a later blip starts a FRESH window instead of inheriting the old
    one."""
    f, clock = _elastic_fixture()
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms
    clock.t += 50                          # inside the window
    _seed_ready(f, "test", 2, 2)
    f.run("default/test")                  # Ready → timer cleared
    _seed_ready(f, "test", 0, 2)
    clock.t += 30                          # 50+30 > 60, but fresh window
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None


def test_elastic_window_rearms_on_operator_restart():
    """The degraded/recovery COUNTDOWNS are process-memory by design: a
    new controller re-observes not-Ready and starts a FRESH window (the
    level-triggered-acceptable trade — a restart can delay a shrink by
    up to one window, never cause a spurious one). The ARMING gate (has
    the gang ever been Ready) is NOT process-memory: it rides the
    persisted Running condition, so a restarted operator still knows a
    once-Ready gang from a never-Ready one. Pinned here; documented in
    README."""
    f, clock = _elastic_fixture()
    _elastic_go_running(f)
    f.run("default/test")                  # timer arms in controller #1
    clock.t += 45                          # 45s of the 60s window elapse

    # operator restart: fresh controller, same API server state
    f2 = Fixture.__new__(Fixture)
    f2.api = f.api
    from mpi_operator_tpu.controller import TPUJobController
    from mpi_operator_tpu.controller.controller import ControllerConfig
    f2.controller = TPUJobController(
        f.api, config=ControllerConfig(elastic_degraded_seconds=60,
                                       elastic_recovery_seconds=120))
    f2.controller.factory.start_all()
    f2.controller.now = clock
    f2.run("default/test")                 # re-arms a FRESH window
    clock.t += 30                          # 45 + 30 > 60 but fresh window
    f2.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None  # NOT shrunk yet
    clock.t += 31                           # full fresh window elapses
    f2.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus == 4     # now it shrinks


def test_elastic_never_shrinks_before_first_ready():
    """A fresh elastic gang that takes longer than the degraded window to
    schedule (image pulls, capacity waits) must NOT shrink below spec
    before ever running at spec size — 'never yet Ready' is not 'lost
    capacity'. The countdown arms only once the Running condition (set at
    the first readiness-gate pass, persisted in status) exists."""
    f, clock = _elastic_fixture()
    f.run("default/test")                  # creates the 2-worker STS
    f.run("default/test")                  # still scheduling...
    clock.t += 3600                        # way past the degraded window
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    assert job.status.elastic_tpus is None
    assert job.status.get_condition(api.COND_DEGRADED) is None


def _seed_ready(f, name, ready, replicas):
    from mpi_operator_tpu.cluster.resources import StatefulSetStatus
    sts = f.api.get("StatefulSet", "default", name + WORKER_SUFFIX)
    sts.status = StatefulSetStatus(ready_replicas=ready, replicas=replicas)
    f.api.update(sts)
    return sts


def test_custom_replicas_cpu():
    """Mode B with cpu resource type (ref TestAllResourcesCreatedCustom
    cpu variant :564-596)."""
    f = Fixture()
    job = new_job(tpus=None)
    job.spec.replicas = 4
    job.spec.processing_resource_type = api.RESOURCE_CPU
    job.spec.template.main_container().limits = {"cpu": 2}
    f.seed(job)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 4
    # cpu jobs get no TPU node selectors
    assert "cloud.google.com/gke-tpu-accelerator" not in (
        sts.spec.template.node_selector
    )


def test_custom_replicas_tpu_limits():
    """Mode B with explicit google.com/tpu limits (ref :584-593)."""
    f = Fixture()
    job = new_job(tpus=None)
    job.spec.replicas = 2
    job.spec.template.main_container().limits = {api.RESOURCE_TPU: 4}
    f.seed(job)
    f.run("default/test")
    cm = f.api.get("ConfigMap", "default", "test" + CONFIG_SUFFIX)
    assert cm.data["tpus-per-worker"] == "4"


def test_gang_scheduling_creates_pdb():
    """ref: getOrCreatePDB (:490-494, :601-623) minAvailable=workers."""
    f = Fixture(enable_gang_scheduling=True)
    f.seed(new_job(tpus=16))
    actions = f.run("default/test")
    assert ("create", "PodDisruptionBudget") in verbs(actions)
    pdb = f.api.get("PodDisruptionBudget", "default", "test" + WORKER_SUFFIX)
    assert pdb.min_available == 4


# ---------------------------------------------------------------------------
# launcher gating (ref TestWorkerNotReady / TestWorkerReady :712-789)
# ---------------------------------------------------------------------------

def _seed_workers(f, job, replicas, ready):
    alloc = f.controller.allocate_processing_units(job, False)
    sts = f.controller.new_worker(job, alloc)
    sts.status = StatefulSetStatus(ready_replicas=ready, replicas=replicas)
    return f.seed(sts)


def test_launcher_not_created_until_workers_ready():
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=1)
    actions = f.run("default/test")
    assert ("create", "Job") not in verbs(actions)


def test_launcher_created_when_workers_ready():
    """ref TestWorkerReady (:739-763): ready==desired → launcher Job."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=2)
    # seed remaining deps so only the launcher create is new
    actions = f.run("default/test")
    assert ("create", "Job") in verbs(actions)
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    env = launcher.spec.template.main_container().env
    assert env["TPU_COORDINATOR_ADDRESS"].startswith("test-worker-0.")
    assert env["TPU_NUM_PROCESSES"] == "2"
    assert env["TPU_LAUNCHER"] == "1"
    assert "TPU_WORKER_ID" not in env
    # no kubectl-delivery init container (SURVEY §7: bootstrap path is env)
    assert launcher.spec.template.init_containers == []
    assert launcher.spec.backoff_limit == api.DEFAULT_BACKOFF_LIMIT


def test_launcher_created_cpu_variant():
    """ref TestWorkerReadyCPU variant (:765-789)."""
    f = Fixture()
    job = new_job(tpus=None)
    job.spec.processing_units = 2
    job.spec.processing_resource_type = api.RESOURCE_CPU
    job = f.seed(job)
    _seed_workers(f, job, replicas=1, ready=1)
    actions = f.run("default/test")
    assert ("create", "Job") in verbs(actions)


# ---------------------------------------------------------------------------
# status propagation (ref TestLauncherSucceeded/Failed :494-531)
# ---------------------------------------------------------------------------

def _seed_finished_launcher(f, job, *, succeeded):
    alloc = f.controller.allocate_processing_units(job, False)
    launcher = f.controller.new_launcher(job, alloc)
    launcher.status = JobStatus(
        succeeded=1 if succeeded else 0, failed=0 if succeeded else 1,
        completion_time=123.0,
    )
    return f.seed(launcher)


def test_launcher_succeeded_updates_status_and_scales_down():
    """ref TestLauncherSucceeded (:494-512) + TestShutdownWorker (:667-692):
    done → status Succeeded, workers scaled to 0."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_finished_launcher(f, job, succeeded=True)
    actions = f.run("default/test")
    # no ConfigMap/RBAC recreation when done (ref :468)
    assert ("create", "ConfigMap") not in verbs(actions)
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 0                       # ref :594-596
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.launcher_status == api.LAUNCHER_SUCCEEDED
    assert updated.status.completion_time == 123.0
    assert updated.status.is_done()
    assert updated.status.get_condition(api.COND_SUCCEEDED).status == "True"


def test_launcher_failed_updates_status():
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_finished_launcher(f, job, succeeded=False)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.launcher_status == api.LAUNCHER_FAILED
    assert updated.status.get_condition(api.COND_FAILED).status == "True"


def test_launcher_active_sets_running_condition_and_start_time():
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    alloc = f.controller.allocate_processing_units(job, False)
    launcher = f.controller.new_launcher(job, alloc)
    launcher.status = JobStatus(active=1, start_time=100.0)
    f.seed(launcher)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.launcher_status == api.LAUNCHER_ACTIVE
    assert updated.status.start_time == 100.0
    assert updated.status.get_condition(api.COND_RUNNING).status == "True"


def test_worker_replicas_status_tracks_ready():
    """ref updateMPIJobStatus worker readiness (:780-786)."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=2)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.worker_replicas == 2


def test_replica_statuses_track_launcher_and_workers():
    """v1alpha2 ReplicaStatus (common_types.go:68-80): per-role
    active/succeeded/failed counts reconciled into status."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_workers(f, job, replicas=2, ready=2)
    f.run("default/test")          # creates the launcher (workers ready)
    # play kubelet: launcher pod starts
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    launcher.status.active = 1
    f.api.update(launcher)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.replica_statuses["worker"].active == 2
    assert updated.status.replica_statuses["launcher"].active == 1

    # launcher completes → launcher succeeded=1, workers scale to 0
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    launcher.status.succeeded = 1
    launcher.status.active = 0
    f.api.update(launcher)
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    sts.status.ready_replicas = 0
    f.api.update(sts)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.replica_statuses["launcher"].succeeded == 1
    assert updated.status.replica_statuses["launcher"].active == 0
    assert updated.status.replica_statuses["worker"].active == 0


def test_launcher_on_master_pins_launcher_only():
    """ref types.go:90-94: launcherOnMaster → control-plane node selector +
    taint toleration on the launcher pod; workers keep TPU node selectors."""
    f = Fixture()
    job = f.seed(new_job(tpus=8, launcher_on_master=True))
    _seed_workers(f, job, replicas=2, ready=2)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    sel = launcher.spec.template.node_selector
    assert sel.get("node-role.kubernetes.io/control-plane") == ""
    assert any(t.get("key") == "node-role.kubernetes.io/control-plane"
               for t in launcher.spec.template.tolerations)
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert "node-role.kubernetes.io/control-plane" \
        not in sts.spec.template.node_selector
    assert sts.spec.template.tolerations == []


# ---------------------------------------------------------------------------
# ownership conflicts — one per child kind (ref :479-492, :598-710)
# ---------------------------------------------------------------------------

def _foreign_meta(name):
    return ObjectMeta(
        name=name, namespace="default",
        owner_references=[api.OwnerReference(
            api_version="v1", kind="Foreign", name="other", uid="foreign-uid",
        )],
    )


@pytest.mark.parametrize("make_obj", [
    lambda: ConfigMap(metadata=_foreign_meta("test" + CONFIG_SUFFIX)),
    lambda: ServiceAccount(metadata=_foreign_meta("test" + LAUNCHER_SUFFIX)),
    lambda: Role(metadata=_foreign_meta("test" + LAUNCHER_SUFFIX)),
    lambda: RoleBinding(metadata=_foreign_meta("test" + LAUNCHER_SUFFIX)),
    lambda: StatefulSet(metadata=_foreign_meta("test" + WORKER_SUFFIX)),
    lambda: Job(metadata=_foreign_meta("test" + LAUNCHER_SUFFIX)),
], ids=["configmap", "serviceaccount", "role", "rolebinding",
        "statefulset", "launcher-job"])
def test_foreign_ownership_refused(make_obj):
    """Adoption is refused, never forced (ref :641-645 and siblings); a
    Warning event is recorded (ref :539)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.seed(make_obj())
    f.run("default/test", expect_error=ForeignOwnershipError)
    assert any(e.type == "Warning" for e in f.controller.recorder.events)


# ---------------------------------------------------------------------------
# idempotence / drift repair (level-triggered model, SURVEY §3.2)
# ---------------------------------------------------------------------------

def test_second_sync_is_idempotent():
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    f.run("default/test")
    actions = f.run("default/test")
    # nothing to create or update on a converged state
    assert verbs(actions) == []


def test_replica_drift_is_repaired():
    """ref :748-756: update worker set if replica drift."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    alloc = f.controller.allocate_processing_units(job, False)
    sts = f.controller.new_worker(job, alloc)
    sts.spec.replicas = 5   # drifted
    f.seed(sts)
    actions = f.run("default/test")
    assert ("update", "StatefulSet") in verbs(actions)
    assert f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX).spec.replicas == 2


def test_configmap_drift_is_repaired():
    """The hostfile analogue is rewritten when contents drift
    (ref getOrCreateConfigMap :627-648)."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    cm = f.controller.new_config_map(
        job, f.controller.allocate_processing_units(job, False))
    cm.data = {"worker-hostnames": "stale\n"}
    f.seed(cm)
    actions = f.run("default/test")
    assert ("update", "ConfigMap") in verbs(actions)
    fixed = f.api.get("ConfigMap", "default", "test" + CONFIG_SUFFIX)
    assert "test-worker-0" in fixed.data["worker-hostnames"]


# ---------------------------------------------------------------------------
# event → queue plumbing (ref handleObject :811-844)
# ---------------------------------------------------------------------------

def _drain(queue):
    while True:
        key = queue.get(timeout=0)
        if key is None:
            return
        queue.done(key)


def test_dependent_event_enqueues_owner():
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _drain(f.controller.queue)
    sts = StatefulSet(metadata=ObjectMeta(
        name="test" + WORKER_SUFFIX, namespace="default",
        owner_references=owned(job),
    ), spec=StatefulSetSpec(replicas=2))
    f.api.create(sts)
    key = f.controller.queue.get(timeout=1)
    assert key == "default/test"


def test_orphan_event_ignored():
    f = Fixture()
    f.seed(new_job(tpus=8))
    _drain(f.controller.queue)
    f.api.create(StatefulSet(metadata=_foreign_meta("orphan")))
    assert f.controller.queue.get(timeout=0.05) is None


def test_admission_rejects_invalid_spec_at_create():
    """Invalid shapes fail at admission, not at runtime (SURVEY §7): the
    controller registers validate_spec as the CRD-schema analogue."""
    from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer as S
    f = Fixture()
    with pytest.raises(S.AdmissionError, match="slice chip count"):
        f.api.create(new_job(tpus=3))


def test_launcher_restart_policy_is_on_failure():
    """ref :1175-1177 — Never would make the first pod failure terminal,
    defeating backoffLimit."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    alloc = f.controller.allocate_processing_units(job, False)
    launcher = f.controller.new_launcher(job, alloc)
    assert launcher.spec.template.restart_policy == "OnFailure"


def test_per_worker_default_pairs_with_sizing_field():
    """tpus pairs with tpus_per_worker config; processing_units with
    processing_units_per_worker (ref :449-460)."""
    f = Fixture(tpus_per_worker=4, processing_units_per_worker=8)
    job = new_job(tpus=None)
    job.spec.processing_units = 16
    job.spec.processing_resource_type = api.RESOURCE_CPU
    alloc = f.controller.allocate_processing_units(job, False)
    assert alloc.worker_replicas == 2       # 16/8, not 16/4
    assert alloc.units_per_worker == 8


def test_workqueue_returns_due_rate_limited_item():
    """A due rate-limited item must be returned, not treated as timeout."""
    from mpi_operator_tpu.cluster.workqueue import RateLimitingQueue
    q = RateLimitingQueue(base_delay=0.01)
    q.add_rate_limited("ns/x")
    assert q.get(timeout=2.0) == "ns/x"


def test_cascade_delete_on_owner():
    """ref SURVEY §3.4: deletion is K8s GC via ownerReferences — the
    controller has no delete logic of its own."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    f.run("default/test")
    doomed = f.api.cascade_delete(job.metadata.uid)
    assert {k for k, _, _ in doomed} >= {
        "ConfigMap", "ServiceAccount", "Role", "RoleBinding", "StatefulSet",
    }


# ---------------------------------------------------------------------------
# gang restart (v1alpha2 RestartPolicy, common_types.go:131-156) and
# CleanPodPolicy (v1alpha2 types.go:55-66)
# ---------------------------------------------------------------------------

def _seed_failed_launcher(f, job, exit_code=None):
    alloc = f.controller.allocate_processing_units(job, False)
    launcher = f.controller.new_launcher(job, alloc)
    launcher.status = JobStatus(failed=1, completion_time=123.0,
                                exit_code=exit_code)
    return f.seed(launcher)


def test_restart_policy_never_is_terminal():
    """Default (v1alpha1 behavior): a failed launcher ends the job."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    _seed_failed_launcher(f, job)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.get_condition(api.COND_FAILED) is not None
    assert updated.status.restart_count == 0


def test_restart_policy_onfailure_recreates_launcher():
    f = Fixture()
    job = f.seed(new_job(tpus=8, restart_policy="OnFailure"))
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_failed_launcher(f, job, exit_code=1)
    f.run("default/test")
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.restart_count == 1
    assert updated.status.get_condition(api.COND_RESTARTING) is not None
    assert updated.status.get_condition(api.COND_FAILED) is None
    # the launcher was recreated fresh (workers were ready)
    fresh = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    assert fresh.status.failed == 0


def test_restart_policy_exitcode_distinguishes_permanent_and_retryable():
    # retryable (>=128): restart
    f = Fixture()
    job = f.seed(new_job(tpus=8, restart_policy="ExitCode"))
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_failed_launcher(f, job, exit_code=213)     # LAUNCHER_LOST_EXIT
    f.run("default/test")
    assert f.api.get(api.KIND, "default", "test").status.restart_count == 1

    # permanent (1-127): terminal
    f2 = Fixture()
    job2 = f2.seed(new_job(tpus=8, restart_policy="ExitCode"))
    _seed_failed_launcher(f2, job2, exit_code=2)
    f2.run("default/test")
    updated = f2.api.get(api.KIND, "default", "test")
    assert updated.status.restart_count == 0
    assert updated.status.get_condition(api.COND_FAILED) is not None


def test_restart_budget_exhaustion_fails_job():
    f = Fixture()
    job = new_job(tpus=8, restart_policy="OnFailure", backoff_limit=1)
    job = f.seed(job)
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_failed_launcher(f, job, exit_code=137)
    f.run("default/test")          # restart 1/1
    assert f.api.get(api.KIND, "default", "test").status.restart_count == 1
    # fail the recreated launcher too
    relaunched = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    relaunched.status = JobStatus(failed=1, exit_code=137)
    f.api.update(relaunched)
    f.run("default/test")          # budget exhausted → terminal
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.restart_count == 1
    assert updated.status.get_condition(api.COND_FAILED) is not None


def test_clean_pod_policy_none_keeps_workers():
    f = Fixture()
    job = f.seed(new_job(tpus=8, clean_pod_policy="None"))
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_finished_launcher(f, job, succeeded=True)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 2          # NOT scaled down
    assert f.api.get(api.KIND, "default",
                     "test").status.get_condition(api.COND_SUCCEEDED)


def test_clean_pod_policy_all_deletes_launcher_and_stays_done():
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    f = Fixture()
    job = f.seed(new_job(tpus=8, clean_pod_policy="All"))
    _seed_workers(f, job, replicas=2, ready=2)
    _seed_finished_launcher(f, job, succeeded=True)
    f.run("default/test")
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    # level-triggered: a later reconcile must NOT recreate the launcher
    # (terminal state lives in conditions now)
    f.run("default/test")
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 0
    # replicaStatuses must keep the terminal launcher counts, not flap to 0
    # after the launcher Job object is garbage-collected
    updated = f.api.get(api.KIND, "default", "test")
    assert updated.status.replica_statuses["launcher"].succeeded == 1


def test_restart_policy_validation():
    from mpi_operator_tpu.api.validation import ValidationError, validate_spec
    with pytest.raises(ValidationError, match="restartPolicy"):
        validate_spec(new_job(tpus=8, restart_policy="Always").spec)


def test_metrics_and_healthz_endpoints():
    """Operator observability (extension over the reference, which has
    glog only — SURVEY §5): /metrics exposes sync counters, queue depth,
    and per-phase job gauges in Prometheus text format; /healthz reports
    200 while starting AND while workers run (so a slow cache sync can't
    crash-loop the pod), 503 once a worker thread has died."""
    import urllib.error
    from urllib.request import urlopen

    from mpi_operator_tpu.controller.metrics import MetricsServer

    f = Fixture()
    f.seed(new_job("obs", tpus=8))
    f.controller.enqueue_tpu_job(f.api.get(api.KIND, "default", "obs"))
    assert f.controller.process_next_work_item(timeout=1.0)

    server = MetricsServer(f.controller, port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urlopen(base + "/metrics").read().decode()
        assert "tpu_operator_syncs_total 1" in body
        assert "tpu_operator_sync_errors_total 0" in body
        assert "tpu_operator_workqueue_depth" in body
        assert 'tpu_operator_jobs{phase="Created"} 1' in body
        # zero phases are emitted too — a vanishing series reads as "no
        # data", not 0
        assert 'tpu_operator_jobs{phase="Failed"} 0' in body
        assert "tpu_operator_job_restarts 0" in body

        # healthy while starting (run() not yet called): the probe must not
        # crash-loop a pod that is still syncing caches
        assert urlopen(base + "/healthz").status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urlopen(base + "/nope")
        assert exc.value.code == 404

        stop = f.controller.run(threadiness=1)
        assert urlopen(base + "/healthz").status == 200
        # dead worker threads flip liveness to 503
        stop.set()
        f.controller.queue.shut_down()
        for t in f.controller._threads:
            t.join(timeout=5)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urlopen(base + "/healthz")
        assert exc.value.code == 503
    finally:
        server.close()


def test_metrics_sync_error_counter():
    """A failing sync (foreign-owned child → ForeignOwnershipError) lands in
    sync_errors_total and the key re-enters the queue via the rate limiter."""
    from mpi_operator_tpu.controller.metrics import render_metrics

    f = Fixture()
    f.seed(new_job(tpus=8))
    f.seed(ConfigMap(metadata=_foreign_meta("test" + CONFIG_SUFFIX)))
    f.controller.enqueue_tpu_job(f.api.get(api.KIND, "default", "test"))
    assert f.controller.process_next_work_item(timeout=1.0)
    body = render_metrics(f.controller)
    assert "tpu_operator_sync_errors_total 1" in body


# ---------------------------------------------------------------------------
# real Kubernetes Events (ref StartRecordingToSink :165-172; Synced :518,
# ErrResourceExists :539)
# ---------------------------------------------------------------------------

def test_synced_event_posted_and_aggregated():
    """The recorder POSTs a core/v1 Event through the API server on every
    Synced, and a repeated identical event bumps count on the SAME Event
    object (client-go correlator aggregation) instead of flooding new
    ones."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    events = f.api.list("Event", "default")
    synced = [e for e in events if e.reason == "Synced"]
    assert len(synced) == 1
    ev = synced[0]
    assert ev.type == "Normal"
    assert ev.involved_object.kind == api.KIND
    assert ev.involved_object.name == "test"
    assert ev.involved_object.uid
    assert ev.count == 1
    assert ev.source_component == "tpu-operator"
    assert ev.first_timestamp and ev.last_timestamp

    f.run("default/test")                 # level-triggered re-sync
    events = f.api.list("Event", "default")
    synced = [e for e in events if e.reason == "Synced"]
    assert len(synced) == 1               # still one object...
    assert synced[0].count == 2           # ...with the count bumped
    assert synced[0].last_timestamp >= ev.last_timestamp


def test_ownership_conflict_event_posted():
    """The ErrResourceExists warning reaches the Events API (ref :539) so
    `kubectl describe tpujob` shows it while a user debugs a stuck job."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.seed(ConfigMap(metadata=_foreign_meta("test" + CONFIG_SUFFIX)))
    f.run("default/test", expect_error=ForeignOwnershipError)
    warnings = [e for e in f.api.list("Event", "default")
                if e.type == "Warning"]
    assert len(warnings) == 1
    assert warnings[0].reason == "ErrResourceExists"
    assert "test-config" in warnings[0].message


def test_event_posts_never_fail_reconcile():
    """A broken Events sink must not fail a sync — posting is best-effort
    observability (the reference's broadcaster is fire-and-forget too)."""
    class ExplodingSink:
        def __getattr__(self, _name):
            raise RuntimeError("sink down")

    f = Fixture()
    f.seed(new_job(tpus=8))
    f.controller.recorder.api = ExplodingSink()
    f.run("default/test")      # must not raise
    status = f.api.get(api.KIND, "default", "test").status
    assert status.conditions              # sync actually did its work


# ---------------------------------------------------------------------------
# worker failure visibility (v1alpha2 ReplicaStatus, common_types.go:68-80)
# ---------------------------------------------------------------------------

def _worker_pod(name, job="test", restarts=0, phase="Running"):
    from mpi_operator_tpu.cluster.resources import Pod, PodStatus
    return Pod(
        metadata=ObjectMeta(
            name=name, namespace="default",
            labels={"tpu_job_name": job, "tpu_job_role": "worker"}),
        status=PodStatus(phase=phase, restart_count=restarts),
    )


def test_worker_restarts_surface_in_replica_status():
    """A crash-looping worker must be visible: kubelet resurrects workers
    in place (RestartPolicy=Always) so the StatefulSet always looks
    healthy — the controller reads worker pods and surfaces restart
    DELTAS into replicaStatuses["worker"].failed, plus a Warning Event.
    (The first sync adopts current counts as the baseline, so crashes are
    counted from when this controller started watching.)"""
    f = Fixture()
    f.seed(new_job(tpus=8))
    _seed_workers(f, job=f.api.get(api.KIND, "default", "test"),
                  replicas=2, ready=2)
    f.seed(_worker_pod("test-worker-0", restarts=0))
    f.seed(_worker_pod("test-worker-1", restarts=0))
    f.run("default/test")                   # baseline sync
    pod = f.api.get("Pod", "default", "test-worker-0")
    pod.status.restart_count = 3            # three crashes since
    f.api.update(pod)
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 3
    assert st.replica_statuses["worker"].active == 2
    warnings = [e for e in f.controller.recorder.events
                if e.type == "Warning"]
    assert any(e.reason == "WorkerCrashLoop" for e in warnings)


def test_operator_restart_does_not_recount_crashes():
    """A fresh controller process must adopt current restart counts as the
    baseline instead of re-counting history into .failed (which would
    double the number on every operator redeploy)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    _seed_workers(f, job=f.api.get(api.KIND, "default", "test"),
                  replicas=2, ready=2)
    f.seed(_worker_pod("test-worker-0", restarts=0))
    f.run("default/test")                   # baseline
    pod = f.api.get("Pod", "default", "test-worker-0")
    pod.status.restart_count = 5
    f.api.update(pod)
    f.run("default/test")
    assert f.api.get(api.KIND, "default", "test") \
        .status.replica_statuses["worker"].failed == 5
    # "operator restart": a NEW controller over the same API server
    ctrl2 = TPUJobController(f.api)
    ctrl2.factory.start_all()
    ctrl2.sync_handler("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 5   # not 10


def test_healthy_workers_report_zero_failed():
    f = Fixture()
    f.seed(new_job(tpus=8))
    _seed_workers(f, job=f.api.get(api.KIND, "default", "test"),
                  replicas=2, ready=2)
    f.seed(_worker_pod("test-worker-0"))
    f.seed(_worker_pod("test-worker-1"))
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 0
    assert not any(e.reason == "WorkerCrashLoop"
                   for e in f.controller.recorder.events)


def test_failed_count_is_cumulative_across_pod_recreation():
    """Pod deletion resets kubelet restart counters; the recorded failed
    count is a true cumulative crash history — it neither regresses NOR
    hides fresh crashes of the replacement pod (per-pod uid-keyed restart
    baselines, not a high-water mark)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    _seed_workers(f, job=f.api.get(api.KIND, "default", "test"),
                  replicas=2, ready=2)
    f.seed(_worker_pod("test-worker-0", restarts=0))
    f.run("default/test")                              # baseline
    pod = f.api.get("Pod", "default", "test-worker-0")
    pod.status.restart_count = 4
    f.api.update(pod)
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 4
    f.api.delete("Pod", "default", "test-worker-0")   # pod recreated fresh
    f.seed(_worker_pod("test-worker-0", restarts=0))  # counter reset
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 4   # no regression
    # the REPLACEMENT crash-loops: its fresh restarts must still count
    pod = f.api.get("Pod", "default", "test-worker-0")
    pod.status.restart_count = 3
    f.api.update(pod)
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 7   # 4 + 3, cumulative


def test_foreign_pods_ignored_in_failure_count():
    """Pods of other jobs (or non-worker roles) don't pollute the count."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    _seed_workers(f, job=f.api.get(api.KIND, "default", "test"),
                  replicas=2, ready=2)
    f.seed(_worker_pod("other-worker-0", job="other", restarts=0))
    launcher_pod = _worker_pod("test-launcher-x", restarts=0)
    launcher_pod.metadata.labels["tpu_job_role"] = "launcher"
    f.seed(launcher_pod)
    f.run("default/test")                              # baseline
    for name in ("other-worker-0", "test-launcher-x"):
        pod = f.api.get("Pod", "default", name)
        pod.status.restart_count = 9                   # foreign crashes
        f.api.update(pod)
    f.run("default/test")
    st = f.api.get(api.KIND, "default", "test").status
    assert st.replica_statuses["worker"].failed == 0


# ---------------------------------------------------------------------------
# create-race read-through (real-cluster informer lag)
# ---------------------------------------------------------------------------

def test_create_race_resolved_by_read_through():
    """Against a real API server the informer lags its own writes by a
    watch round-trip: a child can exist server-side while the lister still
    misses it. The sync must read through (create → AlreadyExists → direct
    GET + ownership check) and converge in THIS pass instead of failing
    8-10 syncs on requeue backoff (which is what the reference does)."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    alloc = f.controller.allocate_processing_units(job, False)
    cm = f.controller.new_config_map(job, alloc)
    # plant the child server-side WITHOUT a watch notification — the
    # informer-lag state (white-box: the in-memory server's watch fanout
    # is synchronous, so this is the only way to simulate the lag)
    cm.metadata.resource_version = 999
    cm.metadata.uid = "uid-race"
    f.api._store[("ConfigMap", "default", "test" + CONFIG_SUFFIX)] = cm
    assert f.controller.configmap_lister.try_get(
        "default", "test" + CONFIG_SUFFIX) is None     # lister blind
    f.run("default/test")                              # must not raise
    st = f.api.get(api.KIND, "default", "test").status
    assert st.conditions                               # sync completed


def test_create_race_foreign_owner_still_refused():
    """Read-through must NOT become adoption: a same-named child owned by
    someone else still fails the sync (ref :641-645)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    foreign = ConfigMap(metadata=_foreign_meta("test" + CONFIG_SUFFIX))
    foreign.metadata.resource_version = 999
    foreign.metadata.uid = "uid-foreign"
    f.api._store[("ConfigMap", "default", "test" + CONFIG_SUFFIX)] = foreign
    f.run("default/test", expect_error=ForeignOwnershipError)


# ---------------------------------------------------------------------------
# TPU-health readiness gate (SURVEY §7 "Readiness vs ICI formation")
# ---------------------------------------------------------------------------

def test_worker_readiness_probe_injected():
    """TPU workers carry a readinessProbe checking the bootstrap's health
    marker, so ReadyReplicas (the launcher gate, ref :503-509) means "the
    TPU runtime enumerated its chips", not "the container started"."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    c = sts.spec.template.main_container()
    probe = c.readiness_probe
    assert probe is not None
    assert probe["exec"]["command"][-1] == "test -f /tmp/tpu-ready"
    assert probe["failureThreshold"] >= 30     # first libtpu init is slow
    assert c.env["TPU_READY_FILE"] == "/tmp/tpu-ready"
    assert c.env["TPU_EXPECTED_CHIPS"] == "4"  # tpus=8 / 2 workers


def test_cpu_workers_get_no_tpu_probe():
    """cpu-resource jobs have no TPU runtime to gate on."""
    f = Fixture()
    job = new_job(tpus=None)
    job.spec.replicas = 2
    job.spec.processing_resource_type = api.RESOURCE_CPU
    job.spec.template.main_container().limits = {"cpu": 2}
    f.seed(job)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    c = sts.spec.template.main_container()
    assert c.readiness_probe is None
    assert "TPU_READY_FILE" not in c.env


def test_user_supplied_probe_not_overwritten():
    """A user's own readinessProbe in the pod template wins — the operator
    only fills the gap."""
    f = Fixture()
    job = new_job(tpus=8)
    job.spec.template.main_container().readiness_probe = {
        "httpGet": {"path": "/healthz", "port": 9999}}
    f.seed(job)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    probe = sts.spec.template.main_container().readiness_probe
    assert probe == {"httpGet": {"path": "/healthz", "port": 9999}}


def test_health_gate_annotation_opt_out():
    """Worker images that never call mpi_operator_tpu.bootstrap can opt
    out of the TPU-health probe (they would otherwise sit NotReady
    forever, since nothing writes the marker)."""
    f = Fixture()
    job = new_job(tpus=8)
    job.metadata.annotations["tpu.kubeflow.org/health-gate"] = "false"
    f.seed(job)
    f.run("default/test")
    c = f.api.get("StatefulSet", "default",
                  "test" + WORKER_SUFFIX).spec.template.main_container()
    assert c.readiness_probe is None
    assert "TPU_READY_FILE" not in c.env


# ---------------------------------------------------------------------------
# multi-slice topology (SURVEY §7 "Multi-slice (DCN) bootstrap";
# VERDICT r02 missing #2 — the controller must actually PLACE slices)
# ---------------------------------------------------------------------------

def _two_slice_job(name="ms", tpus=16, num_slices=2):
    job = new_job(name=name, tpus=tpus)
    job.spec.num_slices = num_slices
    job.spec.slice_topology = "2x4"      # per-slice v5e-8
    return job


def test_multislice_materializes_per_slice_worker_groups():
    """numSlices=2, tpus=16, 4/worker → two StatefulSets of 2 workers
    each, named <job>-worker-s<k>, with slice-id env and a SHARED
    governing Service (pod names are unique across groups)."""
    f = Fixture()
    f.seed(_two_slice_job())
    f.run("default/ms")
    groups = []
    for k in (0, 1):
        sts = f.api.get("StatefulSet", "default", f"ms-worker-s{k}")
        groups.append(sts)
        assert sts.spec.replicas == 2
        assert sts.spec.service_name == "ms-worker"   # shared DNS backing
        c = sts.spec.template.main_container()
        assert c.env["TPU_SLICE_ID"] == str(k)
        assert c.env["MEGASCALE_SLICE_ID"] == str(k)
        assert c.env["MEGASCALE_NUM_SLICES"] == "2"
        assert c.env["TPU_WORKERS_PER_SLICE"] == "2"
        assert c.env["TPU_NUM_SLICES"] == "2"
        assert sts.spec.template.metadata.labels["tpu_job_slice"] == str(k)
        # each slice carries the per-slice topology selector
        assert sts.spec.template.node_selector[
            "cloud.google.com/gke-tpu-topology"] == "2x4"
    # the flat single-slice name must NOT exist
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    with pytest.raises(NotFoundError):
        f.api.get("StatefulSet", "default", "ms-worker")
    # megascale coordinator points at slice-0 worker-0
    c0 = groups[0].spec.template.main_container()
    assert c0.env["MEGASCALE_COORDINATOR_ADDRESS"].startswith(
        "ms-worker-s0-0.")


def test_multislice_configmap_is_rank_major():
    """worker-hostnames must list slice-0's workers first (global rank
    order = slice-major), and the role must name every pod of every
    slice — the hostfile-as-topology-truth analogue
    (ref mpi_job_controller.go:857-869)."""
    f = Fixture()
    f.seed(_two_slice_job())
    f.run("default/ms")
    cm = f.api.get("ConfigMap", "default", "ms" + CONFIG_SUFFIX)
    assert cm.data["worker-hostnames"] == (
        "ms-worker-s0-0.ms-worker.default.svc\n"
        "ms-worker-s0-1.ms-worker.default.svc\n"
        "ms-worker-s1-0.ms-worker.default.svc\n"
        "ms-worker-s1-1.ms-worker.default.svc\n"
    )
    assert cm.data["coordinator-address"] == (
        "ms-worker-s0-0.ms-worker.default.svc:8476")
    assert cm.data["num-slices"] == "2"
    assert cm.data["workers-per-slice"] == "2"
    assert cm.data["num-processes"] == "4"
    role = f.api.get("Role", "default", "ms-launcher")
    names = [n for rule in role.rules for n in rule.resource_names]
    for pod in ("ms-worker-s0-0", "ms-worker-s0-1",
                "ms-worker-s1-0", "ms-worker-s1-1"):
        assert pod in names


def test_multislice_launcher_gated_on_all_slices():
    """One Ready slice is NOT enough — the launcher must wait for every
    slice (a missing slice would hang the first cross-slice collective)."""
    f = Fixture()
    f.seed(_two_slice_job())
    f.run("default/ms")
    # slice 0 fully ready, slice 1 not
    s0 = f.api.get("StatefulSet", "default", "ms-worker-s0")
    s0.status = StatefulSetStatus(ready_replicas=2, replicas=2)
    f.api.update(s0)
    f.run("default/ms")
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "ms-launcher")
    # slice 1 comes up → launcher created
    s1 = f.api.get("StatefulSet", "default", "ms-worker-s1")
    s1.status = StatefulSetStatus(ready_replicas=2, replicas=2)
    f.api.update(s1)
    f.run("default/ms")
    f.api.get("Job", "default", "ms-launcher")      # exists now
    st = f.api.get(api.KIND, "default", "ms").status
    assert st.worker_replicas == 4                  # aggregated across slices


def test_multislice_scale_down_covers_all_groups():
    f = Fixture()
    f.seed(_two_slice_job())
    f.run("default/ms")
    for k in (0, 1):
        s = f.api.get("StatefulSet", "default", f"ms-worker-s{k}")
        s.status = StatefulSetStatus(ready_replicas=2, replicas=2)
        f.api.update(s)
    f.run("default/ms")
    launcher = f.api.get("Job", "default", "ms-launcher")
    launcher.status.succeeded = 1
    f.api.update_status(launcher)
    f.run("default/ms")
    for k in (0, 1):
        assert f.api.get("StatefulSet", "default",
                         f"ms-worker-s{k}").spec.replicas == 0


def test_multislice_indivisible_replicas_rejected_at_admission():
    """replicas mode: 3 workers cannot split into 2 slices — rejected at
    admission (fail at admission, not at runtime); the controller's
    allocate keeps the same check as a backstop."""
    f = Fixture()
    job = new_job(name="bad", tpus=None)
    job.spec.replicas = 3
    job.spec.num_slices = 2
    job.spec.template.main_container().limits = {api.RESOURCE_TPU: 4}
    with pytest.raises(InMemoryAPIServer.AdmissionError,
                       match="does not divide into 2 slices"):
        f.seed(job)


def test_discovery_init_container_wired():
    """--discovery-image injects the init container into WORKERS (they do
    the DNS rendezvous) and the LAUNCHER (ref kubectl-delivery injection
    :1106-1121), each with the ConfigMap mount its wait script reads."""
    f = Fixture(discovery_image="tpu-discovery:latest")
    f.seed(new_job(tpus=8))
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    sts.status = StatefulSetStatus(ready_replicas=2, replicas=2)
    f.api.update(sts)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    for tmpl in (sts.spec.template, launcher.spec.template):
        inits = tmpl.init_containers
        assert len(inits) == 1
        assert inits[0].image == "tpu-discovery:latest"
        assert inits[0].env["TPU_CONFIG_PATH"] == "/etc/tpu"
        assert inits[0].env["DISCOVERY_TIMEOUT"] == "300"
        assert {"name": "tpu-job-config",
                "mountPath": "/etc/tpu"} in inits[0].volume_mounts



def test_worker_service_drift_repaired():
    """Spec fixes must reach Services created by older operator versions
    (e.g. publishNotReadyAddresses — without the repair, pre-upgrade jobs
    stay DNS-deadlocked forever)."""
    f = Fixture()
    job = f.seed(new_job(tpus=8))
    f.run("default/test")
    svc = f.api.get("Service", "default", "test" + WORKER_SUFFIX)
    svc.publish_not_ready_addresses = False    # pre-fix operator wrote this
    f.api.update(svc)
    f.run("default/test")
    svc = f.api.get("Service", "default", "test" + WORKER_SUFFIX)
    assert svc.publish_not_ready_addresses is True


def test_resize_reconciles_worker_env_and_topology():
    """tpus 8→16 mid-run: the reference only fixes the replica count
    (:748-756), leaving surviving pods on stale TPU_NUM_PROCESSES/
    hostnames — a broken rendezvous after the gang restart. The full
    template reconciles, so the StatefulSet rolls every worker onto the
    new topology (checkpoint resume carries the run over)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 2
    assert sts.spec.template.main_container().env["TPU_NUM_PROCESSES"] == "2"

    job = f.api.get(api.KIND, "default", "test")
    job.spec.tpus = 16
    f.api.update(job)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.replicas == 4
    env = sts.spec.template.main_container().env
    assert env["TPU_NUM_PROCESSES"] == "4"
    assert env["TPU_WORKER_HOSTNAMES"].count(",") == 3     # 4 workers
    cm = f.api.get("ConfigMap", "default", "test" + CONFIG_SUFFIX)
    assert cm.data["num-processes"] == "4"                 # consistent


def test_template_edit_propagates_to_workers():
    """User edits the pod template image: the worker StatefulSet follows
    (the reference never reconciles templates at all)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    job.spec.template.main_container().image = "tpu-bench:v2"
    f.api.update(job)
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.template.main_container().image == "tpu-bench:v2"


def test_stable_spec_causes_no_update_churn():
    """Template reconciliation must be change-driven: an unchanged spec
    re-synced twice emits NO StatefulSet update actions (level-triggered
    idempotence, ref test style :533-562)."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    actions = f.run("default/test")       # second sync, nothing changed
    assert ("update", "StatefulSet") not in verbs(actions)


def test_resize_replaces_launcher_without_burning_restart_budget():
    """A running launcher carries the old-topology env (Job pod templates
    are immutable): resize must replace it OUTSIDE the failure path — no
    restart_count bump, no terminal failure under restart_policy=Never —
    and the readiness gate recreates it with the new env."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    _seed_ready_workers(f, "test" + WORKER_SUFFIX, 2)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    assert launcher.spec.template.main_container().env[
        "TPU_NUM_PROCESSES"] == "2"

    job = f.api.get(api.KIND, "default", "test")
    job.spec.tpus = 16
    f.api.update(job)
    f.run("default/test")
    # old launcher deleted, none recreated yet (workers not Ready at the
    # new size), and the restart budget untouched
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    st = f.api.get(api.KIND, "default", "test").status
    assert st.restart_count == 0
    assert st.get_condition(api.COND_FAILED) is None
    # gang comes up at the new size → launcher recreated with new env
    _seed_ready_workers(f, "test" + WORKER_SUFFIX, 4)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    assert launcher.spec.template.main_container().env[
        "TPU_NUM_PROCESSES"] == "4"
    assert any(e.reason == "TPUJobResized"
               for e in f.controller.recorder.events)


def _seed_ready_workers(f, name, n):
    sts = f.api.get("StatefulSet", "default", name)
    sts.status = StatefulSetStatus(ready_replicas=n, replicas=n)
    f.api.update(sts)


def test_numslices_downsize_prunes_orphaned_groups():
    """numSlices 2→1: the old per-slice groups must be deleted — their
    stale-topology pods would keep matching the shared Service selector
    and dial the new coordinator with the old world size."""
    f = Fixture()
    job = new_job(name="ms2", tpus=16)
    job.spec.num_slices = 2
    job.spec.slice_topology = "2x4"
    f.seed(job)
    f.run("default/ms2")
    f.api.get("StatefulSet", "default", "ms2-worker-s0")
    f.api.get("StatefulSet", "default", "ms2-worker-s1")

    job = f.api.get(api.KIND, "default", "ms2")
    job.spec.num_slices = 1
    job.spec.slice_topology = "4x4"
    f.api.update(job)
    f.run("default/ms2")
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    sts = f.api.get("StatefulSet", "default", "ms2-worker")   # flat group
    assert sts.spec.replicas == 4
    with pytest.raises(NotFoundError):
        f.api.get("StatefulSet", "default", "ms2-worker-s0")
    with pytest.raises(NotFoundError):
        f.api.get("StatefulSet", "default", "ms2-worker-s1")


def test_resize_gang_deletes_worker_pods():
    """OnDelete update strategy: the controller must delete the worker
    pods itself after a template change, or nothing ever restarts them
    onto the new topology."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    sts = f.api.get("StatefulSet", "default", "test" + WORKER_SUFFIX)
    assert sts.spec.update_strategy == "OnDelete"
    f.seed(_worker_pod("test-worker-0"))
    f.seed(_worker_pod("test-worker-1"))
    job = f.api.get(api.KIND, "default", "test")
    job.spec.tpus = 16
    f.api.update(job)
    f.run("default/test")
    assert f.api.list("Pod", "default",
                      label_selector="tpu_job_name=test") == []


def test_template_edit_defers_launcher_until_gang_restarts():
    """Same-world-size template edit: the StatefulSet status still shows
    the PRE-deletion ready count during the resize sync — the launcher
    must NOT be recreated against a gang that was just deleted."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    _seed_ready_workers(f, "test" + WORKER_SUFFIX, 2)
    f.run("default/test")
    f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)   # running

    job = f.api.get(api.KIND, "default", "test")
    job.spec.template.main_container().image = "tpu-bench:v2"
    f.api.update(job)
    f.run("default/test")       # resize sync: ready counts are stale lies
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    # next sync with the gang genuinely Ready → launcher reborn on v2
    _seed_ready_workers(f, "test" + WORKER_SUFFIX, 2)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    assert launcher.spec.template.main_container().image == "tpu-bench:v2"


def test_failed_gang_deletion_is_retried():
    """The restart signal is level-triggered: a failed pod deletion must
    leave the template-hash annotation stale so a LATER sync retries —
    under OnDelete nothing else ever replaces the old pods."""
    f = Fixture()
    f.seed(new_job(tpus=8))
    f.run("default/test")
    f.seed(_worker_pod("test-worker-0"))
    f.seed(_worker_pod("test-worker-1"))
    job = f.api.get(api.KIND, "default", "test")
    job.spec.template.main_container().image = "tpu-bench:v2"
    f.api.update(job)

    real_list = f.api.list
    def broken_list(kind, *a, **k):
        if kind == "Pod":
            raise RuntimeError("transient apiserver hiccup")
        return real_list(kind, *a, **k)
    f.api.list = broken_list
    f.run("default/test")                    # deletion fails, logged
    f.api.list = real_list
    assert f.api.list("Pod", "default",
                      label_selector="tpu_job_name=test") != []
    f.run("default/test")                    # retried and succeeds
    assert f.api.list("Pod", "default",
                      label_selector="tpu_job_name=test") == []


# ---------------------------------------------------------------------------
# job packing (controller/packing.py: shared gang for compatible jobs)
# ---------------------------------------------------------------------------

def _pack_job(name, ts, tpus=8, group="sweep"):
    job = new_job(name=name, tpus=tpus, pack_group=group)
    job.metadata.creation_timestamp = ts
    return job


def test_pack_leader_gang_carries_membership_env():
    """Oldest compatible member leads: its worker gang (and launcher)
    carry the TPU_PACK_* identity env naming every packed job."""
    f = Fixture()
    f.seed(_pack_job("a", 100.0))
    f.seed(_pack_job("b", 200.0))
    actions = f.run("default/a")
    assert ("create", "StatefulSet") in verbs(actions)
    sts = f.api.get("StatefulSet", "default", "a" + WORKER_SUFFIX)
    env = sts.spec.template.main_container().env
    assert env["TPU_PACK_GROUP"] == "sweep"
    assert env["TPU_PACK_JOBS"] == "a,b"      # leader first = replica 0
    assert env["TPU_PACK_K"] == "2"
    job = f.api.get(api.KIND, "default", "a")
    cond = job.status.get_condition("Packed")
    assert cond is not None and cond.reason == "PackLeader"
    # launcher (gated on Ready workers) inherits the same identity env
    _seed_ready_workers(f, "a" + WORKER_SUFFIX, 2)
    f.run("default/a")
    launcher = f.api.get("Job", "default", "a" + LAUNCHER_SUFFIX)
    assert launcher.spec.template.main_container().env[
        "TPU_PACK_JOBS"] == "a,b"


def test_packed_member_owns_nothing():
    """A non-leader's sync short-circuits: no gang, no launcher — only a
    Packed condition naming the leader and its replica index."""
    f = Fixture()
    f.seed(_pack_job("a", 100.0))
    f.seed(_pack_job("b", 200.0))
    actions = f.run("default/b")
    assert verbs(actions) == [("update-status", "TPUJob")]
    job = f.api.get(api.KIND, "default", "b")
    cond = job.status.get_condition("Packed")
    assert cond is not None and cond.reason == "PackedWithLeader"
    assert "'a'" in cond.message and "replica 1 of 2" in cond.message
    # idempotent: a second sync emits nothing
    assert f.run("default/b") == []


def test_pack_requires_identical_resource_shape():
    """Same group, different shape (tpus=16): NOT forced into the pack —
    it leads its own shape-class with no pack env (a gang of one)."""
    f = Fixture()
    f.seed(_pack_job("a", 100.0))
    f.seed(_pack_job("b", 200.0))
    f.seed(_pack_job("big", 50.0, tpus=16))   # oldest overall, wrong shape
    f.run("default/a")
    env = f.api.get("StatefulSet", "default",
                    "a" + WORKER_SUFFIX).spec.template.main_container().env
    assert env["TPU_PACK_JOBS"] == "a,b"      # big excluded despite age
    f.run("default/big")
    env = f.api.get("StatefulSet", "default",
                    "big" + WORKER_SUFFIX).spec.template.main_container().env
    assert "TPU_PACK_GROUP" not in env        # solo leader: template as-is


def test_pack_membership_change_is_a_template_edit():
    """Adding a member to a running solo leader rewrites the worker env —
    an ordinary level-triggered template drift, so the gang restarts on
    the new member list. A member finishing shrinks it back."""
    f = Fixture()
    f.seed(_pack_job("a", 100.0))
    f.run("default/a")
    env = f.api.get("StatefulSet", "default",
                    "a" + WORKER_SUFFIX).spec.template.main_container().env
    assert "TPU_PACK_GROUP" not in env        # pack of one: no env at all
    f.seed(_pack_job("b", 200.0))
    f.run("default/a")
    env = f.api.get("StatefulSet", "default",
                    "a" + WORKER_SUFFIX).spec.template.main_container().env
    assert env["TPU_PACK_JOBS"] == "a,b"
    # b finishes: it drops out of the plan and the env shrinks again
    b = f.api.get(api.KIND, "default", "b")
    b.status.set_condition(api.JobCondition(
        api.COND_SUCCEEDED, "True", "Done", "done"))
    f.api.update_status(b)
    f.run("default/a")
    env = f.api.get("StatefulSet", "default",
                    "a" + WORKER_SUFFIX).spec.template.main_container().env
    assert "TPU_PACK_GROUP" not in env


def test_packed_member_tears_down_pre_packing_resources():
    """b ran standalone first (created its own gang), THEN an older peer
    appeared (lister lag): b's next sync deletes its launcher/workers and
    defers to the leader."""
    f = Fixture()
    f.seed(_pack_job("b", 200.0))
    f.run("default/b")                        # standalone life: owns a gang
    assert f.api.get("StatefulSet", "default", "b" + WORKER_SUFFIX)
    f.seed(_pack_job("a", 100.0))             # older peer appears
    actions = f.run("default/b")
    assert ("delete", "StatefulSet") in verbs(actions)
    assert ("update-status", "TPUJob") in verbs(actions)

# ---------------------------------------------------------------------------
# disaggregated serving role pools (spec.serving; serve/engine.py DisaggEngine)
# ---------------------------------------------------------------------------

def _serving_job(name="test", tpus=16, prefill=3, decode=1, **kw):
    return new_job(name=name, tpus=tpus,
                   serving=api.ServingSpec(prefill_replicas=prefill,
                                           decode_replicas=decode), **kw)


def test_serving_stands_up_both_role_pools():
    """One TPUJob spec materializes TWO worker StatefulSets — the
    reference's heterogeneous-roles trick (launcher vs worker) extended
    to prefill vs decode. Each pool carries its role + both pools' peer
    addresses in env, and the pool label for per-pool federation."""
    from mpi_operator_tpu.controller.controller import (
        DECODE_SUFFIX, KV_TRANSFER_PORT, LABEL_SERVE_ROLE, PREFILL_SUFFIX,
    )
    f = Fixture()
    f.seed(_serving_job())            # 16 chips / 4 per worker = 4 workers
    f.run("default/test")
    pre = f.api.get("StatefulSet", "default", "test" + PREFILL_SUFFIX)
    dec = f.api.get("StatefulSet", "default", "test" + DECODE_SUFFIX)
    assert pre.spec.replicas == 3 and dec.spec.replicas == 1
    for sts, role in ((pre, "prefill"), (dec, "decode")):
        env = sts.spec.template.main_container().env
        assert env["TPU_SERVE_ROLE"] == role
        assert env["TPU_SERVE_PREFILL_HOSTS"] == (
            "test-prefill-0.test-worker.default.svc,"
            "test-prefill-1.test-worker.default.svc,"
            "test-prefill-2.test-worker.default.svc")
        assert env["TPU_SERVE_DECODE_HOSTS"] == (
            "test-decode-0.test-worker.default.svc")
        assert env["TPU_SERVE_KV_PORT"] == str(KV_TRANSFER_PORT)
        assert sts.spec.template.metadata.labels[LABEL_SERVE_ROLE] == role
        # both pools still match the shared governing Service selector
        assert sts.spec.template.metadata.labels["tpu_job_role"] == "worker"
    # discovery data is prefill-major and records the split
    cm = f.api.get("ConfigMap", "default", "test" + CONFIG_SUFFIX)
    assert cm.data["worker-hostnames"].splitlines()[0].startswith(
        "test-prefill-0.")
    assert cm.data["serving-prefill-replicas"] == "3"
    assert cm.data["serving-decode-replicas"] == "1"


def test_serving_launcher_gated_on_both_pools():
    """The readiness gate spans BOTH pools (total ready == worker
    replicas); the launcher — the serving router — gets the peer host
    lists but no role of its own."""
    from mpi_operator_tpu.controller.controller import (
        DECODE_SUFFIX, PREFILL_SUFFIX,
    )
    f = Fixture()
    f.seed(_serving_job())
    f.run("default/test")
    _seed_ready_workers(f, "test" + PREFILL_SUFFIX, 3)
    f.run("default/test")             # decode pool not Ready yet
    from mpi_operator_tpu.cluster.apiserver import NotFoundError
    with pytest.raises(NotFoundError):
        f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    _seed_ready_workers(f, "test" + DECODE_SUFFIX, 1)
    f.run("default/test")
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    env = launcher.spec.template.main_container().env
    assert "TPU_SERVE_ROLE" not in env
    assert env["TPU_SERVE_PREFILL_HOSTS"].count("test-prefill-") == 3
    assert env["TPU_SERVE_DECODE_HOSTS"] == (
        "test-decode-0.test-worker.default.svc")


def test_serving_pool_split_change_is_a_gang_restart():
    """Re-partitioning 3/1 -> 2/2 at the same chip count changes every
    pod's peer env — template drift on BOTH pools, so the change rides
    the template hash as one ordinary level-triggered gang restart."""
    from mpi_operator_tpu.controller.controller import (
        DECODE_SUFFIX, PREFILL_SUFFIX,
    )
    f = Fixture()
    f.seed(_serving_job())
    f.run("default/test")
    job = f.api.get(api.KIND, "default", "test")
    job.spec.serving = api.ServingSpec(prefill_replicas=2, decode_replicas=2)
    f.api.update(job)
    f.run("default/test")
    pre = f.api.get("StatefulSet", "default", "test" + PREFILL_SUFFIX)
    dec = f.api.get("StatefulSet", "default", "test" + DECODE_SUFFIX)
    assert pre.spec.replicas == 2 and dec.spec.replicas == 2
    assert dec.spec.template.main_container().env[
        "TPU_SERVE_DECODE_HOSTS"].count("test-decode-") == 2
    assert any(e.reason == "TPUJobResized"
               for e in f.controller.recorder.events)


def test_serving_scales_down_both_pools_when_done():
    from mpi_operator_tpu.controller.controller import (
        DECODE_SUFFIX, PREFILL_SUFFIX,
    )
    f = Fixture()
    f.seed(_serving_job())
    f.run("default/test")
    _seed_ready_workers(f, "test" + PREFILL_SUFFIX, 3)
    _seed_ready_workers(f, "test" + DECODE_SUFFIX, 1)
    f.run("default/test")                         # creates the launcher
    launcher = f.api.get("Job", "default", "test" + LAUNCHER_SUFFIX)
    launcher.status = JobStatus(succeeded=1, completion_time=123.0)
    f.api.update(launcher)
    f.run("default/test")
    assert f.api.get("StatefulSet", "default",
                     "test" + PREFILL_SUFFIX).spec.replicas == 0
    assert f.api.get("StatefulSet", "default",
                     "test" + DECODE_SUFFIX).spec.replicas == 0


def test_serving_admission_rejects_bad_pool_split():
    """Pool counts must re-partition the derived worker count exactly —
    and serving composes with neither elastic nor packing."""
    from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer as S
    f = Fixture()
    with pytest.raises(S.AdmissionError, match="prefillReplicas"):
        # explicit per-worker: admission can derive 4 workers != 3 pooled
        f.api.create(_serving_job(prefill=2, decode=1, tpus_per_worker=4))
    with pytest.raises(S.AdmissionError, match="elastic"):
        f.api.create(_serving_job(elastic=True))
    with pytest.raises(S.AdmissionError, match="packGroup"):
        f.api.create(_serving_job(pack_group="sweep"))
    with pytest.raises(S.AdmissionError, match="decodeReplicas"):
        f.api.create(_serving_job(prefill=4, decode=0))
    # flag-default per-worker count: only the controller can derive the
    # worker count — the backstop converges to Failed/InvalidTPUJobSpec
    f.seed(_serving_job(prefill=2, decode=1))
    f.run("default/test")
    cond = f.api.get(api.KIND, "default", "test").status.get_condition(
        api.COND_FAILED)
    assert cond is not None and cond.reason == "InvalidTPUJobSpec"
    assert "prefillReplicas" in cond.message


# ---------------------------------------------------------------------------
# pack-aware slice quota accounting (controller/packing.py slices_used)
# ---------------------------------------------------------------------------

def test_slice_quota_counts_packed_gang_once():
    """Two packed members share ONE physical gang: quota accounting must
    charge their slice once (via the leader), not once per member job —
    the naive per-job sum overcharges by k-1 slices per gang."""
    f = Fixture()
    f.seed(_pack_job("a", 100.0))
    f.seed(_pack_job("b", 200.0))
    f.seed(new_job(name="solo", tpus=8))
    assert f.controller.slices_in_use() == 2      # pack(a,b) + solo
    # a member finishing doesn't change the count (its gang was never
    # separately charged); the LEADER finishing releases the pack's slice
    b = f.api.get(api.KIND, "default", "b")
    b.status.set_condition(api.JobCondition(
        api.COND_SUCCEEDED, "True", "Done", "done"))
    f.api.update_status(b)
    assert f.controller.slices_in_use() == 2
    a = f.api.get(api.KIND, "default", "a")
    a.status.set_condition(api.JobCondition(
        api.COND_SUCCEEDED, "True", "Done", "done"))
    f.api.update_status(a)
    assert f.controller.slices_in_use() == 1      # solo only


def test_slice_quota_multi_slice_and_metrics_surface():
    """A multi-slice job charges num_slices; the gauge rides the operator
    /metrics scrape so a cluster quota check can consume it."""
    from mpi_operator_tpu.controller.metrics import render_metrics
    f = Fixture()
    f.seed(new_job(name="ms", tpus=16, num_slices=2))
    f.seed(_pack_job("a", 100.0))
    f.seed(_pack_job("b", 200.0))
    assert f.controller.slices_in_use() == 3      # 2 + pack(a,b)
    assert "tpu_operator_slices_in_use 3" in render_metrics(f.controller)
