"""Input-pipeline tests: synthetic stream + the npy-shard real-data path
(the --data-dir surface of the benchmark, ref
examples/tensorflow-benchmarks-imagenet.yaml:32-45)."""
import jax
import jax.numpy as jnp
import numpy as np

from mpi_operator_tpu.data import (
    NpyImageDataset, SyntheticImageDataset, write_npy_shard)


def test_synthetic_dataset_stream_and_shapes():
    ds = SyntheticImageDataset(8, image_size=16, num_classes=10,
                               dtype=jnp.float32)
    it = iter(ds)
    x1, y1 = next(it)
    x2, y2 = next(it)
    assert x1.shape == (8, 16, 16, 3) and y1.shape == (8,)
    assert not bool(jnp.all(x1 == x2))        # stream advances


def test_npy_dataset_reads_shards(tmp_path):
    rng = np.random.RandomState(0)
    for stem in ("a", "b"):
        write_npy_shard(str(tmp_path), stem,
                        rng.randint(0, 255, (10, 8, 8, 3), np.uint8),
                        rng.randint(0, 10, (10,), np.int64))
    ds = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                         dtype=jnp.float32)
    try:
        seen_labels = []
        for _ in range(6):                    # > one epoch (2×2 batches)
            x, y = next(ds)
            assert x.shape == (4, 8, 8, 3)
            assert x.dtype == jnp.float32
            # normalized: mean far below the raw 0-255 range
            assert float(jnp.abs(x).mean()) < 5.0
            assert y.shape == (4,) and y.dtype == jnp.int32
            seen_labels.append(np.asarray(y))
        assert any(l.size for l in seen_labels)
    finally:
        ds.close()


def test_npy_dataset_missing_dir_raises(tmp_path):
    import pytest
    with pytest.raises(FileNotFoundError):
        NpyImageDataset(str(tmp_path), batch_size=4)


def test_benchmark_with_data_dir(tmp_path):
    """run_benchmark honors data_dir end-to-end (the reviewed regression:
    --data-dir was parsed but silently ignored)."""
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "s",
                    rng.randint(0, 255, (64, 32, 32, 3), np.uint8),
                    rng.randint(0, 1000, (64,), np.int64))
    from mpi_operator_tpu.examples.benchmark import run_benchmark
    _state, metrics = run_benchmark(
        model_name="resnet18", batch_per_device=1, num_steps=2,
        warmup_steps=1, image_size=32, dtype_name="float32",
        data_dir=str(tmp_path), log=lambda s: None)
    assert metrics["steps"] == 2
    assert np.isfinite(metrics["final_loss"])


def test_npy_dataset_rejects_undersized_shards(tmp_path):
    import pytest
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "tiny",
                    rng.randint(0, 255, (3, 8, 8, 3), np.uint8),
                    rng.randint(0, 10, (3,), np.int64))
    with pytest.raises(ValueError, match="smaller"):
        NpyImageDataset(str(tmp_path), batch_size=8, image_size=8)


def test_npy_dataset_close_stops_feeder(tmp_path):
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "s",
                    rng.randint(0, 255, (32, 8, 8, 3), np.uint8),
                    rng.randint(0, 10, (32,), np.int64))
    ds = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                         dtype=jnp.float32, prefetch=1)
    next(ds)
    ds.close()
    assert not ds._thread.is_alive()
