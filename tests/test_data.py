"""Input-pipeline tests: synthetic stream + the npy-shard real-data path
(the --data-dir surface of the benchmark, ref
examples/tensorflow-benchmarks-imagenet.yaml:32-45)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.data import (
    NpyImageDataset, SyntheticImageDataset, write_npy_shard)


def test_synthetic_dataset_stream_and_shapes():
    ds = SyntheticImageDataset(8, image_size=16, num_classes=10,
                               dtype=jnp.float32)
    it = iter(ds)
    x1, y1 = next(it)
    x2, y2 = next(it)
    assert x1.shape == (8, 16, 16, 3) and y1.shape == (8,)
    assert not bool(jnp.all(x1 == x2))        # stream advances


def test_npy_dataset_reads_shards(tmp_path):
    rng = np.random.RandomState(0)
    for stem in ("a", "b"):
        write_npy_shard(str(tmp_path), stem,
                        rng.randint(0, 255, (10, 8, 8, 3), np.uint8),
                        rng.randint(0, 10, (10,), np.int64))
    ds = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                         dtype=jnp.float32)
    try:
        seen_labels = []
        for _ in range(6):                    # > one epoch (2×2 batches)
            x, y = next(ds)
            assert x.shape == (4, 8, 8, 3)
            assert x.dtype == jnp.float32
            # normalized: mean far below the raw 0-255 range
            assert float(jnp.abs(x).mean()) < 5.0
            assert y.shape == (4,) and y.dtype == jnp.int32
            seen_labels.append(np.asarray(y))
        assert any(l.size for l in seen_labels)
    finally:
        ds.close()


def test_npy_dataset_missing_dir_raises(tmp_path):
    import pytest
    with pytest.raises(FileNotFoundError):
        NpyImageDataset(str(tmp_path), batch_size=4)


def test_benchmark_with_data_dir(tmp_path):
    """run_benchmark honors data_dir end-to-end (the reviewed regression:
    --data-dir was parsed but silently ignored)."""
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "s",
                    rng.randint(0, 255, (64, 32, 32, 3), np.uint8),
                    rng.randint(0, 1000, (64,), np.int64))
    from mpi_operator_tpu.examples.benchmark import run_benchmark
    _state, metrics = run_benchmark(
        model_name="resnet18", batch_per_device=1, num_steps=2,
        warmup_steps=1, image_size=32, dtype_name="float32",
        data_dir=str(tmp_path), log=lambda s: None)
    assert metrics["steps"] == 2
    assert np.isfinite(metrics["final_loss"])


def test_npy_dataset_rejects_undersized_shards(tmp_path):
    import pytest
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "tiny",
                    rng.randint(0, 255, (3, 8, 8, 3), np.uint8),
                    rng.randint(0, 10, (3,), np.int64))
    with pytest.raises(ValueError, match="smaller"):
        NpyImageDataset(str(tmp_path), batch_size=8, image_size=8)


def test_npy_dataset_close_stops_feeder(tmp_path):
    rng = np.random.RandomState(0)
    write_npy_shard(str(tmp_path), "s",
                    rng.randint(0, 255, (32, 8, 8, 3), np.uint8),
                    rng.randint(0, 10, (32,), np.int64))
    ds = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                         dtype=jnp.float32, prefetch=1)
    next(ds)
    ds.close()
    assert not ds._thread.is_alive()


# ---------------------------------------------------------------------------
# native C++ loader (mpi_operator_tpu/native)
# ---------------------------------------------------------------------------

class TestNativeLoader:
    def _shard(self, tmp_path, n=12, hw=8, dtype=np.uint8):
        from mpi_operator_tpu.data.imagefolder import write_npy_shard
        rng = np.random.RandomState(0)
        if dtype == np.uint8:
            images = rng.randint(0, 256, (n, hw, hw, 3)).astype(np.uint8)
        else:
            images = rng.randn(n, hw, hw, 3).astype(np.float32)
        labels = rng.randint(0, 10, (n,)).astype(np.int64)
        write_npy_shard(str(tmp_path), "s0", images, labels)
        return images, labels

    @pytest.mark.parametrize("src_dtype", [np.uint8, np.float32])
    def test_matches_python_normalization(self, tmp_path, src_dtype):
        from mpi_operator_tpu.data.imagefolder import _MEAN, _STD
        from mpi_operator_tpu.native import NativeShardLoader, native_available
        if not native_available():
            pytest.skip("no g++ available")
        images, labels = self._shard(tmp_path, dtype=src_dtype)
        shards = [(str(tmp_path / "s0_images.npy"),
                   str(tmp_path / "s0_labels.npy"))]
        loader = NativeShardLoader(shards, batch_size=4,
                                   image_shape=(8, 8, 3), dtype="float32",
                                   mean=_MEAN.tolist(), std=_STD.tolist(),
                                   seed=0)
        img, lbl = next(loader)
        ref = (images[:4].astype(np.float32) - _MEAN) / _STD
        np.testing.assert_allclose(img, ref, atol=1e-5)
        np.testing.assert_array_equal(lbl, labels[:4].astype(np.int32))
        # second batch continues through the shard
        img2, _ = next(loader)
        ref2 = (images[4:8].astype(np.float32) - _MEAN) / _STD
        np.testing.assert_allclose(img2, ref2, atol=1e-5)
        loader.close()

    def test_bf16_output_rounds_correctly(self, tmp_path):
        import ml_dtypes

        from mpi_operator_tpu.data.imagefolder import _MEAN, _STD
        from mpi_operator_tpu.native import NativeShardLoader, native_available
        if not native_available():
            pytest.skip("no g++ available")
        images, _ = self._shard(tmp_path)
        shards = [(str(tmp_path / "s0_images.npy"),
                   str(tmp_path / "s0_labels.npy"))]
        loader = NativeShardLoader(shards, batch_size=4,
                                   image_shape=(8, 8, 3), dtype="bfloat16",
                                   mean=_MEAN.tolist(), std=_STD.tolist())
        img, _ = next(loader)
        assert img.dtype == np.dtype(ml_dtypes.bfloat16)
        ref = (((images[:4].astype(np.float32) - _MEAN) / _STD)
               .astype(ml_dtypes.bfloat16))
        np.testing.assert_array_equal(
            img.view(np.uint16), ref.view(np.uint16))
        loader.close()

    def test_dataset_uses_native_path(self, tmp_path):
        from mpi_operator_tpu.data.imagefolder import NpyImageDataset
        from mpi_operator_tpu.native import native_available
        if not native_available():
            pytest.skip("no g++ available")
        self._shard(tmp_path, n=16)
        ds = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                             dtype=jnp.float32, use_native="always")
        assert ds._native is not None
        images, labels = next(ds)
        assert images.shape == (4, 8, 8, 3)
        assert labels.shape == (4,)
        assert bool(jnp.isfinite(images).all())
        ds.close()

    def test_native_and_python_paths_agree(self, tmp_path):
        from mpi_operator_tpu.data.imagefolder import NpyImageDataset
        from mpi_operator_tpu.native import native_available
        if not native_available():
            pytest.skip("no g++ available")
        self._shard(tmp_path, n=16)
        a = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                            dtype=jnp.float32, use_native="always")
        b = NpyImageDataset(str(tmp_path), batch_size=4, image_size=8,
                            dtype=jnp.float32, use_native="never")
        # single shard: identical deterministic order
        for _ in range(4):
            ia, la = next(a)
            ib, lb = next(b)
            np.testing.assert_allclose(np.asarray(ia), np.asarray(ib),
                                       atol=1e-5)
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        a.close()
        b.close()

    def test_shape_mismatch_rejected_not_overflowed(self, tmp_path):
        """An RGBA (or wrong-resolution) shard must fail nsl_open with a
        clean error — the destination buffer is sized from the requested
        shape, so accepting the shard would overflow it."""
        from mpi_operator_tpu.native import NativeShardLoader, native_available
        if not native_available():
            pytest.skip("no g++ available")
        from mpi_operator_tpu.data.imagefolder import write_npy_shard
        rng = np.random.RandomState(0)
        write_npy_shard(str(tmp_path), "s0",
                        rng.randint(0, 256, (8, 8, 8, 4)).astype(np.uint8),
                        rng.randint(0, 10, (8,)).astype(np.int64))
        shards = [(str(tmp_path / "s0_images.npy"),
                   str(tmp_path / "s0_labels.npy"))]
        with pytest.raises(RuntimeError, match="shape"):
            NativeShardLoader(shards, batch_size=4, image_shape=(8, 8, 3))

    def test_int_image_shard_rejected(self, tmp_path):
        from mpi_operator_tpu.native import NativeShardLoader, native_available
        if not native_available():
            pytest.skip("no g++ available")
        from mpi_operator_tpu.data.imagefolder import write_npy_shard
        rng = np.random.RandomState(0)
        write_npy_shard(str(tmp_path), "s0",
                        rng.randint(0, 256, (8, 8, 8, 3)).astype(np.int32),
                        rng.randint(0, 10, (8,)).astype(np.int64))
        shards = [(str(tmp_path / "s0_images.npy"),
                   str(tmp_path / "s0_labels.npy"))]
        with pytest.raises(RuntimeError, match="u1 or f4"):
            NativeShardLoader(shards, batch_size=4, image_shape=(8, 8, 3))


# ---------------------------------------------------------------------------
# token-stream shards (data/tokenstream.py — the LM --data-dir path)
# ---------------------------------------------------------------------------

def test_token_dataset_window_alignment(tmp_path):
    """Contiguous windows with next-token alignment: targets must be the
    inputs shifted by one WITHIN each window, and windows must tile the
    stream in order."""
    from mpi_operator_tpu.data.tokenstream import (NpyTokenDataset,
                                                   write_token_shard)
    S, B = 8, 2
    stream = np.arange(10_000, dtype=np.int64) % 97
    write_token_shard(str(tmp_path), "s0", stream)
    ds = NpyTokenDataset(str(tmp_path), batch_size=B, seq_len=S,
                         vocab_size=97)
    toks, tgts = next(ds)
    assert toks.shape == (B, S) and tgts.shape == (B, S)
    np.testing.assert_array_equal(np.asarray(toks)[:, 1:],
                                  np.asarray(tgts)[:, :-1])
    # first window starts at the stream head
    np.testing.assert_array_equal(np.asarray(toks)[0], stream[:S])
    np.testing.assert_array_equal(np.asarray(tgts)[0], stream[1:S + 1])
    ds.close()


def test_token_dataset_vocab_validation(tmp_path):
    from mpi_operator_tpu.data.tokenstream import (NpyTokenDataset,
                                                   write_token_shard)
    write_token_shard(str(tmp_path), "s0",
                      np.full((1000,), 500, dtype=np.int32))
    ds = NpyTokenDataset(str(tmp_path), batch_size=2, seq_len=8,
                         vocab_size=100)
    with pytest.raises(RuntimeError, match="feeder"):
        next(ds)                      # out-of-range ids surface, not gather
    ds.close()


def test_token_dataset_rejects_undersized_and_bad_shards(tmp_path):
    from mpi_operator_tpu.data.tokenstream import (NpyTokenDataset,
                                                   write_token_shard)
    write_token_shard(str(tmp_path), "s0", np.arange(10, dtype=np.int32))
    with pytest.raises(ValueError, match="shorter"):
        NpyTokenDataset(str(tmp_path), batch_size=4, seq_len=8)
    np.save(tmp_path / "bad_tokens.npy", np.zeros((4, 4), np.float32))
    with pytest.raises(ValueError, match="integer"):
        NpyTokenDataset(str(tmp_path), batch_size=1, seq_len=2)


def test_lm_benchmark_with_data_dir(tmp_path):
    """End-to-end: gpt2 and bert (MLM corruption wrapper) train from real
    token shards through the shipped benchmark entrypoint."""
    from mpi_operator_tpu.data.tokenstream import write_token_shard
    from mpi_operator_tpu.examples.lm_benchmark import run_lm_benchmark

    rng = np.random.RandomState(0)
    write_token_shard(str(tmp_path), "s0",
                      rng.randint(0, 128, 200_000).astype(np.uint16))
    for workload in ("gpt2", "bert"):
        _state, metrics = run_lm_benchmark(
            workload=workload, size="test", batch_per_device=1,
            seq_len=32, num_steps=3, warmup_steps=1,
            dtype_name="float32", data_dir=str(tmp_path),
            log=lambda s: None)
        assert np.isfinite(metrics["final_loss"])
    # pipeline path: flat [B, S] pairs placed with B over (pp, data axes)
    _state, metrics = run_lm_benchmark(
        workload="gpt2", size="test", batch_per_device=4, pp=2,
        seq_len=32, num_steps=2, warmup_steps=1,
        dtype_name="float32", data_dir=str(tmp_path),
        log=lambda s: None)
    assert np.isfinite(metrics["final_loss"])
