"""Data-plane tests on 8 virtual CPU devices (conftest sets
--xla_force_host_platform_device_count=8): real XLA collectives without TPUs,
the multi-worker simulation strategy from SURVEY.md §4."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_operator_tpu.data import SyntheticImageDataset, synthetic_image_batch
from mpi_operator_tpu.models.resnet import create_model
from mpi_operator_tpu.parallel import MeshConfig, make_mesh, local_batch_size
from mpi_operator_tpu.parallel.collectives import (
    allreduce_gradients, hierarchical_allreduce_mean, sharded_allreduce_fn,
)
from mpi_operator_tpu.train import Trainer, TrainerConfig


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_mesh_data_parallel():
    mesh = make_mesh(MeshConfig.data_parallel(8))
    assert mesh.shape["dp"] == 8
    assert mesh.size == 8
    assert local_batch_size(64, mesh) == 8


def test_mesh_multislice_shape():
    mesh = make_mesh(MeshConfig.data_parallel(8, num_slices=2))
    assert mesh.shape["dcn"] == 2 and mesh.shape["dp"] == 4


def test_mesh_wrong_device_count_errors():
    with pytest.raises(ValueError, match="devices"):
        make_mesh(MeshConfig(dp=4))     # 4 != 8


def test_explicit_allreduce_matches_mean():
    mesh = make_mesh(MeshConfig.data_parallel(8))
    fn = sharded_allreduce_fn(mesh, ("dp",))
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    out = fn(xs)
    np.testing.assert_allclose(out, x.mean(0, keepdims=True), rtol=1e-6)


def test_hierarchical_allreduce_matches_flat():
    """Two-phase ICI/DCN allreduce must equal a plain global mean."""
    from mpi_operator_tpu.utils.compat import shard_map
    mesh = make_mesh(MeshConfig(dp=4, dcn=2))
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 33))  # odd inner dim

    flat = shard_map(lambda v: jax.lax.pmean(v, ("dcn", "dp")),
                     mesh=mesh, in_specs=(P(("dcn", "dp")),), out_specs=P())
    # the scatter/gather chain's replication can't be statically inferred
    hier = shard_map(
        lambda v: hierarchical_allreduce_mean(v, ici_axes=("dp",), dcn_axis="dcn"),
        mesh=mesh, in_specs=(P(("dcn", "dp")),), out_specs=P(),
        check_vma=False)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "dp"))))
    np.testing.assert_allclose(jax.jit(hier)(xs), jax.jit(flat)(xs),
                               rtol=1e-5, atol=1e-6)


def test_allreduce_gradients_pytree():
    from mpi_operator_tpu.utils.compat import shard_map
    mesh = make_mesh(MeshConfig.data_parallel(8))
    tree = {"w": jnp.ones((8, 2)), "b": jnp.arange(8, dtype=jnp.float32)}
    fn = shard_map(lambda t: allreduce_gradients(t, ("dp",)),
                   mesh=mesh,
                   in_specs=({"w": P("dp"), "b": P("dp")},),
                   out_specs={"w": P(), "b": P()})
    out = jax.jit(fn)(jax.device_put(
        tree, {"w": NamedSharding(mesh, P("dp")),
               "b": NamedSharding(mesh, P("dp"))}))
    np.testing.assert_allclose(out["w"], tree["w"].mean(0, keepdims=True))


def test_synthetic_batch_shapes():
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(0), 16, image_size=32, num_classes=10)
    assert imgs.shape == (16, 32, 32, 3) and imgs.dtype == jnp.bfloat16
    assert labels.shape == (16,) and int(labels.max()) < 10


def test_resnet_forward_shapes():
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    vars_ = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(vars_, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet_s2d_stem_trains():
    """The space-to-depth stem (4x4 s2d + dense 2x2 conv — the MXU-fed
    TPU stem): same output contract and spatial downsampling as conv7,
    and a few train steps reduce the loss."""
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32,
                         stem="s2d")
    x = jnp.zeros((2, 32, 32, 3))
    vars_ = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(vars_, x, train=False)
    assert logits.shape == (2, 10)
    # stem conv contracts 2·2·48 dense input channels
    k = vars_["params"]["conv_init"]["kernel"]
    assert k.shape == (2, 2, 48, 64)
    # same downsampling as conv7+maxpool: both stems leave H/4
    mesh = make_mesh(MeshConfig.data_parallel(8))
    cfg = TrainerConfig(global_batch_size=16, image_size=32, num_classes=10,
                        learning_rate=0.05)
    trainer = Trainer(model, mesh, cfg)
    state = trainer.init_state(jax.random.PRNGKey(0))
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)
    imgs = jax.device_put(imgs, trainer.batch_sharding)
    labels = jax.device_put(labels, trainer.batch_sharding)
    state, m0 = trainer.train_step(state, imgs, labels)
    first = float(m0["loss"])
    for _ in range(5):
        state, m = trainer.train_step(state, imgs, labels)
    assert float(m["loss"]) < first

    from mpi_operator_tpu.utils import flops as _fl
    # the s2d analytic adjustment keeps MFU honest (fewer actual FLOPs)
    assert (_fl.resnet_train_flops_per_image("resnet101", stem="s2d")
            < _fl.resnet_train_flops_per_image("resnet101"))


def test_trainer_step_runs_and_improves_loss():
    """End-to-end DP train step on the 8-device mesh: loss must drop on a
    fixed batch (the optimizer + implicit allreduce actually work)."""
    mesh = make_mesh(MeshConfig.data_parallel(8))
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32)
    cfg = TrainerConfig(global_batch_size=16, image_size=32, num_classes=10,
                        learning_rate=0.05)
    trainer = Trainer(model, mesh, cfg)
    state = trainer.init_state(jax.random.PRNGKey(0))
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)
    imgs = jax.device_put(imgs, trainer.batch_sharding)
    labels = jax.device_put(labels, trainer.batch_sharding)
    state, m0 = trainer.train_step(state, imgs, labels)
    first = float(m0["loss"])
    for _ in range(5):
        state, m = trainer.train_step(state, imgs, labels)
    assert float(m["loss"]) < first
    assert int(state.step) == 6


def test_trainer_dp_matches_single_device():
    """Gradient-allreduce correctness: a DP-8 step must produce the same
    params as a single-device step on the same global batch."""
    model = create_model("resnet18", num_classes=10, dtype=jnp.float32)
    cfg = TrainerConfig(global_batch_size=16, image_size=32, num_classes=10)
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)

    mesh8 = make_mesh(MeshConfig.data_parallel(8))
    t8 = Trainer(model, mesh8, cfg)
    s8 = t8.init_state(jax.random.PRNGKey(0))
    s8, _ = t8.train_step(
        s8,
        jax.device_put(imgs, t8.batch_sharding),
        jax.device_put(labels, t8.batch_sharding))

    mesh1 = make_mesh(MeshConfig.data_parallel(1), devices=jax.devices()[:1])
    t1 = Trainer(model, mesh1, cfg)
    s1 = t1.init_state(jax.random.PRNGKey(0))
    s1, _ = t1.train_step(
        s1,
        jax.device_put(imgs, t1.batch_sharding),
        jax.device_put(labels, t1.batch_sharding))

    flat8 = jax.tree_util.tree_leaves(s8.params)
    flat1 = jax.tree_util.tree_leaves(s1.params)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_synthetic_dataset_sharded():
    mesh = make_mesh(MeshConfig.data_parallel(8))
    from mpi_operator_tpu.parallel import batch_sharding
    ds = SyntheticImageDataset(16, image_size=32, num_classes=10,
                               sharding=batch_sharding(mesh))
    imgs, labels = next(iter(ds))
    assert imgs.sharding.spec == P(("dcn", "dp", "fsdp"))


# ---------------------------------------------------------------------------
# allreduce scaling harness (VERDICT #8; BASELINE "≥90% 4→32")
# ---------------------------------------------------------------------------

def test_allreduce_bench_curve_structure():
    from mpi_operator_tpu.examples.allreduce_bench import (
        run_allreduce_benchmark)

    result = run_allreduce_benchmark(payload_mb=[0.25], iters=2,
                                     device_counts=[1, 2, 4, 8],
                                     log=lambda s: None)
    assert len(result["points"]) == 4
    for p in result["points"]:
        assert p["time_ms"] > 0 and p["algbw_gbs"] > 0
    # efficiency relative to the smallest multi-device ring, which is 1.0
    curve = result["efficiency_curve"]
    assert set(curve) == {"2", "4", "8"}
    assert curve["2"] == 1.0


def test_benchmark_profile_dir_writes_trace(tmp_path):
    """SURVEY §5: the reference has no profiling story; ours writes an
    XProf/xplane trace of the first measurement window on request."""
    import glob

    from mpi_operator_tpu.examples.benchmark import run_benchmark

    _state, _metrics = run_benchmark(
        model_name="resnet18", batch_per_device=2, num_steps=4,
        warmup_steps=1, image_size=32, profile_dir=str(tmp_path),
        log=lambda s: None)
    traces = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
    assert traces, "no xplane trace written"


def test_alltoall_matches_transpose():
    """alltoall over n ranks is a block transpose: rank i's j-th chunk
    lands as rank j's i-th chunk."""
    from mpi_operator_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.parallel.collectives import alltoall

    mesh = make_mesh(MeshConfig(dp=8))
    x = jnp.arange(8 * 8 * 4, dtype=jnp.float32).reshape(8, 8, 4)
    fn = shard_map(lambda s: alltoall(s[0], "dp")[None],
                   mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = np.asarray(fn(x))
    # global semantics: out[j, i*C:(i+1)*C] == x[i, j*C:(j+1)*C], C=1 row
    ref = np.asarray(x).reshape(8, 8, 1, 4).transpose(1, 0, 2, 3) \
        .reshape(8, 8, 4)
    np.testing.assert_array_equal(out, ref)
