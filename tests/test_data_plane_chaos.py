"""Data-plane chaos tests (telemetry/chaos.py + the degraded-gang and
serving-lease paths it exercises).

The contracts under test, mirroring the control-plane chaos layer:

- **Scrape fault injection**: seeded ``<rank>/<kind>=<rate>`` rules are
  deterministic and replayable; each kind has load-bearing semantics
  (delay delivers one cycle late, stale-replay must NOT look like
  progress, a partition window keeps a rank dark for a stretch).
- **Federation vs. flakiness**: a failed scrape retains the rank's
  last-known samples, so neither the step nor the token frontier ever
  moves backward — and a stale replay never moves it forward.
- **Degraded, not stuck**: a partial partition (some ranks dark, the
  frontier still advancing through the rest) marks the gang
  DegradedGang and never restarts it; every rank dark IS a stall by
  design (an unobservable gang cannot prove liveness).
- **The serving progress lease**: serving gangs are watched through the
  retired-request/token frontier; a wedged engine is caught within
  progressDeadlineSeconds. Engine-side, expired requests retire with
  finish_reason "timeout" leaking no slots and no KV pages.
"""
import io

import pytest

from mpi_operator_tpu.api import types as api
from mpi_operator_tpu.controller.chaos import (
    ConvergenceError,
    data_plane_degraded,
    data_plane_serving_lease,
    data_plane_tpot_slope,
)
from mpi_operator_tpu.telemetry import events as ev
from mpi_operator_tpu.telemetry.chaos import (
    DEFAULT_PARTITION_FETCHES,
    SCRAPE_FAULT_KINDS,
    ScrapeFaultInjector,
    ScrapeFaultRule,
)
from mpi_operator_tpu.telemetry.collector import (
    JobObservatory,
    MetricsFederation,
)
from mpi_operator_tpu import postmortem

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# scrape fault rules: parsing, matching, determinism
# ---------------------------------------------------------------------------

def test_scrape_rule_parses_the_documented_syntax():
    rule = ScrapeFaultRule.parse("3/partition-window=0.05")
    assert rule == ScrapeFaultRule(rank="3", kind="partition-window",
                                   rate=0.05)
    assert rule.matches(3) and not rule.matches(2)
    wildcard = ScrapeFaultRule.parse("*/fail=0.2")
    assert wildcard.matches(0) and wildcard.matches(17)
    assert set(SCRAPE_FAULT_KINDS) == {
        "fail", "delay", "stale-replay", "partition-window"}


@pytest.mark.parametrize("bad", [
    "nonsense", "0/fail", "fail=0.5", "0/explode=0.5", "x/fail=0.5",
    "-1/fail=0.5", "0/fail=0", "0/fail=1.5", "0/fail=abc"])
def test_scrape_rule_rejects_malformed_rules(bad):
    with pytest.raises(ValueError):
        ScrapeFaultRule.parse(bad)


def test_scrape_injection_is_deterministic_per_seed():
    def run(seed):
        inj = ScrapeFaultInjector(["*/fail=0.5"], seed=seed)
        outcomes = []
        for i in range(40):
            try:
                inj.fetch(i % 2, f"http://w{i % 2}/metrics",
                          lambda url: "ok")
                outcomes.append("ok")
            except IOError:
                outcomes.append("fail")
        return outcomes
    assert run(7) == run(7)
    assert run(7) != run(8)
    assert "ok" in run(7) and "fail" in run(7)


def test_first_matching_rule_wins_and_faults_are_attributed():
    inj = ScrapeFaultInjector(["0/fail=1", "0/stale-replay=1"], seed=1)
    for _ in range(5):
        with pytest.raises(IOError, match=r"seed=1"):
            inj.fetch(0, "http://w0/metrics", lambda url: "ok")
    # rank 1 matches no rule: pure pass-through
    assert inj.fetch(1, "http://w1/metrics", lambda url: "ok") == "ok"
    assert inj.faults_injected == {(0, "fail"): 5}
    assert inj.fault_count() == 5 and inj.fault_count("stale-replay") == 0


# ---------------------------------------------------------------------------
# fault kind semantics
# ---------------------------------------------------------------------------

def test_delay_delivers_one_cycle_late():
    inj = ScrapeFaultInjector(["0/delay=1"], seed=0)
    payloads = iter(["v1", "v2", "v3"])
    fetch = lambda url: next(payloads)       # noqa: E731
    # first delayed fetch has nothing lagged yet: injected timeout
    with pytest.raises(IOError, match="timed out"):
        inj.fetch(0, "u", fetch)
    # from then on the slow link delivers, one cycle behind
    assert inj.fetch(0, "u", fetch) == "v1"
    assert inj.fetch(0, "u", fetch) == "v2"
    assert inj.fault_count("delay") == 3


def test_stale_replay_serves_a_frozen_snapshot():
    inj = ScrapeFaultInjector(["0/stale-replay=1"], seed=0)
    payloads = iter(["v1", "v2", "v3"])
    fetch = lambda url: next(payloads)       # noqa: E731
    # nothing cached yet: the first fetch passes through (and caches)
    assert inj.fetch(0, "u", fetch) == "v1"
    # a stuck cache: the same snapshot forever, never refreshed
    assert inj.fetch(0, "u", fetch) == "v1"
    assert inj.fetch(0, "u", fetch) == "v1"
    assert inj.fault_count("stale-replay") == 2


def test_partition_window_keeps_the_rank_dark_then_heals():
    inj = ScrapeFaultInjector(["0/partition-window=1"], seed=0,
                              partition_fetches=2)
    with pytest.raises(IOError, match="window opened"):
        inj.fetch(0, "u", lambda url: "ok")
    assert inj.partitioned_ranks() == [0]
    # drop the rules: only the already-open window keeps it dark
    inj.rules = ()
    for _ in range(2):
        with pytest.raises(IOError, match="partitioned"):
            inj.fetch(0, "u", lambda url: "ok")
    assert inj.partitioned_ranks() == []
    assert inj.fetch(0, "u", lambda url: "ok") == "ok"
    assert inj.fault_count("partition-window") == 3
    assert DEFAULT_PARTITION_FETCHES >= 2    # default spans several passes


def test_open_partition_window_dominates_other_rules():
    # fail would fire every roll, but the open window wins (the rank is
    # dark, full stop) and its countdown is what decides the heal
    inj = ScrapeFaultInjector(["0/partition-window=1", "0/fail=1"],
                              seed=0, partition_fetches=1)
    with pytest.raises(IOError, match="window opened"):
        inj.fetch(0, "u", lambda url: "ok")
    with pytest.raises(IOError, match="partitioned"):
        inj.fetch(0, "u", lambda url: "ok")
    assert inj.faults_injected[(0, "partition-window")] == 2


# ---------------------------------------------------------------------------
# federation under flakiness: frontiers never move backward (satellite:
# scrape_failed <-> frontier interplay)
# ---------------------------------------------------------------------------

def test_scrape_failed_retains_last_known_samples():
    fed = MetricsFederation("j", clock=lambda: 0.0)
    fed.ingest(0, "tpu_worker_step 7\n")
    fed.ingest(1, "tpu_worker_step 5\n")
    assert fed.observed_step() == 7 and fed.unreachable_ranks() == []
    # rank 0 goes dark: its last-known step is RETAINED, so the frontier
    # cannot move backward under pure scrape flakiness
    fed.scrape_failed(0)
    assert fed.unreachable_ranks() == [0]
    assert fed.observed_step() == 7
    # the partition heals at a later step: per-rank frontier resumes
    fed.ingest(0, "tpu_worker_step 9\n")
    assert fed.unreachable_ranks() == [] and fed.observed_step() == 9


def test_never_scraped_rank_has_no_verdict():
    fed = MetricsFederation("j", clock=lambda: 0.0)
    assert fed.unreachable_ranks() == []
    fed.ingest(1, "tpu_worker_step 3\n")
    # rank 0 has never been attempted: no attempt, no verdict — it must
    # not show up as partition evidence
    assert fed.unreachable_ranks() == []


def test_observed_tokens_monotone_under_stale_and_failed_scrapes():
    fed = MetricsFederation("j", clock=lambda: 0.0)
    text = "tpu_worker_requests_total 3\ntpu_worker_tokens_total 50\n"
    fed.ingest(0, text)
    fed.ingest(1, "tpu_worker_requests_total 1\ntpu_worker_tokens_total 9\n")
    assert fed.observed_tokens() == 63
    # a stale replay re-ingests the identical snapshot: the latest scrape
    # REPLACES the rank's samples, so nothing double-counts and the
    # frontier reads the same value (stale must not look like progress)
    fed.ingest(0, text)
    assert fed.observed_tokens() == 63
    fed.scrape_failed(0)                     # dark: last counts retained
    assert fed.observed_tokens() == 63
    fed.ingest(0, "tpu_worker_requests_total 4\ntpu_worker_tokens_total 60\n")
    assert fed.observed_tokens() == 74       # resumption, no double count


def test_observatory_lease_slides_only_on_real_progress():
    clock = {"now": 1000.0}
    payload = {"text": "tpu_worker_step 5\n"}

    def fetch(url):
        if url.endswith("/metrics"):
            return payload["text"]
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0)
    assert obs.stall_seconds("j") is None    # lease disarmed before scrape
    obs.observe("j", {0: "http://w0:9100"}, force=True)
    assert obs.stall_seconds("j") == 0.0
    clock["now"] += 30
    obs.observe("j", {0: "http://w0:9100"}, force=True)
    assert obs.stall_seconds("j") == 30.0    # same step: lease frozen
    payload["text"] = "tpu_worker_step 6\n"
    clock["now"] += 10
    obs.observe("j", {0: "http://w0:9100"}, force=True)
    assert obs.stall_seconds("j") == 0.0     # frontier moved: lease slides


def test_never_scraped_rank_does_not_pin_the_lease():
    # rank 0 never scrapes successfully; rank 1's frontier advances.
    # The federated frontier is a MAX across ranks, so the dark rank
    # must not hold progress_ts back (no false stall from one straggler
    # that was never observable in the first place).
    clock = {"now": 1000.0}
    step = {"v": 5}

    def fetch(url):
        if "w0" in url:
            raise IOError("rank 0 dark from birth")
        if url.endswith("/metrics"):
            return f"tpu_worker_step {step['v']}\n"
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0)
    targets = {0: "http://w0:9100", 1: "http://w1:9100"}
    obs.observe("j", targets, force=True)
    for _ in range(4):
        clock["now"] += 30
        step["v"] += 1
        obs.observe("j", targets, force=True)
        assert obs.stall_seconds("j") == 0.0
    unreachable, total = obs.partition_state("j")
    assert unreachable == [0] and total == 2


def test_observatory_serving_lease_watches_the_token_frontier():
    clock = {"now": 1000.0}
    frontier = {"tokens": 0}

    def fetch(url):
        if url.endswith("/metrics"):
            return (f"tpu_worker_requests_total 2\n"
                    f"tpu_worker_tokens_total {frontier['tokens']}\n")
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0)
    obs.observe("s", {0: "http://w0:9100"}, force=True, serving=True)
    clock["now"] += 20
    frontier["tokens"] = 40                  # requests retiring
    obs.observe("s", {0: "http://w0:9100"}, force=True, serving=True)
    assert obs.stall_seconds("s") == 0.0
    clock["now"] += 45                       # the engine wedges
    obs.observe("s", {0: "http://w0:9100"}, force=True, serving=True)
    assert obs.stall_seconds("s") == 45.0


# ---------------------------------------------------------------------------
# degraded-gang discipline, end to end (the soak legs, in process)
# ---------------------------------------------------------------------------

def test_partial_partition_degrades_without_restart():
    report = data_plane_degraded(seed=0)
    assert report["false_positive_restarts"] == 0
    assert report["degraded_windows"] == 1
    assert report["scrape_faults_injected"] > 0


def test_all_ranks_dark_is_a_stall_not_a_degradation():
    # every rank dark: the frontier is unobservable, which IS a stall by
    # design — the degraded leg's zero-false-positive assertion trips
    with pytest.raises(ConvergenceError, match="restarted the gang"):
        data_plane_degraded(seed=0, scrape_faults=("*/fail=1",))


def test_serving_lease_catches_a_wedged_gang():
    report = data_plane_serving_lease(seed=0)
    assert report == {"serving_stalls_detected": 1,
                      "serving_false_positives": 0}


def test_observatory_tpot_slope_freezes_the_lease_below_floor():
    # the frontier ADVANCES every scrape, but below serving_rate_floor:
    # the lease must NOT renew — a creeping engine goes stuck by the
    # same wall-clock deadline as a frozen one
    clock = {"now": 1000.0}
    frontier = {"tokens": 0}

    def fetch(url):
        if url.endswith("/metrics"):
            return (f"tpu_worker_requests_total 2\n"
                    f"tpu_worker_tokens_total {frontier['tokens']}\n")
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0, serving_rate_floor=1.0)
    tgt = {0: "http://w0:9100"}
    # first advance of the incarnation always arms (no window yet)
    obs.observe("s", tgt, force=True, serving=True)
    # healthy: 40 tokens / 20 s = 2 tok/s >= floor -> lease renews
    clock["now"] += 20
    frontier["tokens"] = 40
    obs.observe("s", tgt, force=True, serving=True)
    assert obs.stall_seconds("s") == 0.0
    # creep: 2 tokens / 20 s = 0.1 tok/s < floor — progress_ts frozen
    # even though the frontier moves every scrape
    for _ in range(3):
        clock["now"] += 20
        frontier["tokens"] += 2
        obs.observe("s", tgt, force=True, serving=True)
    assert obs.stall_seconds("s") == 60.0
    # recovery: one healthy advance re-arms the lease
    clock["now"] += 20
    frontier["tokens"] += 100
    obs.observe("s", tgt, force=True, serving=True)
    assert obs.stall_seconds("s") == 0.0


def test_observatory_tpot_slope_off_by_default():
    # no floor configured: the same creeping trace renews the lease on
    # every advance (pre-existing behavior unchanged)
    clock = {"now": 1000.0}
    frontier = {"tokens": 0}

    def fetch(url):
        if url.endswith("/metrics"):
            return (f"tpu_worker_requests_total 2\n"
                    f"tpu_worker_tokens_total {frontier['tokens']}\n")
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0)
    tgt = {0: "http://w0:9100"}
    obs.observe("s", tgt, force=True, serving=True)
    for _ in range(3):
        clock["now"] += 20
        frontier["tokens"] += 2
        obs.observe("s", tgt, force=True, serving=True)
        assert obs.stall_seconds("s") == 0.0


def test_reset_progress_lease_clears_the_rate_window():
    # a gang restart must not measure its first post-restart advance
    # against the pre-restart frontier (that window spans the outage)
    clock = {"now": 1000.0}
    frontier = {"tokens": 0}

    def fetch(url):
        if url.endswith("/metrics"):
            return (f"tpu_worker_requests_total 2\n"
                    f"tpu_worker_tokens_total {frontier['tokens']}\n")
        raise IOError("no events endpoint")

    obs = JobObservatory(clock=lambda: clock["now"], fetch=fetch,
                         scrape_interval=0.0, serving_rate_floor=1.0)
    tgt = {0: "http://w0:9100"}
    obs.observe("s", tgt, force=True, serving=True)
    clock["now"] += 500                      # long outage, then restart
    obs.reset_progress_lease("s")
    assert obs.view("s")["rate_ts"] is None
    frontier["tokens"] = 10
    obs.observe("s", tgt, force=True, serving=True)
    # first advance after reset arms unconditionally — 10 tokens / 500 s
    # would read as creep if the stale window survived the reset
    assert obs.stall_seconds("s") == 0.0


def test_tpot_slope_lease_catches_a_creeping_gang():
    report = data_plane_tpot_slope(seed=0)
    assert report == {"tpot_slope_stalls_detected": 1,
                      "tpot_slope_false_positives": 0}


def test_degraded_condition_constants_exist():
    assert api.COND_DEGRADED_GANG == "DegradedGang"
    assert ev.GANG_DEGRADED == "gang_degraded"
    assert ev.REQUEST_TIMEOUT == "request_timeout"


# ---------------------------------------------------------------------------
# engine-side lease enforcement: request timeouts leak nothing
# ---------------------------------------------------------------------------

class _EventSink:
    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        rec = {"event": event, **fields}
        self.records.append(rec)
        return rec


def test_engine_request_timeouts_retire_and_reclaim():
    import jax
    import jax.numpy as jnp
    from flax.core import meta as flax_meta

    from mpi_operator_tpu.models import CausalLM, gpt2_config
    from mpi_operator_tpu.serve import EngineConfig, Request, ServingEngine

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    params = flax_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    )["params"]
    sink = _EventSink()
    engine = ServingEngine(model, params, EngineConfig(
        slots=2, chunk_buckets=(4, 8), paged=True, page_size=8,
        rng_seed=0, request_timeout=0.0), events=sink)
    reqs = [Request(i, [1 + (i % 5)] * 6, 8) for i in range(3)]
    results = engine.run(reqs)
    assert len(results) == 3
    assert all(r.finish_reason == "timeout" for r in results.values())
    # the -1.0 ttft sentinel fires exactly when no token was emitted
    assert all((r.ttft == -1.0) == (not r.token_times)
               for r in results.values())
    timeouts = [r for r in sink.records
                if r["event"] == ev.REQUEST_TIMEOUT]
    assert {r["request"] for r in timeouts} == {0, 1, 2}
    assert all(r["deadline_seconds"] == 0.0 for r in timeouts)
    # zero leaks: every slot back in the pool, every KV page reclaimed
    engine.page_allocator.check()
    assert engine.page_allocator.in_use == 0
    assert len(engine.slots.free) == engine.config.slots
    # lift the timeout: the SAME engine must serve normally again
    engine.config.request_timeout = None
    after = engine.run([Request(9, [2, 3, 4], 4)])
    assert after[9].finish_reason in ("eos", "length")
    assert after[9].tokens


# ---------------------------------------------------------------------------
# postmortem: degraded windows land as first-class incidents
# ---------------------------------------------------------------------------

def test_postmortem_pairs_degraded_open_with_heal():
    records = [
        {"ts": 100.0, "event": ev.JOB_CREATED, "job": "j"},
        {"ts": 110.0, "event": ev.GANG_DEGRADED, "ranks": [0],
         "partitioned_ranks": 1, "total_ranks": 2},
        # the dark set changes shape mid-window: updates in place
        {"ts": 120.0, "event": ev.GANG_DEGRADED, "ranks": [0, 3],
         "partitioned_ranks": 2, "total_ranks": 4},
        {"ts": 150.0, "event": ev.GANG_DEGRADED, "healed": True,
         "ranks": [], "partitioned_ranks": 0},
        {"ts": 200.0, "event": ev.JOB_SUCCEEDED},
    ]
    summary = postmortem.summarize(records)
    (window,) = summary["degraded"]
    assert window["t"] == 10.0
    assert window["ranks"] == [0, 3]
    assert window["resolution"] == "healed"
    assert window["resolution_t"] == 50.0
    buf = io.StringIO()
    postmortem.render(summary, buf)
    text = buf.getvalue()
    assert "degraded gangs:" in text
    assert "no restart" in text
    assert "healed" in text


def test_postmortem_unhealed_window_resolved_by_terminal_event():
    records = [
        {"ts": 0.0, "event": ev.JOB_CREATED, "job": "j"},
        {"ts": 10.0, "event": ev.GANG_DEGRADED, "ranks": [1],
         "partitioned_ranks": 1, "total_ranks": 2},
        {"ts": 90.0, "event": ev.JOB_FAILED},
    ]
    summary = postmortem.summarize(records)
    (window,) = summary["degraded"]
    assert window["resolution"] == ev.JOB_FAILED
    buf = io.StringIO()
    postmortem.render(summary, buf)
    assert "degraded gangs:" in buf.getvalue()
