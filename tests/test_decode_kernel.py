"""Pallas decode-attention kernel tests (interpret mode on CPU — the same
kernel code path that compiles to Mosaic on TPU).

The dense `_decode_attend` path in models/transformer.py is the
correctness oracle: the kernel must match it within dtype tolerance for
MHA, GQA, and int8-quantized caches, INCLUDING mid-generation cursors —
a partially filled cache whose unfilled suffix is poisoned, so any read
past the cursor shows up as a huge error, not a lucky zero.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, generate, gpt2_config
from mpi_operator_tpu.models.transformer import llama_config
from mpi_operator_tpu.ops.attention import decode_attention, decode_block_k

POISON = 1e4          # beyond-cursor cache contents: loud if ever read


def _dense_ref(q, k, v, cur, k_scale=None, v_scale=None):
    """The dense decode oracle, mirroring transformer._decode_attend:
    dequant, GQA repeat on the kv-head axis, masked softmax over the
    filled prefix [0, cur]."""
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[..., None]
        v = v.astype(jnp.float32) * v_scale[..., None]
    B, KV, L, D = k.shape
    H = q.shape[1]
    k = jnp.repeat(k, H // KV, axis=1)            # [B, H, L, D]
    v = jnp.repeat(v, H // KV, axis=1)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (D ** 0.5)
    s = jnp.where(jnp.arange(L)[None, None] <= cur, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,bhld->bhd", p, v.astype(jnp.float32))


def _cache(B, H, KV, L, D, cur, quantized=False, seed=0):
    """A cache filled up to `cur` (inclusive) and POISONed past it."""
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (B, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, KV, L, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, KV, L, D), jnp.float32)
    dead = jnp.arange(L)[None, None, :, None] > cur
    if not quantized:
        return q, jnp.where(dead, POISON, k), jnp.where(dead, POISON, v), \
            None, None
    scale = jnp.maximum(jnp.max(jnp.abs(k), -1) / 127.0, 1e-8)
    k8 = jnp.clip(jnp.round(k / scale[..., None]), -127, 127)
    vscale = jnp.maximum(jnp.max(jnp.abs(v), -1) / 127.0, 1e-8)
    v8 = jnp.clip(jnp.round(v / vscale[..., None]), -127, 127)
    k8 = jnp.where(dead, 127, k8).astype(jnp.int8)
    v8 = jnp.where(dead, 127, v8).astype(jnp.int8)
    dead3 = jnp.arange(L)[None, None] > cur
    return (q, k8, v8, jnp.where(dead3, POISON, scale),
            jnp.where(dead3, POISON, vscale))


@pytest.mark.parametrize("H,KV", [(4, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("quantized", [False, True])
def test_decode_kernel_matches_dense(H, KV, quantized):
    """MHA (H==KV), GQA, and MQA (KV=1), each with and without the int8
    cache — cursor mid-block so both the block skip and the in-block
    column mask are exercised."""
    B, L, D, cur = 2, 64, 16, 37
    q, k, v, ks, vs = _cache(B, H, KV, L, D, cur, quantized)
    if quantized:
        ref = _dense_ref(q, k, v, cur, ks, vs)
        out = decode_attention(q, k, v, cur, k_scale=ks, v_scale=vs,
                               block_k=16, interpret=True)
    else:
        ref = _dense_ref(q, k, v, cur)
        out = decode_attention(q, k, v, cur, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("cur", [0, 15, 16, 31, 63])
def test_decode_kernel_cursor_positions(cur):
    """Mid-generation cursors: the first position, both sides of a block
    boundary, and the full cache — the length-aware index_map and the
    boundary-block column mask must agree with the oracle at each."""
    B, H, KV, L, D = 2, 4, 2, 64, 16
    q, k, v, _, _ = _cache(B, H, KV, L, D, cur, seed=cur + 1)
    ref = _dense_ref(q, k, v, cur)
    out = decode_attention(q, k, v, cur, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_decode_kernel_per_row_cursors(quantized):
    """[B] cursor vector (the serving engine's slot mode): each row reads
    exactly its own filled prefix — per-row poison past each cursor makes
    any cross-row or beyond-cursor read loud."""
    B, H, KV, L, D = 4, 4, 2, 64, 16
    curs = np.array([0, 17, 31, 63], np.int32)
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(keys[0], (B, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, KV, L, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, KV, L, D), jnp.float32)
    dead = jnp.arange(L)[None, None, :, None] > curs[:, None, None, None]
    ks = vs = None
    if quantized:
        ks = jnp.maximum(jnp.max(jnp.abs(k), -1) / 127.0, 1e-8)
        vs = jnp.maximum(jnp.max(jnp.abs(v), -1) / 127.0, 1e-8)
        k = jnp.clip(jnp.round(k / ks[..., None]), -127, 127)
        v = jnp.clip(jnp.round(v / vs[..., None]), -127, 127)
        k = jnp.where(dead, 127, k).astype(jnp.int8)
        v = jnp.where(dead, 127, v).astype(jnp.int8)
        dead3 = dead[..., 0]
        ks = jnp.where(dead3, POISON, ks)
        vs = jnp.where(dead3, POISON, vs)
    else:
        k = jnp.where(dead, POISON, k)
        v = jnp.where(dead, POISON, v)
    ref = jnp.concatenate([
        _dense_ref(q[b:b + 1], k[b:b + 1], v[b:b + 1], int(curs[b]),
                   None if ks is None else ks[b:b + 1],
                   None if vs is None else vs[b:b + 1])
        for b in range(B)])
    out = decode_attention(q, k, v, jnp.asarray(curs), k_scale=ks,
                           v_scale=vs, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_decode_kernel_vector_cursor_matches_broadcast_scalar():
    """A uniform [B] cursor vector must agree exactly with the scalar
    cursor path (same program semantics, different operand rank), and a
    wrong-shaped cursor is rejected."""
    B, H, KV, L, D, cur = 2, 4, 2, 64, 16, 29
    q, k, v, _, _ = _cache(B, H, KV, L, D, cur, seed=9)
    scalar = decode_attention(q, k, v, cur, block_k=16, interpret=True)
    vector = decode_attention(q, k, v, jnp.full((B,), cur, jnp.int32),
                              block_k=16, interpret=True)
    np.testing.assert_array_equal(np.asarray(scalar), np.asarray(vector))
    with pytest.raises(ValueError, match="cache_index"):
        decode_attention(q, k, v, jnp.zeros((B + 1,), jnp.int32),
                         block_k=16, interpret=True)


def test_decode_kernel_rejects_bad_shapes():
    q, k, v, _, _ = _cache(1, 4, 2, 64, 16, 10)
    with pytest.raises(ValueError, match="multiple of KV"):
        decode_attention(q[:, :3], k, v, 10, interpret=True)
    with pytest.raises(ValueError, match="tile"):
        decode_attention(q, k, v, 10, block_k=48, interpret=True)


def test_decode_block_k_policy():
    assert decode_block_k(1024) == 128          # default tile
    assert decode_block_k(32) == 32             # short caches shrink
    assert decode_block_k(1024, 256) == 256     # explicit override


def _e2e(cfg, new_tokens=8, seed=1):
    """Token-exact agreement between the kernel decode path and the dense
    oracle on the SAME params — the end-to-end form of the parity above
    (cache writes, cursor plumbing, and output layout included)."""
    model = CausalLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(seed), (2, 5), 0,
                                cfg.vocab_size)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), prompt))["params"]
    ref = generate(model, params, prompt, max_new_tokens=new_tokens,
                   decode_kernel=False)
    out = generate(model, params, prompt, max_new_tokens=new_tokens,
                   decode_kernel=True)
    assert np.array_equal(np.array(ref.tokens), np.array(out.tokens))
    assert bool(jnp.isfinite(out.logprobs).all())


def test_generate_kernel_matches_dense_gpt2():
    _e2e(gpt2_config("test", attention="dense", dtype=jnp.float32,
                     vocab_size=64, max_len=32))


@pytest.mark.slow
def test_generate_kernel_matches_dense_llama_gqa():
    _e2e(llama_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32))


@pytest.mark.slow
def test_generate_kernel_matches_dense_int8_kv():
    cfg = llama_config("test", attention="dense", dtype=jnp.float32,
                       vocab_size=64, max_len=32)
    _e2e(dataclasses.replace(cfg, kv_cache_dtype="int8"))


def test_decode_kernel_config_falls_back_on_odd_cache_len():
    """A cache length that doesn't tile must silently use the dense path
    (same tokens), not crash — the transformer-side gate."""
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=24)   # 24 % 24 == 0 tiles...
    cfg = dataclasses.replace(cfg, decode_block_k=7)   # ...but 7 doesn't
    _e2e(cfg, new_tokens=4)
