"""Disaggregated prefill/decode serving tests (serve/engine.py
DisaggEngine + serve/transfer.py).

The colocated paged ServingEngine is the oracle: a greedy trace served
through the split pools — prompt-span admission on the prefill pool,
paged-KV handoff, decode on its own device — must be TOKEN-EXACT
against the same trace run colocated, across retire/slot-reuse, on the
dense and Pallas-kernel paths and with int8 KV (the scale planes ride
the handoff). On top of that, the per-pool compile pins that ARE the
perf story: the prefill pool never compiles a decode step, the decode
pool never compiles a prefill, and the transfer's gather/scatter stay
within the power-of-two width buckets — all held across reset().
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, gpt2_config
from mpi_operator_tpu.serve import (
    DisaggEngine, EngineConfig, PageTransfer, Request, Scheduler,
    ServingEngine,
)
from mpi_operator_tpu.telemetry import events as ev
from mpi_operator_tpu.telemetry.core import Registry
from mpi_operator_tpu.telemetry.events import EventLog, read_events

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# host-side policy (no jax)
# ---------------------------------------------------------------------------

def test_prompt_pages_needed():
    # prefill writes [0, P-1): the prompt span excludes the decode span
    ps = 8
    assert Scheduler.prompt_pages_needed(Request(0, [1], 64), ps) == 0
    assert Scheduler.prompt_pages_needed(Request(0, [1, 2], 64), ps) == 1
    assert Scheduler.prompt_pages_needed(Request(0, [1] * 9, 64), ps) == 1
    assert Scheduler.prompt_pages_needed(Request(0, [1] * 10, 64), ps) == 2
    assert Scheduler.prompt_pages_needed(Request(0, [1] * 17, 64), ps) == 2
    # always <= the full span, whatever max_new_tokens is
    for p in range(1, 40):
        r = Request(0, [1] * p, 1)
        assert (Scheduler.prompt_pages_needed(r, ps)
                <= Scheduler.pages_needed(r, ps))


def test_scheduler_reserve_mode_validates():
    with pytest.raises(ValueError, match="reserve"):
        Scheduler((4, 8), max_len=64, reserve="both")


def test_scheduler_gate_blocks_and_packs_past():
    """A gated head stays queued but the lookahead still admits a
    request behind it — the same packing rule as a failed page
    reservation."""
    s = Scheduler((4, 8), max_len=64)
    s.submit(Request(0, [1] * 8, 4))
    s.submit(Request(1, [2] * 4, 4))
    s.gate = lambda req: req.id != 0
    admitted = s.admit([0, 1], now=0.0)
    assert [st.req.id for st in admitted] == [1]
    assert [r.id for r in s.queue] == [0]
    s.gate = None
    assert [st.req.id for st in s.admit([0], now=0.0)] == [0]


def test_transfer_width_bucketing():
    assert PageTransfer.TRASH == 0
    from mpi_operator_tpu.serve.transfer import _bucket
    assert [_bucket(n) for n in (1, 2, 3, 4, 5, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 16]


# ---------------------------------------------------------------------------
# the disagg facade vs the colocated oracle
# ---------------------------------------------------------------------------

def _setup(decode_kernel=False, kv_cache_dtype=None, slots=4,
           page_size=8, num_pages=None, max_len=64, **disagg_kw):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=max_len,
                      kv_cache_dtype=kv_cache_dtype)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    ecfg = EngineConfig(slots=slots, chunk_buckets=(4, 8),
                        decode_kernel=decode_kernel, paged=True,
                        page_size=page_size, num_pages=num_pages)
    colocated = ServingEngine(model, params, ecfg)
    disagg = DisaggEngine(model, params, ecfg, **disagg_kw)
    return colocated, disagg


def _mixed_trace(n=8, seed=7, eos=None):
    rs = np.random.RandomState(seed)
    lens = [(1, 6), (3, 9), (9, 4), (14, 7), (5, 5), (7, 8), (12, 6),
            (2, 7)]
    return [Request(i, list(rs.randint(0, 64, (p,))), max_new_tokens=m,
                    eos_id=eos)
            for i, (p, m) in enumerate(lens[:n])]


def _assert_pool_pins(disagg):
    counts = disagg.compile_counts()
    # neither pool ever compiles the other's programs — the per-pool
    # HBM program-footprint win of the split
    assert counts["prefill_pool"]["step"] == 0
    assert counts["prefill_pool"]["prefill"] <= 2
    assert counts["decode_pool"]["prefill"] == 0
    assert counts["decode_pool"]["step"] <= 3
    # transfer widths are power-of-two bucketed: ≤ log2(pool) + 1 each
    cap = int(np.log2(disagg.decode.page_allocator.num_pages)) + 1
    assert counts["transfer"]["gather"] <= cap
    assert counts["transfer"]["scatter"] <= cap
    return counts


@pytest.mark.parametrize("decode_kernel", [False, True])
def test_disagg_token_exact_vs_colocated(decode_kernel):
    """The acceptance gate: greedy decode through the split pools is
    token-for-token identical to the colocated paged engine on the same
    trace — mixed prompt lengths, more requests than slots (slot AND
    page reuse across retire/admit, pages crossing devices mid-request),
    dense and kernel paths."""
    colocated, disagg = _setup(decode_kernel)
    want = colocated.run(_mixed_trace())
    got = disagg.run(_mixed_trace())
    for rid, res in want.items():
        assert got[rid].tokens == res.tokens, f"request {rid} diverged"
        assert got[rid].finish_reason == res.finish_reason
    assert disagg.transfer.pages_moved > 0     # pages really crossed
    for alloc in (disagg.prefill.page_allocator,
                  disagg.decode.page_allocator):
        alloc.check()
        assert alloc.in_use == 0               # every page released
    counts = _assert_pool_pins(disagg)
    assert counts["decode_pool"]["step"] == 1  # pure-greedy trace


def test_disagg_int8_cache_token_exact():
    """int8 KV through the handoff: quantized pages move WITH their
    [NP, KV, ps] scale planes (one generic pytree gather/scatter), so
    the decode pool dequantizes the same bytes the colocated engine
    would."""
    colocated, disagg = _setup(kv_cache_dtype="int8")
    want = colocated.run(_mixed_trace(n=5))
    got = disagg.run(_mixed_trace(n=5))
    for rid, res in want.items():
        assert got[rid].tokens == res.tokens, f"request {rid} diverged"
    assert disagg.transfer.pages_moved > 0


def test_disagg_eos_retirement_and_pins_across_reset():
    """EOS mid-flight retires through the decode pool (pages park in
    its prefix cache); a reset() replays the trace token-identically
    WITHOUT growing any pool's compile counts — the warmup→measure
    contract the bench relies on."""
    colocated, disagg = _setup()
    probe = colocated.run(_mixed_trace(n=1))
    eos = probe[0].tokens[2]
    colocated.reset()
    want = colocated.run(_mixed_trace(eos=eos))
    got = disagg.run(_mixed_trace(eos=eos))
    assert any(r.finish_reason == "eos" for r in got.values())
    for rid, res in want.items():
        assert got[rid].tokens == res.tokens
    counts_before = _assert_pool_pins(disagg)
    disagg.reset()
    again = disagg.run(_mixed_trace(eos=eos))
    for rid, res in want.items():
        assert again[rid].tokens == res.tokens
    assert disagg.compile_counts() == counts_before


def test_prefix_hit_handoff_moves_only_noncached_pages():
    """The handoff reads the DECODE pool's prefix cache: a repeat
    prompt's full prompt pages are already resident there, so the
    second handoff moves zero pages (and a diverging prompt moves only
    its divergent tail)."""
    _, disagg = _setup()
    shared = list(np.random.RandomState(3).randint(0, 64, (33,)))
    # p1=32, page_size=8: 4 full prompt pages, all published at install
    disagg.run([Request(0, shared, max_new_tokens=4)])
    first = disagg.transfer.pages_moved
    assert first >= 4
    out = disagg.run([Request(1, shared, max_new_tokens=4)])
    assert disagg.transfer.pages_moved == first   # full hit: no bytes
    assert out[1].cached_tokens == 32             # prefill skipped too
    # divergence in the last full page: pages 0-2 hit, page 3 moves
    fork = list(shared)
    fork[30] = (fork[30] + 1) % 64
    disagg.run([Request(2, fork, max_new_tokens=4)])
    assert disagg.transfer.pages_moved == first + 1
    # decode-side hit/miss counters saw the savings
    assert disagg.decode.page_allocator.hits >= 7


def test_backpressure_bounds_prefill_admission():
    """A decode pool sized for ONE request forces serial service: the
    admission gate keeps prompts out of the prefill pool until the
    decode pool can absorb their full span — bounded handoff queue, no
    page deadlock, every request still completes exactly."""
    # each request: prompt 14, max_new 7 -> (14-2+7)//8+1 = 3 pages
    reqs = [Request(i, list(np.random.RandomState(i).randint(0, 64, (14,))),
                    max_new_tokens=7) for i in range(3)]
    colocated, disagg = _setup(num_pages=4)     # 3 usable decode pages
    want = colocated.run(reqs)
    got = disagg.run(reqs)
    for r in reqs:
        assert got[r.id].tokens == want[r.id].tokens
    assert disagg.prefill.occupancy_peak == 1   # gate held admissions
    assert disagg.decode.occupancy_peak == 1
    assert not disagg._handoff_q


def test_disagg_rejects_unservable_requests():
    _, disagg = _setup(num_pages=4)             # 3 usable decode pages
    with pytest.raises(ValueError, match="decode pool"):
        disagg.run([Request(0, [1] * 20, max_new_tokens=30)])


# ---------------------------------------------------------------------------
# telemetry + events
# ---------------------------------------------------------------------------

def test_disagg_per_pool_telemetry_and_handoff_events(tmp_path):
    """One registry, two labeled bundles: every serve series shows up
    per pool (the federation keeps the label), kv_handoff_* instruments
    fill on the decode side, and the event log carries kv_handoff
    records plus pool-stamped admissions."""
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    reg = Registry()
    log = EventLog(str(tmp_path / "events.jsonl"))
    disagg = DisaggEngine(
        model, params,
        EngineConfig(slots=4, chunk_buckets=(4, 8), paged=True,
                     page_size=8),
        registry=reg, events=log)
    disagg.run(_mixed_trace(n=4))
    log.close()
    pre_tel, dec_tel = disagg.prefill.telemetry, disagg.decode.telemetry
    assert pre_tel.labels == {"pool": "prefill"}
    assert dec_tel.labels == {"pool": "decode"}
    # the decode pool's queue is the handoff queue; its occupancy and
    # handoff instruments are distinct series from the prefill pool's
    assert dec_tel.queue_depth is not pre_tel.queue_depth
    assert dec_tel.kv_handoff_pages.value == disagg.transfer.pages_moved
    assert dec_tel.kv_handoff_seconds.count == len(disagg.handoff_log)
    assert dec_tel.requests_total.value == 4
    assert pre_tel.requests_total.value == 0    # retirement is decode-side
    handoffs = read_events(log.path, kind=ev.KV_HANDOFF)
    assert len(handoffs) == 4
    assert all(h["pages"] >= 0 and h["seconds"] >= 0 for h in handoffs)
    admits = read_events(log.path, kind=ev.SLOT_ADMIT)
    pools = {a.get("pool") for a in admits}
    assert pools == {"prefill", "decode"}


def test_debug_pages_env_gates_reset_audit(monkeypatch):
    """Satellite: the O(num_pages) PageAllocator.check() audit runs on
    reset() only under TPU_DEBUG_PAGES=1 (the conftest sets it for the
    suite) — the bench's hot warmup→measure reset skips it."""
    assert os.environ.get("TPU_DEBUG_PAGES") == "1"
    _, disagg = _setup()
    calls = []
    monkeypatch.setattr(disagg.decode.page_allocator, "check",
                        lambda: calls.append(True))
    disagg.reset()
    assert calls                                # debug build: audited
    calls.clear()
    monkeypatch.delenv("TPU_DEBUG_PAGES")
    disagg.reset()
    assert not calls                            # production reset: O(1)
