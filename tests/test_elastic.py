"""Elastic gang resize — control-plane and telemetry units.

Covers the pieces the out-of-process smoke (scripts/tier1.sh --elastic)
exercises end-to-end: the resize ledger phase split and its Prometheus
rendering, spec.resize validation + serialization + controller sizing,
the auto-tuned stop-check cadence, FIRST_RESUME_STEP emission, and the
postmortem summary keys."""
import pytest

from mpi_operator_tpu.api.types import (
    Container, ObjectMeta, PodTemplateSpec, TPUJob, TPUJobSpec,
)
from mpi_operator_tpu.api.validation import ValidationError, validate_spec
from mpi_operator_tpu.cluster.serialize import from_manifest, to_manifest
from mpi_operator_tpu.postmortem import summarize
from mpi_operator_tpu.telemetry import events as ev
from mpi_operator_tpu.telemetry.collector import (
    JobObservatory, resize_ledger, resize_lines,
)
from mpi_operator_tpu.telemetry.events import EventLog
from mpi_operator_tpu.train.resilience import (
    ResilienceConfig, ResilienceContext, auto_stop_check_every,
    drain_latency_from_events, suggest_stop_check_every,
)


def _rec(event, ts, **fields):
    return {"ts": ts, "event": event, **fields}


#: one clean 4->2 resize: drain 0.4s, restore 0.7s, recompile 1.5s,
#: total 3.5s (drain start 10.0 -> first resume step 13.5)
_RESIZE_RECORDS = [
    _rec(ev.JOB_CREATED, 9.0, job="j"),
    _rec(ev.PREEMPTION_DRAIN, 10.0, step=5, stop_check_every=8),
    _rec(ev.EMERGENCY_CHECKPOINT, 10.4, step=5),
    _rec(ev.GANG_RESIZE, 11.0, job="j", workers=2, tpus=4, replicas=2),
    _rec(ev.CHECKPOINT_RESTORE, 12.0, step=5, seconds=0.7,
         resharded=True),
    _rec(ev.FIRST_RESUME_STEP, 13.5, step=7, seconds=1.5),
]


# ---------------------------------------------------------------------------
# resize ledger (telemetry/collector.py)
# ---------------------------------------------------------------------------

def test_resize_ledger_phase_split():
    (resize,) = resize_ledger(_RESIZE_RECORDS)
    assert resize["drain_seconds"] == 0.4
    assert resize["restore_seconds"] == 0.7
    assert resize["recompile_seconds"] == 1.5
    assert resize["total_seconds"] == 3.5      # drain start -> resume step
    assert resize["workers"] == 2 and resize["tpus"] == 4


def test_resize_ledger_incomplete_entry_kept():
    """A gang that died mid-resize still shows up — with only the phases
    it reached and no total."""
    records = _RESIZE_RECORDS[:4]              # no restore, no resume
    (resize,) = resize_ledger(records)
    assert resize["drain_seconds"] == 0.4
    assert "restore_seconds" not in resize
    assert "total_seconds" not in resize


def test_resize_ledger_ignores_plain_restores():
    """checkpoint_restore outside a resize window (ordinary restart)
    never opens a ledger entry."""
    records = [
        _rec(ev.CHECKPOINT_RESTORE, 5.0, step=3, seconds=0.2),
        _rec(ev.FIRST_RESUME_STEP, 6.0, step=4, seconds=0.9),
    ]
    assert resize_ledger(records) == []


def test_resize_lines_prometheus_text():
    lines = resize_lines("j", resize_ledger(_RESIZE_RECORDS))
    text = "\n".join(lines)
    # total 3.5 lands in the le=5.0 bucket and above, not le=2.5
    assert 'tpu_job_resize_seconds_bucket{job="j",le="2.5"} 0' in text
    assert 'tpu_job_resize_seconds_bucket{job="j",le="5.0"} 1' in text
    assert 'tpu_job_resize_seconds_bucket{job="j",le="+Inf"} 1' in text
    assert 'tpu_job_resize_seconds_count{job="j"} 1' in text
    assert 'tpu_job_resizes_total{job="j"} 1' in text
    assert 'tpu_job_resize_drain_seconds{job="j"} 0.4' in text
    assert 'tpu_job_resize_restore_seconds{job="j"} 0.7' in text
    assert 'tpu_job_resize_recompile_seconds{job="j"} 1.5' in text


def test_note_resize_gang_flag_picks_event():
    obs = JobObservatory()
    obs.note_resize("j", gang=True, workers=2, tpus=4)
    obs.note_resize("j", replicas=4)           # elastic shrink/grow
    events = [r["event"] for r in obs.view("j")["controller_records"]]
    assert events == [ev.GANG_RESIZE, ev.JOB_RESIZED]


# ---------------------------------------------------------------------------
# spec.resize (api + serialize + controller)
# ---------------------------------------------------------------------------

def _spec(**kw):
    return TPUJobSpec(
        template=PodTemplateSpec(
            containers=[Container(name="train", image="tpu-bench:latest")]
        ),
        **kw,
    )


def test_spec_resize_valid():
    validate_spec(_spec(tpus=8, resize=4))


@pytest.mark.parametrize("kw", [
    dict(replicas=2, resize=4),                # needs tpus sizing mode
    dict(tpus=8, num_slices=2, resize=4),      # single-slice only
    dict(tpus=8, resize=3),                    # not a valid chip count
    dict(tpus=8, elastic=True, resize=4),      # elastic owns sizing
    dict(tpus=8, resize=4, pack_group="g"),    # packed jobs are pinned
], ids=["mode", "slices", "ladder", "elastic", "packed"])
def test_spec_resize_rejected(kw):
    with pytest.raises(ValidationError):
        validate_spec(_spec(**kw))


def test_spec_resize_serialize_round_trip():
    job = TPUJob(metadata=ObjectMeta(name="j", namespace="default"),
                 spec=_spec(tpus=8, resize=4))
    manifest = to_manifest(job)
    assert manifest["spec"]["resize"] == 4
    assert from_manifest(manifest).spec.resize == 4
    # absent stays absent
    job.spec.resize = None
    assert from_manifest(to_manifest(job)).spec.resize is None


def test_controller_allocation_follows_resize():
    """spec.resize replaces the spec size in Mode A sizing — the edited
    target drives the next gang bootstrap."""
    from tests.test_controller import Fixture, new_job

    f = Fixture()
    job = new_job(tpus=8)
    f.seed(job)
    base = f.controller.allocate_processing_units(job, False)
    job.spec.resize = 4
    shrunk = f.controller.allocate_processing_units(job, False)
    assert shrunk.worker_replicas == base.worker_replicas // 2
    assert shrunk.units_per_worker == base.units_per_worker


# ---------------------------------------------------------------------------
# auto-tuned stop-check cadence (train/resilience.py)
# ---------------------------------------------------------------------------

def test_suggest_stop_check_every_scales_and_clamps():
    # 0.4s drain at cadence 8 with a 5s target -> 100
    assert suggest_stop_check_every(0.4, 8, target=5.0) == 100
    # slow drain shrinks the cadence, floor 1
    assert suggest_stop_check_every(80.0, 8, target=5.0) == 1
    # fast drain is capped at 256
    assert suggest_stop_check_every(0.001, 8, target=5.0) == 256
    assert suggest_stop_check_every(0.0, 8) is None
    assert suggest_stop_check_every(1.0, 0) is None


def _write_drain_events(tmp_path, drain_seconds=0.4, cadence=8):
    t = iter([100.0, 100.0 + drain_seconds])
    log = EventLog(str(tmp_path / "events.jsonl"), clock=lambda: next(t))
    log.emit(ev.PREEMPTION_DRAIN, step=5, stop_check_every=cadence)
    log.emit(ev.EMERGENCY_CHECKPOINT, step=5)
    log.close()
    return str(tmp_path / "events.jsonl")


def test_drain_latency_from_events(tmp_path):
    path = _write_drain_events(tmp_path)
    worst, cadence = drain_latency_from_events(path)
    assert worst == pytest.approx(0.4)
    assert cadence == 8
    assert drain_latency_from_events(str(tmp_path / "none.jsonl")) \
        == (None, None)


def test_auto_stop_check_every(tmp_path):
    _write_drain_events(tmp_path)
    logs = []
    assert auto_stop_check_every(str(tmp_path), log=logs.append) == 100
    assert any("auto-tuned to 100" in l for l in logs)
    # nothing measured yet -> default
    assert auto_stop_check_every(None) == 8
    assert auto_stop_check_every(str(tmp_path / "fresh")) == 8


def test_from_env_auto_cadence(tmp_path):
    _write_drain_events(tmp_path)
    cfg = ResilienceConfig.from_env(
        env={"TPU_STOP_CHECK_EVERY": "auto"}, train_dir=str(tmp_path))
    assert cfg.stop_check_every == 100
    cfg = ResilienceConfig.from_env(env={"TPU_STOP_CHECK_EVERY": "16"})
    assert cfg.stop_check_every == 16


# ---------------------------------------------------------------------------
# FIRST_RESUME_STEP (recompile-phase probe)
# ---------------------------------------------------------------------------

def test_first_resume_step_emitted_once(tmp_path):
    path = str(tmp_path / "events.jsonl")
    ctx = ResilienceContext(ResilienceConfig(train_dir=str(tmp_path)),
                            log=lambda s: None, events=EventLog(path))
    with ctx:
        ctx.record_restore(5, seconds=0.7, leaves=7, resharded=True)
        ctx.on_step(6)                 # first completed post-resume step
        ctx.on_step(7)
    records = ev.read_events(path)
    restores = [r for r in records if r["event"] == ev.CHECKPOINT_RESTORE]
    resumes = [r for r in records if r["event"] == ev.FIRST_RESUME_STEP]
    assert restores[0]["seconds"] == 0.7 and restores[0]["resharded"]
    assert len(resumes) == 1           # one-shot: step 7 emits nothing
    assert resumes[0]["step"] == 6 and resumes[0]["seconds"] >= 0


def test_fresh_start_emits_no_resume_events(tmp_path):
    path = str(tmp_path / "events.jsonl")
    ctx = ResilienceContext(ResilienceConfig(train_dir=str(tmp_path)),
                            log=lambda s: None, events=EventLog(path))
    with ctx:
        ctx.record_restore(0)          # step 0 == fresh start
        ctx.on_step(1)
    kinds = {r["event"] for r in ev.read_events(path)}
    assert ev.CHECKPOINT_RESTORE not in kinds
    assert ev.FIRST_RESUME_STEP not in kinds


# ---------------------------------------------------------------------------
# postmortem (satellites 1 + 2)
# ---------------------------------------------------------------------------

def test_postmortem_summary_resizes_and_suggestion():
    summary = summarize(_RESIZE_RECORDS)
    (resize,) = summary["resizes"]
    assert resize["t"] == 2.0          # rebased to the first record
    assert resize["total_seconds"] == 3.5
    assert "drain_start_ts" not in resize
    assert summary["suggested_stop_check_every"] == \
        suggest_stop_check_every(0.4, 8)
    # gang_resize is a milestone, first_resume_step an incident marker
    assert any(m["event"] == ev.GANG_RESIZE for m in summary["milestones"])
    assert any(i["event"] == ev.FIRST_RESUME_STEP
               for i in summary["incidents"])


def test_postmortem_render_mentions_resize():
    import io

    from mpi_operator_tpu.postmortem import render

    out = io.StringIO()
    render(summarize(_RESIZE_RECORDS), out)
    text = out.getvalue()
    assert "gang resizes:" in text
    assert "suggested --stop-check-every" in text
