"""KV-cache generation tests: the decode path must reproduce the
training-mode model exactly (greedy == teacher-forced argmax), and the
sampling/eos machinery must behave."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, generate, gpt2_config


def _setup(vocab=64, max_len=32):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=vocab, max_len=max_len)
    model = CausalLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, vocab)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), prompt))["params"]
    return model, params, prompt


def test_greedy_matches_teacher_forced():
    """Greedy KV-cache decode == argmax over repeated full-context
    forwards — pins the cache writes, the position offsets, and the
    visibility mask in one equality."""
    model, params, prompt = _setup()
    out = generate(model, params, prompt, max_new_tokens=6)
    full = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, full)
        full = jnp.concatenate(
            [full, jnp.argmax(logits[:, -1], -1)[:, None]], axis=1)
    assert np.array_equal(np.array(out.tokens), np.array(full))
    assert out.logprobs.shape == (2, 6)
    assert bool(jnp.all(out.logprobs <= 0))


def test_eos_freezes_finished_rows():
    model, params, prompt = _setup()
    free = generate(model, params, prompt, max_new_tokens=6)
    # greedy is deterministic: whatever row 0 emits second becomes the eos
    eos = int(free.tokens[0, prompt.shape[1] + 1])
    out = generate(model, params, prompt, max_new_tokens=6, eos_id=eos)
    row = np.array(out.tokens[0, prompt.shape[1]:])
    hit = int(np.argmax(row == eos))
    assert (row[hit:] == eos).all()          # frozen after first eos
    assert np.allclose(np.array(out.logprobs[0, hit + 1:]), 0.0)


def test_temperature_sampling_varies_with_rng():
    model, params, prompt = _setup()
    a = generate(model, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    b = generate(model, params, prompt, max_new_tokens=8, temperature=1.0,
                 rng=jax.random.PRNGKey(8))
    assert not np.array_equal(np.array(a.tokens), np.array(b.tokens))


def test_generate_with_tp_sharded_params():
    """Multi-chip inference: params sharded by the Megatron rules
    (shard_init on a tp mesh) flow straight into generate() — GSPMD
    partitions the decode program — and the tokens match the unsharded
    run exactly."""
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.parallel.sharding import shard_init

    model, params, prompt = _setup()
    mesh = make_mesh(MeshConfig(tp=4, dp=2))
    variables, _ = shard_init(model, mesh, jax.random.PRNGKey(0), prompt)
    sharded = variables["params"]
    k = sharded["backbone"]["block_0"]["mlp"]["fc_in"]["kernel"]
    assert "tp" in str(k.sharding.spec)

    out_sharded = generate(model, sharded, prompt, max_new_tokens=6)
    out_ref = generate(model, params, prompt, max_new_tokens=6)
    assert np.array_equal(np.array(out_sharded.tokens),
                          np.array(out_ref.tokens))


def test_top_k_one_equals_greedy():
    """top_k=1 sampling degenerates to argmax regardless of temperature —
    pins the filter against the greedy reference."""
    model, params, prompt = _setup()
    greedy = generate(model, params, prompt, max_new_tokens=6)
    k1 = generate(model, params, prompt, max_new_tokens=6, temperature=1.0,
                  rng=jax.random.PRNGKey(7), top_k=1)
    assert np.array_equal(np.array(greedy.tokens), np.array(k1.tokens))


def test_top_k_restricts_support():
    """Every top_k-sampled token must be among the k most likely under the
    model at its position (checked teacher-forced)."""
    model, params, prompt = _setup()
    k = 3
    out = generate(model, params, prompt, max_new_tokens=5, temperature=1.5,
                   rng=jax.random.PRNGKey(9), top_k=k)
    toks = np.array(out.tokens)
    P = prompt.shape[1]
    for t in range(5):
        logits = np.array(model.apply({"params": params},
                                      out.tokens[:, :P + t]))[:, -1]
        topk = np.argsort(logits, axis=-1)[:, -k:]
        for b in range(toks.shape[0]):
            assert toks[b, P + t] in topk[b]


def test_top_p_one_is_unfiltered_and_validation():
    import pytest

    model, params, prompt = _setup()
    rng = jax.random.PRNGKey(11)
    full = generate(model, params, prompt, max_new_tokens=6,
                    temperature=1.0, rng=rng)
    p1 = generate(model, params, prompt, max_new_tokens=6,
                  temperature=1.0, rng=rng, top_p=1.0)
    assert np.array_equal(np.array(full.tokens), np.array(p1.tokens))
    with pytest.raises(ValueError, match="temperature"):
        generate(model, params, prompt, max_new_tokens=2, top_k=5)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, max_new_tokens=2, temperature=1.0,
                 rng=rng, top_p=1.5)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_new_tokens=2, temperature=1.0,
                 rng=rng, top_k=0)


def test_generate_validation():
    model, params, prompt = _setup(max_len=8)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, max_new_tokens=10)
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_new_tokens=2, temperature=0.5)
    with pytest.raises(ValueError, match="temperature"):
        generate(model, params, prompt, max_new_tokens=2, temperature=-0.7)


def test_int8_kv_cache_generates_consistently():
    """kv_cache_dtype='int8': the quantized cache (half the HBM bytes)
    must stay numerically faithful — greedy decode agrees with the
    full-precision cache on nearly every token, logprobs stay finite, and
    the cache really stores int8."""
    import dataclasses

    from mpi_operator_tpu.models.transformer import llama_config

    cfg = llama_config("test", attention="dense", dtype=jnp.float32,
                       vocab_size=64, max_len=32)
    model = CausalLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 64)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), prompt))["params"]
    ref = generate(model, params, prompt, max_new_tokens=8)

    q_model = CausalLM(dataclasses.replace(cfg, kv_cache_dtype="int8"))
    out = generate(q_model, params, prompt, max_new_tokens=8)
    agree = float(np.mean(np.array(ref.tokens) == np.array(out.tokens)))
    assert agree >= 0.9, f"token agreement {agree}"
    assert bool(jnp.isfinite(out.logprobs).all())
    # white-box: the decode cache really is int8 + scales
    dec_cfg = dataclasses.replace(cfg, kv_cache_dtype="int8", decode=True)
    variables = CausalLM(dec_cfg).init(jax.random.PRNGKey(0), prompt)
    cache = variables["cache"]
    leaves = jax.tree_util.tree_leaves_with_path(cache)
    kinds = {jax.tree_util.keystr(p): l.dtype for p, l in leaves}
    assert any("cached_key" in k and v == jnp.int8 for k, v in kinds.items())
    assert any("key_scale" in k and v == jnp.float32
               for k, v in kinds.items())


def test_int8_quantization_error_bounded():
    """Symmetric per-vector int8: dequantized K/V within 1/127 relative
    of the original (the attend operands' max quantization error)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 7, 2, 16)) * 3.0
    scale = jnp.maximum(
        jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    back = q8.astype(jnp.float32) * scale
    rel = float(jnp.max(jnp.abs(back - x) / jnp.maximum(jnp.abs(x).max(-1,
                keepdims=True), 1e-8)))
    assert rel <= 1.0 / 127 + 1e-6
