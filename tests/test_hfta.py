"""HFTA fused-trainer tests: exactness pins, divergence isolation,
per-replica checkpoints, and labeled telemetry.

The load-bearing pins:

  - K=1 fused is BITWISE the solo LMTrainer — same loss, same params,
    step after step. This holds on a single-device mesh only: the solo
    trainer's compiled step is SPMD-partitioned over the dp mesh while
    the fused step is unpartitioned, and the different reduction
    schedules genuinely change the gradients (~1e-3 after clipping
    amplification). The 1-device mesh removes the partitioning delta and
    leaves only the fusion math, which must be exact.
  - K identical replicas produce K identical curves — the vmap stacking
    itself adds nothing.
  - one diverging replica freezes alone: its K-1 siblings' params stay
    bitwise equal to an unfaulted control run.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
from mpi_operator_tpu.parallel import MeshConfig, make_mesh
from mpi_operator_tpu.telemetry import render_registry
from mpi_operator_tpu.telemetry.core import Registry
from mpi_operator_tpu.train.checkpoint import (restore_checkpoint,
                                               save_checkpoint)
from mpi_operator_tpu.train.hfta import (HFTAHyperparams, HFTATrainer,
                                         poison_replica)
from mpi_operator_tpu.train.lm_trainer import LMTrainer, LMTrainerConfig
from mpi_operator_tpu.train.resilience import FaultInjector

pytestmark = pytest.mark.hfta

VOCAB = 128


def _model():
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=VOCAB, max_len=64)
    return CausalLM(cfg)


def _batch(i, batch=8, seq=16):
    toks = jax.random.randint(jax.random.fold_in(jax.random.PRNGKey(1), i),
                              (batch, seq), 0, VOCAB)
    return toks, jnp.roll(toks, -1, axis=1)


def _stacked(i, k, batch=8, seq=16):
    """K identical copies of the step-i batch, stacked to [K, B, S]."""
    toks, tgts = _batch(i, batch, seq)
    return (jnp.broadcast_to(toks, (k,) + toks.shape),
            jnp.broadcast_to(tgts, (k,) + tgts.shape))


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_k1_fused_bitwise_matches_solo_lm_trainer():
    """The exactness pin: on a 1-device mesh the K=1 fused step IS the
    solo step — loss and params bitwise equal for several steps (warmup
    crossover at step 2 included)."""
    mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
    model = _model()
    tcfg = LMTrainerConfig(global_batch_size=4, seq_len=16, warmup_steps=2)
    solo = LMTrainer(model, mesh, tcfg)
    fused = HFTATrainer(model, mesh, tcfg, HFTAHyperparams.sweep(1, tcfg))
    s_state = solo.init_state(jax.random.PRNGKey(0))
    f_state = fused.init_state()
    _leaves_equal(jax.tree.map(lambda x: x[0], f_state.params),
                  s_state.params)
    for i in range(4):
        toks, tgts = _batch(i, batch=4)
        s_state, sm = solo.train_step(s_state, toks, tgts)
        f_state, fm = fused.train_step(f_state, toks[None], tgts[None])
        assert float(fm["loss"][0]) == float(sm["loss"]), f"step {i}"
    _leaves_equal(jax.tree.map(lambda x: x[0], f_state.params),
                  s_state.params)


def test_k3_identical_hparams_identical_curves():
    """vmap stacking adds nothing: 3 replicas with identical seed/lr fed
    identical batches stay bitwise identical to each other."""
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2)
    tr = HFTATrainer(_model(), mesh, tcfg, HFTAHyperparams.sweep(3, tcfg))
    state = tr.init_state()
    for i in range(3):
        state, m = tr.train_step(state, *_stacked(i, 3))
        loss = np.asarray(m["loss"])
        assert loss[0] == loss[1] == loss[2], f"step {i}"
    for leaf in jax.tree.leaves(state.params):
        a = np.asarray(leaf)
        np.testing.assert_array_equal(a[0], a[1])
        np.testing.assert_array_equal(a[0], a[2])


def test_sweep_axes_validated_and_broadcast():
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16,
                           learning_rate=3e-4, weight_decay=0.1)
    with pytest.raises(ValueError, match="sweep axis"):
        HFTAHyperparams.sweep(3, tcfg, learning_rates=[1e-3])
    hp = HFTAHyperparams.sweep(2, tcfg, learning_rates=[1e-3, 2e-3])
    assert hp.k == 2
    assert hp.weight_decays == (0.1, 0.1)           # broadcast from config
    cfg1 = hp.replica_config(tcfg, 1)
    assert cfg1.learning_rate == 2e-3
    assert cfg1.weight_decay == 0.1


def test_unsupported_configs_rejected():
    mesh = make_mesh(MeshConfig(dp=8))
    with pytest.raises(ValueError, match="causal"):
        HFTATrainer(_model(), mesh,
                    LMTrainerConfig(global_batch_size=8, seq_len=16,
                                    masked_lm=True))
    with pytest.raises(ValueError, match="accumulation"):
        HFTATrainer(_model(), mesh,
                    LMTrainerConfig(global_batch_size=8, seq_len=16,
                                    accum_steps=2))


def test_poisoned_replica_freezes_siblings_bitwise_unaffected():
    """Divergence isolation: NaN-poison replica 1 mid-run. It must freeze
    (after freeze_after consecutive bad steps) while replicas 0/2 stay
    bitwise equal to an unfaulted control run — and the fused step never
    stalls (the step counter keeps advancing)."""
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2)
    tr = HFTATrainer(_model(), mesh, tcfg, HFTAHyperparams.sweep(3, tcfg),
                     freeze_after=2)
    ctrl = tr.init_state()
    fault = tr.init_state()
    for i in range(5):
        toks, tgts = _stacked(i, 3)
        ctrl, _ = tr.train_step(ctrl, toks, tgts)
        if i == 2:
            fault = poison_replica(fault, 1)
        fault, fm = tr.train_step(fault, toks, tgts)
    assert int(fault.step) == 5                       # never stalled
    frozen = np.asarray(fault.frozen)
    assert frozen.tolist() == [False, True, False]
    assert int(np.asarray(fault.nonfinite_streak)[1]) >= 2
    # siblings: every leaf bitwise equal to the control run
    for f, c in zip(jax.tree.leaves(fault.params),
                    jax.tree.leaves(ctrl.params)):
        f, c = np.asarray(f), np.asarray(c)
        np.testing.assert_array_equal(f[0], c[0])
        np.testing.assert_array_equal(f[2], c[2])
    # the poisoned replica is NaN and parked, its loss isolated to lane 1
    assert np.isnan(np.asarray(jax.tree.leaves(fault.params)[0])[1]).all()
    assert np.isnan(np.asarray(fm["loss"])[1])
    assert np.isfinite(np.asarray(fm["loss"])[[0, 2]]).all()


def test_fault_injector_nan_replica_directive():
    faults = FaultInjector("nan-replica:1@3")
    assert faults.check_nan_replica(2) is None
    assert faults.check_nan_replica(3) == 1
    assert faults.check_nan_replica(4) is None        # one-shot
    with pytest.raises(ValueError):
        FaultInjector("nan-replica:nope")


def test_stacked_checkpoint_roundtrip(tmp_path):
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2)
    tr = HFTATrainer(_model(), mesh, tcfg,
                     HFTAHyperparams.sweep(2, tcfg, seeds=[0, 7]))
    state = tr.init_state()
    for i in range(2):
        state, _ = tr.train_step(state, *_stacked(i, 2))
    save_checkpoint(str(tmp_path), state)
    restored = restore_checkpoint(str(tmp_path), tr.init_state())
    assert int(restored.step) == 2
    _leaves_equal(restored.params, state.params)
    _leaves_equal(restored.opt_state, state.opt_state)
    # and the restored state steps
    restored, m = tr.train_step(restored, *_stacked(2, 2))
    assert np.isfinite(np.asarray(m["loss"])).all()


def test_k8_slice_sharing_shards_replicas_over_mesh(tmp_path):
    """When K divides the mesh batch-axis extent, whole replicas shard
    over the devices (controller-side slice sharing at the data plane):
    the [K,...] state leaves carry a K-axis sharding, the step runs
    without cross-replica coupling, and extract/checkpoint still work."""
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2)
    tr = HFTATrainer(_model(), mesh, tcfg,
                     HFTAHyperparams.sweep(8, tcfg, seeds=list(range(8))))
    assert tr._replica_sharding is not None
    state = tr.init_state()
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.spec[0] is not None        # K axis is sharded
    for i in range(2):
        state, m = tr.train_step(state, *_stacked(i, 8))
    assert np.isfinite(np.asarray(m["loss"])).all()
    # the step output keeps the K-axis sharding — no silent fallback to
    # replicated params (that would re-run every adam update per device)
    out_leaf = jax.tree.leaves(state.params)[0]
    assert out_leaf.sharding.spec[0] is not None
    # replica extraction gathers across devices
    rep = tr.extract_replica(state, 5)
    _leaves_equal(rep.params,
                  jax.tree.map(lambda x: x[5], state.params))
    # sharded stacked checkpoint roundtrips through the same template
    save_checkpoint(str(tmp_path), state)
    restored = restore_checkpoint(str(tmp_path), tr.init_state())
    _leaves_equal(restored.params, state.params)
    restored, m = tr.train_step(restored, *_stacked(2, 8))
    assert np.isfinite(np.asarray(m["loss"])).all()


def test_export_replica_checkpoint_restores_into_solo_trainer(tmp_path):
    """A finished sweep member exports a NORMAL single-model checkpoint:
    restore it into a plain LMTrainer and keep training."""
    mesh = make_mesh(MeshConfig(dp=8))
    model = _model()
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2)
    tr = HFTATrainer(model, mesh, tcfg,
                     HFTAHyperparams.sweep(2, tcfg,
                                           learning_rates=[1e-3, 2e-3],
                                           seeds=[0, 7]))
    state = tr.init_state()
    for i in range(2):
        state, _ = tr.train_step(state, *_stacked(i, 2))
    tr.export_replica_checkpoint(str(tmp_path), state, 1)
    solo = LMTrainer(model, mesh, tr.hparams.replica_config(tcfg, 1))
    restored = restore_checkpoint(str(tmp_path),
                                  solo.init_state(jax.random.PRNGKey(7)))
    assert int(restored.step) == 2
    _leaves_equal(restored.params,
                  jax.tree.map(lambda x: x[1], state.params))
    toks, tgts = _batch(2)
    restored, m = solo.train_step(restored, toks, tgts)
    assert bool(np.isfinite(np.asarray(m["loss"])))


def test_benchmark_emits_per_replica_labeled_series():
    """One registry scrape carries each packed replica's own labeled
    tpu_worker_* series — the per-job view under controller packing."""
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=16, warmup_steps=2,
                           log_every=1)
    tr = HFTATrainer(_model(), mesh, tcfg, HFTAHyperparams.sweep(2, tcfg))

    def stream():
        i = 0
        while True:
            yield _stacked(i, 2)
            i += 1

    reg = Registry()
    state, metrics = tr.benchmark(tr.init_state(), stream(), num_steps=2,
                                  warmup_steps=1, log=lambda s: None,
                                  registry=reg, faults=FaultInjector(""))
    assert metrics["k"] == 2
    assert metrics["tokens_per_sec"] > 0
    assert metrics["per_replica"]["goodput"] == [1.0, 1.0]
    assert len(metrics["per_replica"]["tokens_per_sec"]) == 2
    text = render_registry(reg)
    assert 'tpu_worker_tokens_per_sec{replica="0"}' in text
    assert 'tpu_worker_tokens_per_sec{replica="1"}' in text
    assert 'tpu_worker_goodput{replica="1"}' in text
