"""Real-cluster backend tests: KubeAPIServer against a wire-level fake.

The reference pins its controller's behavior by asserting recorded client
Actions (mpi_job_controller_test.go:271-311). These tests go one level
deeper for the real-cluster adapter: the full `TPUJobController` runs
against `KubeAPIServer`, which speaks actual HTTP/JSON to an in-process
fake API server — so the asserted bodies are byte-for-byte what a real
cluster would receive (the manifests the reference's Go structs marshal to,
e.g. newWorker mpi_job_controller.go:1004-1083).
"""
import textwrap
import threading
import time

import pytest

from mpi_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    OwnerReference,
    PodTemplateSpec,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
    JobCondition,
    ReplicaStatus,
    new_tpu_job,
)
from mpi_operator_tpu.cluster.apiserver import (
    AlreadyExistsError,
    NotFoundError,
)
from mpi_operator_tpu.cluster.kubeclient import (
    KubeAPIServer,
    KubeConfig,
    KubeConfigError,
)
from mpi_operator_tpu.cluster.serialize import (
    from_manifest,
    parse_time,
    rfc3339,
    to_manifest,
)
from mpi_operator_tpu.controller import ControllerConfig, TPUJobController

from fake_kube_apiserver import FakeKubeAPIServer


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture()
def fake_server():
    server = FakeKubeAPIServer().start()
    yield server
    server.stop()


@pytest.fixture()
def kube(fake_server):
    client = KubeAPIServer(KubeConfig(server=fake_server.url),
                           request_timeout=5.0, watch_timeout_seconds=2)
    yield client
    client.stop()


def wait_for(pred, desc: str, timeout: float = 10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for: {desc}")


def sample_job(name="trainjob", **kw) -> TPUJob:
    job = new_tpu_job(name, tpus=8, **kw)
    job.spec.template.main_container().image = "tpu-bench:latest"
    return job


# ---------------------------------------------------------------------------
# serialization round-trips
# ---------------------------------------------------------------------------

class TestSerializeRoundTrip:
    def test_tpujob_full(self):
        job = sample_job()
        job.metadata.labels = {"team": "ml"}
        job.spec.slice_topology = "4x2"
        job.spec.backoff_limit = 3
        job.spec.launcher_on_master = True
        job.spec.template.main_container().env = {"A": "1"}
        job.spec.template.main_container().limits = {"google.com/tpu": 4}
        job.status = TPUJobStatus(
            launcher_status="Active", worker_replicas=2,
            start_time=1700000000.0,
            replica_statuses={"worker": ReplicaStatus(active=2)},
        )
        job.status.set_condition(JobCondition(type="Created", status="True",
                                              reason="TPUJobCreated"))
        back = from_manifest(to_manifest(job))
        assert back.spec == job.spec
        assert back.metadata.labels == {"team": "ml"}
        assert back.status.launcher_status == "Active"
        assert back.status.worker_replicas == 2
        assert back.status.start_time == 1700000000.0
        assert back.status.replica_statuses["worker"].active == 2
        assert back.status.get_condition("Created").reason == "TPUJobCreated"

    def test_children_roundtrip(self):
        """Every child kind the reconciler materializes survives the wire."""
        cfg = ControllerConfig()
        ctl = TPUJobController.__new__(TPUJobController)  # constructors only
        ctl.config = cfg
        job = sample_job()
        job.metadata.uid = "uid-7"
        alloc = ctl.allocate_processing_units(job, False)
        for obj in (
            ctl.new_config_map(job, alloc),
            ctl.new_launcher_service_account(job),
            ctl.new_launcher_role(job, alloc),
            ctl.new_launcher_role_binding(job),
            ctl.new_worker_service(job),
            ctl.new_pdb(job, alloc.worker_replicas),
            ctl.new_worker(job, alloc),
            ctl.new_launcher(job, alloc),
        ):
            back = from_manifest(to_manifest(obj))
            assert back.metadata.name == obj.metadata.name
            assert back.metadata.owner_references == \
                obj.metadata.owner_references
            if hasattr(obj, "spec"):
                assert back.spec == obj.spec
            if obj.kind == "ConfigMap":
                assert back.data == obj.data
            if obj.kind == "Role":
                assert back.rules == obj.rules

    def test_event_and_pod_roundtrip(self):
        """The Event sink kind and the Pod read-path kind survive the wire
        (timestamps quantize to whole seconds — RFC3339 without fractions,
        same as every other kind)."""
        from mpi_operator_tpu.cluster.resources import (
            Event, ObjectReference, Pod, PodStatus)

        ev = Event(
            metadata=ObjectMeta(name="trainjob.1a2b3c", namespace="default"),
            involved_object=ObjectReference(
                kind="TPUJob", namespace="default", name="trainjob",
                uid="uid-7", api_version="tpu.kubeflow.org/v1alpha1"),
            reason="Synced", message="TPUJob synced successfully",
            type="Normal", count=3,
            first_timestamp=1700000000.0, last_timestamp=1700000600.0,
            source_component="tpu-operator")
        back = from_manifest(to_manifest(ev))
        assert back == ev

        pod = Pod(
            metadata=ObjectMeta(name="trainjob-worker-0",
                                namespace="default",
                                labels={"tpu_job_name": "trainjob",
                                        "tpu_job_role": "worker"}),
            status=PodStatus(phase="Running", restart_count=2, exit_code=137))
        back = from_manifest(to_manifest(pod))
        assert back == pod

    def test_time_format(self):
        assert rfc3339(0.0) == "1970-01-01T00:00:00Z"
        assert parse_time("1970-01-01T00:00:00Z") == 0.0
        assert parse_time(rfc3339(1700000000.0)) == 1700000000.0
        assert parse_time("2023-11-14T22:13:20.5Z") == 1700000000.0
        assert parse_time(None) is None


# ---------------------------------------------------------------------------
# kubeconfig loading
# ---------------------------------------------------------------------------

class TestKubeConfig:
    def test_from_kubeconfig_token(self, tmp_path):
        cfg_file = tmp_path / "config"
        cfg_file.write_text(textwrap.dedent("""\
            apiVersion: v1
            kind: Config
            current-context: dev
            contexts:
            - name: dev
              context: {cluster: c1, user: u1}
            clusters:
            - name: c1
              cluster:
                server: https://10.0.0.1:6443
                insecure-skip-tls-verify: true
            users:
            - name: u1
              user: {token: sekrit}
        """))
        cfg = KubeConfig.from_kubeconfig(str(cfg_file))
        assert cfg.server == "https://10.0.0.1:6443"
        assert cfg.token == "sekrit"
        assert cfg.insecure_skip_tls_verify

    def test_load_precedence_master_overrides(self, tmp_path):
        cfg_file = tmp_path / "config"
        cfg_file.write_text(textwrap.dedent("""\
            current-context: dev
            contexts:
            - name: dev
              context: {cluster: c1, user: u1}
            clusters:
            - name: c1
              cluster: {server: "https://a:6443"}
            users:
            - name: u1
              user: {token: t}
        """))
        cfg = KubeConfig.load(kubeconfig=str(cfg_file),
                              master="https://b:6443")
        assert cfg.server == "https://b:6443"
        assert cfg.token == "t"

    def test_in_cluster_outside_cluster_raises(self, monkeypatch):
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(KubeConfigError):
            KubeConfig.load()


# ---------------------------------------------------------------------------
# CRUD against the wire
# ---------------------------------------------------------------------------

class TestKubeCRUD:
    def test_create_get_roundtrip(self, kube):
        created = kube.create(sample_job())
        assert created.metadata.uid.startswith("uid-")
        assert created.metadata.resource_version == "1"
        got = kube.get("TPUJob", "default", "trainjob")
        assert got.spec.tpus == 8
        assert got.spec.template.main_container().image == "tpu-bench:latest"

    def test_create_duplicate_is_already_exists(self, kube):
        kube.create(sample_job())
        with pytest.raises(AlreadyExistsError):
            kube.create(sample_job())

    def test_get_missing_is_not_found(self, kube):
        with pytest.raises(NotFoundError):
            kube.get("TPUJob", "default", "nope")
        assert kube.try_get("TPUJob", "default", "nope") is None

    def test_update_bumps_resource_version(self, kube):
        created = kube.create(sample_job())
        created.spec.tpus = 16
        updated = kube.update(created)
        assert updated.spec.tpus == 16
        assert updated.metadata.resource_version != \
            created.metadata.resource_version

    def test_update_status_leaves_spec(self, kube, fake_server):
        created = kube.create(sample_job())
        created.spec.tpus = 32          # must NOT be persisted via /status
        created.status.launcher_status = "Active"
        kube.update_status(created)
        got = kube.get("TPUJob", "default", "trainjob")
        assert got.spec.tpus == 8
        assert got.status.launcher_status == "Active"
        paths = [r.path for r in fake_server.requests_of("PUT", "tpujobs")]
        assert paths == [
            "/apis/tpu.kubeflow.org/v1alpha1/namespaces/default/tpujobs"
            "/trainjob/status"]

    def test_plain_update_cannot_change_status(self, kube, fake_server):
        """A real server with the status subresource enabled strips .status
        from plain PUTs — status writes must go through update_status."""
        created = kube.create(sample_job())
        created.status.launcher_status = "Succeeded"   # smuggled in a PUT
        kube.update(created)
        got = kube.get("TPUJob", "default", "trainjob")
        assert got.status.launcher_status is None

    def test_failed_job_enriched_with_pod_exit_code(self, kube, fake_server):
        """The ExitCode restart policy needs the container exit code, which
        batch/v1 JobStatus omits — the adapter reads it from the Job's pods
        (ref v1alpha2 common_types.go:150-155)."""
        from mpi_operator_tpu.cluster.resources import Job as BatchJob
        job = BatchJob(metadata=ObjectMeta(name="tj-launcher",
                                           namespace="default"))
        kube.create(job)
        # play kubelet: a pod of this Job died with exit code 17
        kube._request("POST", "/api/v1/namespaces/default/pods", body={
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "tj-launcher-abc12",
                         "labels": {"job-name": "tj-launcher"}},
            "status": {"containerStatuses": [
                {"name": "tpu", "state": {"terminated": {"exitCode": 17}}}]},
        })
        fake_server.set_status("jobs", "default", "tj-launcher",
                               {"failed": 1})
        got = kube.get("Job", "default", "tj-launcher")
        assert got.status.failed == 1
        assert got.status.exit_code == 17

    def test_delete(self, kube):
        kube.create(sample_job())
        kube.delete("TPUJob", "default", "trainjob")
        with pytest.raises(NotFoundError):
            kube.get("TPUJob", "default", "trainjob")
        with pytest.raises(NotFoundError):
            kube.delete("TPUJob", "default", "trainjob")

    def test_list_namespaced_and_cluster_wide(self, kube):
        kube.create(sample_job("a"))
        kube.create(sample_job("b", namespace="other"))
        assert [j.metadata.name for j in kube.list("TPUJob", "default")] \
            == ["a"]
        assert sorted(j.metadata.name for j in kube.list("TPUJob")) \
            == ["a", "b"]

    def test_admission_applies_client_side(self, kube):
        from mpi_operator_tpu.api.validation import validate_spec
        kube.register_admission_validator(
            "TPUJob", lambda o: validate_spec(o.spec))
        bad = new_tpu_job("bad")          # no sizing mode at all
        from mpi_operator_tpu.cluster.apiserver import ApiError
        with pytest.raises(ApiError):
            kube.create(bad)


# ---------------------------------------------------------------------------
# watch
# ---------------------------------------------------------------------------

class TestKubeWatch:
    def test_watch_sees_lifecycle(self, kube, fake_server):
        events = []
        seen = threading.Event()

        def handler(etype, obj, old):
            events.append((etype, obj.metadata.name,
                           old.metadata.name if old else None))
            seen.set()

        kube.watch("TPUJob", handler, namespace="default")
        kube.create(sample_job())
        wait_for(lambda: ("ADDED", "trainjob", None) in events,
                 "ADDED event")
        job = kube.get("TPUJob", "default", "trainjob")
        job.spec.tpus = 16
        kube.update(job)
        wait_for(lambda: any(e[0] == "MODIFIED" for e in events),
                 "MODIFIED event")
        modified = [e for e in events if e[0] == "MODIFIED"][0]
        assert modified[2] == "trainjob"      # old obj provided from cache
        kube.delete("TPUJob", "default", "trainjob")
        wait_for(lambda: any(e[0] == "DELETED" for e in events),
                 "DELETED event")


# ---------------------------------------------------------------------------
# wire-format pinning: what the operator actually sends a real cluster
# ---------------------------------------------------------------------------

class TestWireFormat:
    """Create one TPUJob through the real controller and pin the exact JSON
    bodies of every child resource (ref newWorker/newLauncher/newConfigMap,
    mpi_job_controller.go:849-1236)."""

    @pytest.fixture()
    def reconciled(self, kube, fake_server):
        controller = TPUJobController(kube, config=ControllerConfig())
        stop = threading.Event()
        controller.run(threadiness=1, stop_event=stop)
        job = sample_job()
        kube.create(job)
        wait_for(lambda: fake_server.get_object(
            "jobs", "default", "trainjob-launcher") is not None
            or fake_server.get_object(
                "statefulsets", "default", "trainjob-worker") is not None,
            "reconcile fan-out")
        wait_for(lambda: fake_server.get_object(
            "statefulsets", "default", "trainjob-worker"), "worker sts")
        yield fake_server
        stop.set()
        controller.queue.shut_down()

    def test_statefulset_manifest(self, reconciled):
        sts = reconciled.get_object("statefulsets", "default",
                                    "trainjob-worker")
        assert sts["apiVersion"] == "apps/v1"
        spec = sts["spec"]
        assert spec["replicas"] == 2                  # tpus=8 / 4 per worker
        assert spec["serviceName"] == "trainjob-worker"
        assert spec["podManagementPolicy"] == "Parallel"
        assert spec["selector"]["matchLabels"] == {
            "tpu_job_name": "trainjob", "tpu_job_role": "worker"}
        tmpl = spec["template"]
        assert tmpl["metadata"]["labels"] == {
            "tpu_job_name": "trainjob", "tpu_job_role": "worker"}
        pod = tmpl["spec"]
        assert pod["restartPolicy"] == "Always"       # ref :1021
        assert pod["nodeSelector"] == {
            "cloud.google.com/gke-tpu-accelerator": "v5litepod"}
        assert pod["volumes"] == [{
            "name": "tpu-job-config",
            "configMap": {"name": "trainjob-config"}}]
        c = pod["containers"][0]
        assert c["image"] == "tpu-bench:latest"
        assert c["resources"]["limits"] == {"google.com/tpu": "4"}
        # TPU-health readiness gate on the wire (SURVEY §7): kubelet must
        # see the exec probe so Ready == "chips enumerated"
        probe = c["readinessProbe"]
        assert probe["exec"]["command"] == [
            "/bin/sh", "-c", "test -f /tmp/tpu-ready"]
        assert probe["failureThreshold"] >= 30
        assert {"name": "tpu-job-config",
                "mountPath": "/etc/tpu"} in c["volumeMounts"]
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["TPU_WORKER_HOSTNAMES"] == \
            "trainjob-worker-0,trainjob-worker-1"
        assert env["TPU_NUM_PROCESSES"] == "2"
        # ownership: real GC needs a controller ownerReference (ref :876-878)
        owner = sts["metadata"]["ownerReferences"][0]
        assert owner["kind"] == "TPUJob"
        assert owner["controller"] is True
        assert owner["blockOwnerDeletion"] is True
        assert owner["uid"].startswith("uid-")

    def test_configmap_and_rbac_manifests(self, reconciled):
        cm = reconciled.get_object("configmaps", "default", "trainjob-config")
        assert cm["apiVersion"] == "v1"
        assert cm["data"]["worker-hostnames"] == (
            "trainjob-worker-0.trainjob-worker.default.svc\n"
            "trainjob-worker-1.trainjob-worker.default.svc\n")
        assert cm["data"]["coordinator-address"] == (
            "trainjob-worker-0.trainjob-worker.default.svc:8476")
        role = reconciled.get_object("roles", "default", "trainjob-launcher")
        assert role["apiVersion"] == "rbac.authorization.k8s.io/v1"
        names = [n for rule in role["rules"]
                 for n in rule.get("resourceNames", [])]
        assert "trainjob-worker-0" in names          # per-pod least privilege
        rb = reconciled.get_object("rolebindings", "default",
                                   "trainjob-launcher")
        assert rb["roleRef"] == {
            "apiGroup": "rbac.authorization.k8s.io", "kind": "Role",
            "name": "trainjob-launcher"}
        assert rb["subjects"] == [{
            "kind": "ServiceAccount", "name": "trainjob-launcher",
            "namespace": "default"}]

    def test_headless_service_manifest(self, reconciled):
        svc = reconciled.get_object("services", "default", "trainjob-worker")
        assert svc["spec"]["clusterIP"] == "None"
        assert svc["spec"]["selector"]["tpu_job_name"] == "trainjob"
        # pod A-records must exist BEFORE Readiness (the rendezvous and
        # the discovery init wait both run pre-Ready) — without this the
        # TPU-health gate deadlocks against Ready-gated DNS
        assert svc["spec"]["publishNotReadyAddresses"] is True

    def test_synced_event_posted_over_the_wire(self, reconciled):
        """The recorder reaches the real core-v1 Events sink (ref
        StartRecordingToSink, mpi_job_controller.go:165-172; Synced event
        :518): after a reconcile the scripted server must hold a POSTed
        Event manifest with the exact wire fields kubectl consumes."""
        events = reconciled.objects_of("events")
        synced = [e for e in events if e.get("reason") == "Synced"]
        assert synced, f"no Synced event posted; got {events}"
        ev = synced[0]
        assert ev["apiVersion"] == "v1"
        assert ev["kind"] == "Event"
        assert ev["type"] == "Normal"
        assert ev["message"] == "TPUJob synced successfully"
        assert ev["source"] == {"component": "tpu-operator"}
        io = ev["involvedObject"]
        assert io["kind"] == "TPUJob"
        assert io["name"] == "trainjob"
        assert io["apiVersion"] == "tpu.kubeflow.org/v1alpha1"
        assert io["uid"]                        # correlatable by kubectl
        assert ev["firstTimestamp"].endswith("Z")
        assert ev["count"] >= 1
        # the Event's name is "<involved>.<hex>" (client-go convention)
        assert ev["metadata"]["name"].startswith("trainjob.")


# ---------------------------------------------------------------------------
# full lifecycle over the wire (SURVEY §3.3 end-to-end)
# ---------------------------------------------------------------------------

class TestCLIRealClusterMode:
    def test_main_runs_controller_against_kubeconfig(self, fake_server,
                                                     tmp_path):
        """`python -m mpi_operator_tpu --kube-config X` constructs the real
        controller path (ref cmd/mpi-operator/main.go:42-96)."""
        from mpi_operator_tpu.__main__ import main
        cfg_file = tmp_path / "kubeconfig"
        cfg_file.write_text(textwrap.dedent(f"""\
            current-context: test
            contexts:
            - name: test
              context: {{cluster: fake, user: u}}
            clusters:
            - name: fake
              cluster: {{server: "{fake_server.url}"}}
            users:
            - name: u
              user: {{}}
        """))
        # seed a job; the controller must reconcile it after startup sync
        kube = KubeAPIServer(KubeConfig(server=fake_server.url))
        kube.create(sample_job())

        stop = threading.Event()
        result = {}
        t = threading.Thread(
            target=lambda: result.setdefault("rc", main(
                ["--kube-config", str(cfg_file)], stop_event=stop)),
            daemon=True)
        t.start()
        try:
            wait_for(lambda: fake_server.get_object(
                "statefulsets", "default", "trainjob-worker"),
                "reconcile from CLI-constructed controller")
        finally:
            stop.set()
            t.join(timeout=10)
        assert result.get("rc") == 0

    def test_main_bad_kubeconfig_errors(self, tmp_path, capsys):
        from mpi_operator_tpu.__main__ import main
        rc = main(["--kube-config", str(tmp_path / "missing")],
                  stop_event=threading.Event())
        assert rc == 2


class TestRealClusterLifecycle:
    def test_job_runs_to_completion(self, kube, fake_server):
        controller = TPUJobController(kube, config=ControllerConfig())
        stop = threading.Event()
        controller.run(threadiness=1, stop_event=stop)
        try:
            kube.create(sample_job())
            wait_for(lambda: fake_server.get_object(
                "statefulsets", "default", "trainjob-worker"), "worker sts")
            # play kubelet: all workers become ready
            fake_server.set_status("statefulsets", "default",
                                   "trainjob-worker",
                                   {"readyReplicas": 2, "replicas": 2})
            wait_for(lambda: fake_server.get_object(
                "jobs", "default", "trainjob-launcher"),
                "launcher gated on readiness")
            # play kubelet: launcher completes
            fake_server.set_status(
                "jobs", "default", "trainjob-launcher",
                {"succeeded": 1,
                 "completionTime": "2026-01-01T00:00:00Z"})
            done = wait_for(
                lambda: (kube.get("TPUJob", "default", "trainjob")
                         .status.is_done()) or None,
                "TPUJob Succeeded")
            assert done
            job = kube.get("TPUJob", "default", "trainjob")
            assert job.status.launcher_status == "Succeeded"
            wait_for(lambda: fake_server.get_object(
                "statefulsets", "default",
                "trainjob-worker")["spec"]["replicas"] == 0,
                "workers scaled down")
        finally:
            stop.set()
            controller.queue.shut_down()
