"""LM trainer tests: sharded training convergence + objective math."""
import jax
import jax.numpy as jnp
import numpy as np

from mpi_operator_tpu.models.transformer import CausalLM, MaskedLM, \
    bert_config, gpt2_config
from mpi_operator_tpu.parallel import MeshConfig, make_mesh
from mpi_operator_tpu.train.lm_trainer import (
    LMTrainer, LMTrainerConfig, lm_loss)


def _trainer(mesh_cfg, model_cfg_kw=None, **tcfg_kw):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64, **(model_cfg_kw or {}))
    mesh = make_mesh(mesh_cfg)
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=32, warmup_steps=2,
                           **tcfg_kw)
    tr = LMTrainer(CausalLM(cfg), mesh, tcfg)
    return tr


def _batch(tr, vocab=128):
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, vocab)
    tgts = jnp.roll(toks, -1, axis=1)
    return (jax.device_put(toks, tr.batch_sharding),
            jax.device_put(tgts, tr.batch_sharding))


def test_loss_decreases_dp_fsdp_tp():
    tr = _trainer(MeshConfig(dp=2, fsdp=2, tp=2))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks, tgts = _batch(tr)
    losses = []
    for _ in range(5):
        state, m = tr.train_step(state, toks, tgts)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_moe_variant_trains():
    tr = _trainer(MeshConfig(dp=2, ep=2, tp=2),
                  model_cfg_kw={"num_experts": 4, "moe_every": 2})
    state = tr.init_state(jax.random.PRNGKey(0))
    toks, tgts = _batch(tr)
    state, m = tr.train_step(state, toks, tgts)
    assert bool(jnp.isfinite(m["loss"]))


def test_masked_lm_objective():
    """BERT path: only masked positions are scored."""
    cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    mesh = make_mesh(MeshConfig(dp=8))
    tcfg = LMTrainerConfig(global_batch_size=8, seq_len=32, masked_lm=True)
    tr = LMTrainer(MaskedLM(cfg), mesh, tcfg)
    state = tr.init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
    tgts = toks
    mask = jnp.zeros((8, 32)).at[:, ::4].set(1.0)   # 25% masked slots
    state, m = tr.train_step(
        state, jax.device_put(toks, tr.batch_sharding),
        jax.device_put(tgts, tr.batch_sharding),
        jax.device_put(mask, tr.batch_sharding))
    assert bool(jnp.isfinite(m["loss"]))


def test_lm_loss_mask_math():
    logits = jnp.zeros((1, 4, 8))
    targets = jnp.zeros((1, 4), jnp.int32)
    full = lm_loss(logits, targets)
    half = lm_loss(logits, targets, jnp.array([[1.0, 1.0, 0.0, 0.0]]))
    # uniform logits → loss = log(8) regardless of which slots are scored
    np.testing.assert_allclose(float(full), float(jnp.log(8.0)), rtol=1e-6)
    np.testing.assert_allclose(float(half), float(jnp.log(8.0)), rtol=1e-6)


def test_optimizer_state_sharded_like_params():
    tr = _trainer(MeshConfig(tp=8))
    state = tr.init_state(jax.random.PRNGKey(0))
    p = state.params["backbone"]["block_0"]["mlp"]["fc_in"]["kernel"]
    # find the matching adam mu leaf
    mus = [l for l in jax.tree.leaves(state.opt_state)
           if hasattr(l, "shape") and l.shape == p.shape]
    assert mus, "no optimizer moment matching the param"
    assert mus[0].sharding == p.sharding


def test_dp_fsdp_tp_compile_warning_clean(capfd):
    """The sharding rules must compile with zero GSPMD 'involuntary full
    rematerialization' warnings — each one is a silent full-activation
    allgather on the hot path (round-1 verdict weak #2; fixed by the
    activation constraints in models/transformer._constrain + the
    replicated position-embedding rule)."""
    tr = _trainer(MeshConfig(dp=2, fsdp=2, tp=2))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks, tgts = _batch(tr)
    tr.train_step(state, toks, tgts)          # first call compiles
    err = capfd.readouterr().err
    assert "rematerialization" not in err, err


def test_sp_ring_trainer_matches_dense():
    """Context parallelism through the trainer: attention="ring" on an
    sp-sharded mesh must reproduce the dense single-axis run — same losses
    across steps (which pins the ring backward too, since step N's loss
    depends on step N-1's gradients)."""
    import optax

    losses = {}
    for name, mesh_cfg, attn in (
            ("dense", MeshConfig(dp=8), "dense"),
            ("ring", MeshConfig(dp=2, sp=4), "ring")):
        cfg = gpt2_config("test", attention=attn, dtype=jnp.float32,
                          vocab_size=128, max_len=64)
        tr = LMTrainer(CausalLM(cfg), make_mesh(mesh_cfg),
                       LMTrainerConfig(global_batch_size=8, seq_len=32),
                       tx=optax.sgd(0.1))
        state = tr.init_state(jax.random.PRNGKey(0))
        toks, tgts = _batch(tr)
        ls = []
        for _ in range(3):
            state, m = tr.train_step(state, toks, tgts)
            ls.append(float(m["loss"]))
        losses[name] = ls
    np.testing.assert_allclose(losses["ring"], losses["dense"], atol=2e-4)
    assert losses["dense"][-1] < losses["dense"][0]   # actually training


def test_sp_tp_ring_composes():
    """sp×tp: ring attention with the heads dim sharded over tp (each tp
    rank rings its own head group) — one step, loss matches dense."""
    cfg = gpt2_config("test", attention="ring", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=2, sp=2, tp=2)),
                   LMTrainerConfig(global_batch_size=8, seq_len=32,
                                   warmup_steps=2))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks, tgts = _batch(tr)
    _, m_ring = tr.train_step(state, toks, tgts)

    dtr = _trainer(MeshConfig(dp=8))
    dstate = dtr.init_state(jax.random.PRNGKey(0))
    _, m_dense = dtr.train_step(dstate, *_batch(dtr))
    np.testing.assert_allclose(float(m_ring["loss"]),
                               float(m_dense["loss"]), atol=2e-4)


def test_ring_without_sp_context_raises():
    """attention="ring" outside both shard_map and an sp-mesh scope is a
    clear error, not a silent misconfiguration."""
    import pytest

    cfg = gpt2_config("test", attention="ring", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    model = CausalLM(cfg)
    with pytest.raises(ValueError, match="sp"):
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((2, 32), jnp.int32))
    # an sp=1 mesh is equally a misconfiguration (degenerate ring), not a
    # silent fallback
    tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                   LMTrainerConfig(global_batch_size=8, seq_len=32))
    with pytest.raises(ValueError, match="sp"):
        tr.init_state(jax.random.PRNGKey(0))


def test_eval_step_matches_train_loss():
    """eval_step at the current params equals the loss train_step reports
    (train computes loss BEFORE applying the update) — pins that the eval
    path shares the exact objective, sharded the same way."""
    tr = _trainer(MeshConfig(dp=2, fsdp=2, tp=2))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks, tgts = _batch(tr)
    ev = float(tr.eval_step(state, toks, tgts))
    _, m = tr.train_step(state, toks, tgts)
    np.testing.assert_allclose(ev, float(m["loss"]), atol=1e-5)


def test_evaluate_reports_perplexity():
    tr = _trainer(MeshConfig(dp=8))
    state = tr.init_state(jax.random.PRNGKey(0))

    class Stream:
        def __iter__(self):
            return self

        def __next__(self):
            return _batch(tr)

    out = tr.evaluate(state, Stream(), num_batches=2)
    assert np.isfinite(out["val_loss"])
    np.testing.assert_allclose(out["perplexity"], np.exp(out["val_loss"]),
                               rtol=1e-6)


def test_cosine_schedule_option():
    """The schedule make_adamw actually drives: warmup to peak, cosine
    decay to the floor, warmup-clamped decay horizon; unknown names are
    rejected."""
    import pytest

    from mpi_operator_tpu.train.lm_trainer import (LMTrainerConfig,
                                                   make_lr_schedule)

    cfg = LMTrainerConfig(learning_rate=1e-3, warmup_steps=10,
                          lr_schedule="cosine", decay_steps=100,
                          end_lr_fraction=0.1)
    sched = make_lr_schedule(cfg)
    assert float(sched(10)) == pytest.approx(1e-3)          # peak
    assert float(sched(100)) == pytest.approx(1e-4, rel=1e-3)  # floor
    # decay_steps <= warmup_steps clamps instead of crashing optax
    clamped = make_lr_schedule(LMTrainerConfig(
        learning_rate=1e-3, warmup_steps=10, lr_schedule="cosine",
        decay_steps=5))
    assert float(clamped(10)) == pytest.approx(1e-3, rel=1e-2)
    lin = make_lr_schedule(LMTrainerConfig(learning_rate=1e-3,
                                           warmup_steps=10))
    assert float(lin(10)) == pytest.approx(1e-3)
    assert float(lin(1000)) == pytest.approx(1e-3)          # constant after
    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(LMTrainerConfig(lr_schedule="nope"))


def test_grad_accumulation_matches_single_step():
    """accum_steps=2 must produce the SAME update as the unaccumulated
    step on the same global batch: mean of microbatch mean-grads equals
    the full-batch mean grad (linearity), so with sgd the params after one
    optimizer step are identical."""
    import optax

    toks = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0, 128)
    tgts = jnp.roll(toks, -1, axis=1)
    outs = {}
    for accum in (1, 2):
        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=64)
        tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                       LMTrainerConfig(global_batch_size=16, seq_len=32,
                                       accum_steps=accum),
                       tx=optax.sgd(0.1))
        state = tr.init_state(jax.random.PRNGKey(0))
        state, m = tr.train_step(
            state, jax.device_put(toks, tr.batch_sharding),
            jax.device_put(tgts, tr.batch_sharding))
        outs[accum] = (float(m["loss"]), state.params)
    assert abs(outs[2][0] - outs[1][0]) < 1e-5
    for a, b in zip(jax.tree.leaves(outs[2][1]),
                    jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_grad_accumulation_masked_lm_exact():
    """The masked objective is the hard case: microbatches carry DIFFERENT
    mask counts, so naive mean-of-means would weight tokens unevenly. The
    fixed full-batch denominator makes accumulation exact — same params
    after one sgd step."""
    import optax

    cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    toks = jax.random.randint(jax.random.PRNGKey(3), (16, 32), 0, 128)
    # deliberately unbalanced mask: 12 scored slots in the first half of
    # the batch, 4 in the second
    mask = jnp.zeros((16, 32)).at[:8, ::3].set(1.0).at[8:, ::8].set(1.0)
    outs = {}
    for accum in (1, 2):
        tr = LMTrainer(MaskedLM(cfg), make_mesh(MeshConfig(dp=8)),
                       LMTrainerConfig(global_batch_size=16, seq_len=32,
                                       masked_lm=True, accum_steps=accum),
                       tx=optax.sgd(0.1))
        state = tr.init_state(jax.random.PRNGKey(0))
        state, m = tr.train_step(
            state, jax.device_put(toks, tr.batch_sharding),
            jax.device_put(toks, tr.batch_sharding),
            jax.device_put(mask, tr.batch_sharding))
        outs[accum] = (float(m["loss"]), state.params)
    assert abs(outs[2][0] - outs[1][0]) < 1e-5
    for a, b in zip(jax.tree.leaves(outs[2][1]),
                    jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_grad_accumulation_batch_validation():
    import pytest

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=64)
    with pytest.raises(ValueError, match="accum_steps"):
        LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                  LMTrainerConfig(global_batch_size=12, seq_len=32,
                                  accum_steps=2))   # 12 % (2*8) != 0


def test_fused_xent_matches_unfused_step():
    """fused_lm_loss must be numerically identical to the logits path —
    same loss and same params after one step (chunked scan + checkpoint
    changes memory behavior, never values)."""
    import numpy as np
    import optax
    from flax.core import meta

    from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=256, max_len=32)
    toks = jax.random.randint(jax.random.PRNGKey(5), (8, 17), 0, 256)
    toks, tgts = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh(MeshConfig(dp=8))
    outs = {}
    for fused in (False, True):
        t = LMTrainer(CausalLM(cfg), mesh,
                      LMTrainerConfig(global_batch_size=8, seq_len=16,
                                      fused_xent=fused),
                      tx=optax.sgd(0.1))
        s = t.init_state(jax.random.PRNGKey(0))
        s, m = t.train_step(s, toks, tgts)
        outs[fused] = (float(m["loss"]), s.params)
    assert abs(outs[True][0] - outs[False][0]) < 1e-5
    for a, b in zip(jax.tree.leaves(outs[True][1]),
                    jax.tree.leaves(outs[False][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
