"""Model-family tests: GPT-2 / BERT / ViT forward + gradient sanity.

The reference ships its models as opaque container images (SURVEY.md §2.2);
we own them, so they get direct unit coverage on tiny configs.
"""
import jax
import jax.numpy as jnp
import pytest
from flax.core import meta

from mpi_operator_tpu.models.transformer import (
    CausalLM, MaskedLM, ViT, bert_config, create_lm, create_vit,
    dense_attention, gpt2_config, vit_config)


def unboxed_init(model, rng, *args, **kw):
    return meta.unbox(model.init(rng, *args, **kw))


def test_gpt2_forward_shapes():
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=256, max_len=64)
    model = CausalLM(cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    vs = unboxed_init(model, jax.random.PRNGKey(0), toks)
    logits = model.apply(vs, toks)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32        # f32 head for stable loss


def test_gpt2_causality():
    """Changing a future token must not change past logits."""
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32)
    model = CausalLM(cfg)
    rng = jax.random.PRNGKey(0)
    t1 = jax.random.randint(rng, (1, 16), 0, 64)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 64)
    vs = unboxed_init(model, rng, t1)
    l1 = model.apply(vs, t1)
    l2 = model.apply(vs, t2)
    assert jnp.allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not jnp.allclose(l1[0, -1], l2[0, -1], atol=1e-5)


def test_bert_bidirectional():
    """BERT is NOT causal: early logits must see late tokens."""
    cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32)
    model = MaskedLM(cfg)
    rng = jax.random.PRNGKey(0)
    t1 = jax.random.randint(rng, (1, 16), 0, 64)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 64)
    vs = unboxed_init(model, rng, t1)
    l1 = model.apply(vs, t1)
    l2 = model.apply(vs, t2)
    assert not jnp.allclose(l1[0, 0], l2[0, 0], atol=1e-6)


def test_bert_attention_mask():
    cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32)
    model = MaskedLM(cfg)
    toks = jnp.zeros((2, 8), jnp.int32)
    vs = unboxed_init(model, jax.random.PRNGKey(0), toks)
    mask = jnp.ones((2, 8), bool).at[:, 4:].set(False)
    out = model.apply(vs, toks, attention_mask=mask)
    assert out.shape == (2, 8, 64)
    assert bool(jnp.isfinite(out).all())


def test_vit_forward():
    cfg = vit_config("test", attention="dense", dtype=jnp.float32)
    model = ViT(cfg, num_classes=10, patch_size=4)
    imgs = jnp.zeros((2, 32, 32, 3))
    vs = unboxed_init(model, jax.random.PRNGKey(0), imgs)
    logits = model.apply(vs, imgs)
    assert logits.shape == (2, 10)


def test_moe_transformer_forward_and_aux():
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32, num_experts=4, moe_every=2)
    model = CausalLM(cfg)
    toks = jnp.zeros((2, 8), jnp.int32)
    vs = unboxed_init(model, jax.random.PRNGKey(0), toks)
    logits, interm = model.apply(vs, toks, mutable=["intermediates"])
    aux = jax.tree.leaves(interm["intermediates"])
    assert logits.shape == (2, 8, 64)
    assert len(aux) == 1          # one MoE block in a 2-layer moe_every=2 net


def test_factories():
    assert isinstance(create_lm("gpt2-test"), CausalLM)
    assert isinstance(create_lm("bert-test"), MaskedLM)
    assert isinstance(create_vit("vit-test"), ViT)
    with pytest.raises(ValueError):
        create_lm("nope-test")


def test_baseline_ladder_configs():
    """The BASELINE.json shapes: GPT-2 medium, BERT large, ViT-B/16."""
    g = gpt2_config("medium")
    assert (g.num_layers, g.num_heads, g.embed_dim) == (24, 16, 1024)
    b = bert_config("large")
    assert (b.num_layers, b.embed_dim) == (24, 1024)
    assert b.use_token_types and not b.causal
    v = vit_config("b16")
    assert (v.num_layers, v.embed_dim, v.mlp_dim) == (12, 768, 3072)


def test_gradients_flow():
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=32)
    model = CausalLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 64)
    vs = unboxed_init(model, jax.random.PRNGKey(0), toks)

    def loss(p):
        return model.apply(p, toks).sum()

    grads = jax.grad(loss)(vs)
    norms = [float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads)]
    assert all(jnp.isfinite(jnp.asarray(norms)))
    assert sum(n > 0 for n in norms) > len(norms) // 2


def test_padding_mask_keeps_flash_path(monkeypatch):
    """A padding mask must never be silently dropped: the flash kernel now
    takes the mask first-class (ops/attention.py kv_mask), so a masked BERT
    batch keeps the flash path; only ring (no mask support) falls back."""
    from mpi_operator_tpu.models import transformer as tr

    cfg = tr.bert_config("test", attention="flash", dtype=jnp.float32,
                         vocab_size=64, max_len=32)
    model = tr.MaskedLM(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    vs = unboxed_init(model, jax.random.PRNGKey(0), toks)

    seen = {}
    def spy(q, k, v, causal=True, mask=None, **kw):
        seen["mask"] = mask
        return tr.dense_attention(q, k, v, mask=mask, causal=causal,
                                  dtype=jnp.float32)
    import mpi_operator_tpu.ops.attention as opsattn
    monkeypatch.setattr(opsattn, "flash_attention", spy)

    mask = jnp.ones((1, 8), bool).at[:, 4:].set(False)
    out = model.apply(vs, toks, attention_mask=mask)
    assert out.shape == (1, 8, 64)
    assert seen["mask"] is not None          # mask reached the kernel
    # ring has no mask support: masked ring falls back to dense (no error
    # even outside shard_map, because ring_attention_inner never runs)
    ring_cfg = tr.bert_config("test", attention="ring", dtype=jnp.float32,
                              vocab_size=64, max_len=32)
    ring_model = tr.MaskedLM(ring_cfg)
    out2 = ring_model.apply(vs, toks, attention_mask=mask)
    assert out2.shape == (1, 8, 64)


# ---------------------------------------------------------------------------
# Llama-style family: RoPE + RMSNorm + SwiGLU + GQA
# ---------------------------------------------------------------------------

def test_rope_relative_position_invariance():
    """RoPE's defining property: q·k scores depend only on the RELATIVE
    offset — shifting all positions by a constant leaves them unchanged."""
    import numpy as np

    from mpi_operator_tpu.models.transformer import rope

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 2, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 2, 16))
    pos = jnp.arange(6)

    def scores(shift):
        qr = rope(q, pos + shift)
        kr = rope(k, pos + shift)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(17)), atol=1e-4)


def test_llama_trains_sharded():
    """llama-test (RoPE, RMSNorm, SwiGLU, kv_heads=2 of 4) trains on a
    dp×fsdp×tp mesh; GQA kv projections carry kv_heads, not num_heads."""
    import optax

    from mpi_operator_tpu.models.transformer import llama_config
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig

    cfg = llama_config("test", dtype=jnp.float32, vocab_size=128,
                       max_len=64)
    trn = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=2, fsdp=2,
                                                           tp=2)),
                    LMTrainerConfig(global_batch_size=8, seq_len=32),
                    tx=optax.sgd(0.1))
    state = trn.init_state(jax.random.PRNGKey(0))
    kk = state.params["backbone"]["block_0"]["attn"]["key"]["kernel"]
    assert kk.shape == (128, 2, 32)           # [E, kv_heads, head_dim]
    gate = state.params["backbone"]["block_0"]["mlp"]["fc_gate"]["kernel"]
    assert gate.shape == (128, 256)           # swiglu gate exists
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
    tgts = jnp.roll(toks, -1, 1)
    losses = []
    for _ in range(4):
        state, m = trn.train_step(
            state, jax.device_put(toks, trn.batch_sharding),
            jax.device_put(tgts, trn.batch_sharding))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_llama_gqa_decode_matches_teacher_forced():
    """The GQA+RoPE KV-cache decode path must equal full-context argmax —
    pins the cursor-offset rotations, the kv_heads cache layout, and the
    group broadcast in one equality."""
    import numpy as np
    from flax.core import meta

    from mpi_operator_tpu.models import generate
    from mpi_operator_tpu.models.transformer import llama_config

    cfg = llama_config("test", dtype=jnp.float32, vocab_size=64,
                       max_len=32)
    model = CausalLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 64)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), prompt))["params"]
    out = generate(model, params, prompt, max_new_tokens=6)
    full = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, full)
        full = jnp.concatenate(
            [full, jnp.argmax(logits[:, -1], -1)[:, None]], 1)
    assert np.array_equal(np.array(out.tokens), np.array(full))


def test_modern_lm_config_validation():
    from mpi_operator_tpu.models.transformer import llama_config

    bad = llama_config("test", dtype=jnp.float32, vocab_size=64,
                       max_len=32, activation="nope")
    with pytest.raises(ValueError, match="activation"):
        CausalLM(bad).init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 8), jnp.int32))
    bad_norm = llama_config("test", dtype=jnp.float32, vocab_size=64,
                            max_len=32, norm="nope")
    with pytest.raises(ValueError, match="norm"):
        CausalLM(bad_norm).init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="num_kv_heads"):
        llama_config("test", num_kv_heads=3).kv_heads
